"""``paddle.Model`` — the Keras-like high-level trainer.

Parity: ``/root/reference/python/paddle/hapi/model.py`` (``Model``:878,
``prepare``:1450, ``fit``/``evaluate``/``predict``:304-area, save/load).
Runs the dygraph engine (the 2.x default path); static acceleration comes
from the whole-step jit in the underlying tracer.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Union

import numpy as np

from ..dygraph.tensor import Tensor
from ..io import DataLoader, Dataset
from ..metric import Metric
from .callbacks import Callback, CallbackList, ModelCheckpoint, ProgBarLogger
from .progressbar import ProgressBar


from ..static.input import InputSpec  # noqa: F401  (single definition)


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self.stop_training = False

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            metrics = []
        elif isinstance(metrics, Metric):
            metrics = [metrics]
        self._metrics = list(metrics)

    # ------------------------------------------------------------------
    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            raise RuntimeError("call prepare(loss=...) before training")
        if not isinstance(outputs, (list, tuple)):
            outputs = [outputs]
        if not isinstance(labels, (list, tuple)):
            labels = [labels]
        return self._loss(*outputs, *labels)

    @staticmethod
    def _update_metric(m, outputs, labels):
        label = labels[0] if isinstance(labels, (list, tuple)) else labels
        res = m.compute(outputs, label)
        if not isinstance(res, tuple):
            res = (res,)
        m.update(*res)

    def train_batch(self, inputs, labels=None):
        self.network.train()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        outputs = self.network(*inputs)
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        self._optimizer.step()
        self._optimizer.clear_grad()
        for m in self._metrics:
            self._update_metric(m, outputs, labels)
        return loss

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..dygraph.base import no_grad

        with no_grad():
            outputs = self.network(*inputs)
            loss = self._compute_loss(outputs, labels)
        for m in self._metrics:
            self._update_metric(m, outputs, labels)
        return loss

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        from ..dygraph.base import no_grad

        with no_grad():
            return self.network(*inputs)

    # ------------------------------------------------------------------
    @staticmethod
    def _as_loader(data, batch_size, shuffle, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        if isinstance(data, Dataset):
            return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                              num_workers=num_workers)
        return data  # any iterable of batches

    @staticmethod
    def _split_batch(batch):
        if isinstance(batch, (list, tuple)):
            if len(batch) >= 2:
                return list(batch[:-1]), batch[-1]
            return [batch[0]], None
        return [batch], None

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = self._as_loader(train_data, batch_size, shuffle, num_workers)
        eval_loader = self._as_loader(eval_data, batch_size, False, num_workers)

        cbks = [ProgBarLogger(log_freq, verbose=verbose)]
        if save_dir:
            cbks.append(ModelCheckpoint(save_freq, save_dir))
        if callbacks:
            cbks.extend(callbacks)
        cbk = CallbackList(cbks)
        cbk.set_model(self)
        steps = None
        try:
            steps = len(loader)
        except TypeError:
            pass
        cbk.set_params({"epochs": epochs, "steps": steps, "verbose": verbose})

        cbk.on_train_begin()
        it = 0
        logs = {}
        for epoch in range(epochs):
            cbk.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(loader):
                cbk.on_train_batch_begin(step)
                ins, label = self._split_batch(batch)
                loss = self.train_batch(ins, label)
                logs = {"loss": float(loss.numpy())}
                for m in self._metrics:
                    name = m.name()
                    acc = m.accumulate()
                    logs[name if isinstance(name, str) else name[0]] = (
                        acc if not isinstance(acc, (list, tuple)) else acc[0]
                    )
                cbk.on_train_batch_end(step, logs)
                it += 1
                if num_iters is not None and it >= num_iters:
                    break
            cbk.on_epoch_end(epoch, logs or None)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size, verbose=verbose,
                              num_workers=num_workers, _cbk=cbk)
            if any(getattr(c, "stop_training", False) for c in cbks):
                break
            if num_iters is not None and it >= num_iters:
                break
        cbk.on_train_end()

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None, _cbk=None):
        loader = self._as_loader(eval_data, batch_size, False, num_workers)
        if _cbk is None and callbacks:
            _cbk = CallbackList(list(callbacks))
            _cbk.set_model(self)
        if _cbk is not None:
            _cbk.on_eval_begin()
        for m in self._metrics:
            m.reset()
        total_loss, n = 0.0, 0
        for step, batch in enumerate(loader):
            if _cbk is not None:
                _cbk.on_eval_batch_begin(step)
            ins, label = self._split_batch(batch)
            loss = self.eval_batch(ins, label)
            total_loss += float(loss.numpy())
            n += 1
            if _cbk is not None:
                _cbk.on_eval_batch_end(step, {"loss": float(loss.numpy())})
        logs = {"loss": total_loss / max(n, 1)}
        for m in self._metrics:
            name = m.name()
            logs[name if isinstance(name, str) else name[0]] = m.accumulate()
        if _cbk is not None:
            _cbk.on_eval_end(logs)
        if verbose:
            print("Eval - " + " - ".join(f"{k}: {v}" for k, v in logs.items()))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch)
            out = self.predict_batch(ins)
            outputs.append(out.numpy() if hasattr(out, "numpy") else out)
        if stack_outputs and outputs and isinstance(outputs[0], np.ndarray):
            return [np.concatenate(outputs)]
        return [outputs]

    # ------------------------------------------------------------------
    def save(self, path, training=True):
        from .. import io_api

        io_api.save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            io_api.save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from .. import io_api

        state = io_api.load(path + ".pdparams")
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and os.path.exists(opt_path):
            self._optimizer.set_state_dict(io_api.load(opt_path))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        total = 0
        lines = ["-" * 60]
        for name, p in self.network.named_parameters():
            n = int(np.prod(p.shape))
            total += n
            lines.append(f"{name:<40} {str(tuple(p.shape)):<15} {n}")
        lines.append("-" * 60)
        lines.append(f"Total params: {total}")
        out = "\n".join(lines)
        print(out)
        return {"total_params": total}
