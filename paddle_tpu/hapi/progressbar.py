"""Progress bar for hapi fit loops (parity: hapi/progressbar.py)."""

from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self.file = file
        self._start = time.time()

    def update(self, current_num, values=None):
        if self._verbose == 0:
            return
        values = values or []
        msg = f"step {current_num}"
        if self._num:
            msg += f"/{self._num}"
        for k, v in values:
            if isinstance(v, (list, tuple)):
                v = v[0]
            msg += f" - {k}: {v:.4f}" if isinstance(v, float) else f" - {k}: {v}"
        end = "\n" if (self._num and current_num >= self._num) else "\r"
        print(msg, end=end, file=self.file, flush=True)
