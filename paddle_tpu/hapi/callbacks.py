"""hapi callbacks.

Parity: ``/root/reference/python/paddle/hapi/callbacks.py`` (Callback,
ProgBarLogger, ModelCheckpoint, EarlyStopping, LRScheduler, VisualDL stub).
"""

from __future__ import annotations

import os
from typing import List, Optional


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): ...
    def on_train_end(self, logs=None): ...
    def on_eval_begin(self, logs=None): ...
    def on_eval_end(self, logs=None): ...
    def on_predict_begin(self, logs=None): ...
    def on_predict_end(self, logs=None): ...
    def on_epoch_begin(self, epoch, logs=None): ...
    def on_epoch_end(self, epoch, logs=None): ...
    def on_train_batch_begin(self, step, logs=None): ...
    def on_train_batch_end(self, step, logs=None): ...
    def on_eval_batch_begin(self, step, logs=None): ...
    def on_eval_batch_end(self, step, logs=None): ...
    def on_predict_batch_begin(self, step, logs=None): ...
    def on_predict_batch_end(self, step, logs=None): ...


class CallbackList:
    def __init__(self, callbacks: List[Callback]):
        self.callbacks = callbacks

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in logs.items()
            )
            print(f"step {step}: {items}")

    def on_eval_end(self, logs=None):
        logs = logs or {}
        if self.verbose:
            items = " - ".join(
                f"{k}: {v}" for k, v in logs.items()
            )
            print(f"Eval: {items}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and self.model and (epoch + 1) % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir and self.model:
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.wait = 0
        self.best = None
        self.stopped_epoch = 0
        self.stop_training = False

    def _better(self, cur, best):
        if best is None:
            return True
        if self.mode == "min":
            return cur < best - self.min_delta
        return cur > best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, tuple)):
            cur = cur[0]
        if self._better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        from ..optimizer.lr import LRScheduler as Sched

        return lr if isinstance(lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if s and self.by_step:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if s and self.by_epoch:
            s.step()


class ReduceLROnPlateau(Callback):
    """Parity: hapi/callbacks.py:956 — self-contained plateau tracker that
    fires on EVAL end only (never on train logs) and reduces the
    optimizer's float learning rate in place."""

    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        if factor >= 1.0:
            raise ValueError(
                "ReduceLROnPlateau does not support a factor >= 1.0")
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        self.verbose = verbose
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self._reset()

    def _reset(self):
        import numpy as np

        if self.mode == "max" or (self.mode == "auto"
                                  and "acc" in self.monitor):
            self._better = lambda a, b: a > b + self.min_delta
            self.best = -np.inf
        else:
            self._better = lambda a, b: a < b - self.min_delta
            self.best = np.inf
        self.wait = 0
        self.cooldown_counter = 0

    def on_train_begin(self, logs=None):
        self._reset()

    def on_eval_end(self, logs=None):
        if not logs or self.monitor not in logs:
            return
        opt = getattr(self.model, "_optimizer", None)
        if opt is None or not isinstance(
                getattr(opt, "_learning_rate", None), float):
            return  # reference: only float LRs are managed
        val = logs[self.monitor]
        if isinstance(val, (list, tuple)):
            val = val[0]
        current = float(val)
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if self._better(current, self.best):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                old = opt.get_lr()
                new = max(old * self.factor, self.min_lr)
                if new < old:
                    opt.set_lr(new)
                    if self.verbose:
                        print(f"ReduceLROnPlateau: lr {old:.3g} -> {new:.3g}")
                self.cooldown_counter = self.cooldown
                self.wait = 0


class VisualDL(Callback):
    """Training visualization callback (reference hapi/callbacks.py
    VisualDL).  VisualDL itself is not in this build; scalars are written
    as REAL TensorBoard event files (utils/tensorboard.py hand-encodes
    the wire format), so ``tensorboard --logdir <log_dir>`` — or VisualDL
    pointed at the same dir — renders the curves."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._train_writer = None
        self._eval_writer = None
        self._global_step = 0

    def _writer(self, mode):
        from ..utils.tensorboard import SummaryWriter

        attr = f"_{mode}_writer"
        if getattr(self, attr) is None:
            import os

            setattr(self, attr, SummaryWriter(
                os.path.join(self.log_dir, mode)))
        return getattr(self, attr)

    def _log(self, mode, step, logs):
        w = self._writer(mode)
        import numpy as np

        for k, v in (logs or {}).items():
            if k in ("batch_size", "num_samples"):
                continue
            try:
                arr = np.asarray(
                    v.numpy() if hasattr(v, "numpy") else v, dtype="float64")
            except (TypeError, ValueError):
                continue
            if arr.size == 1:
                w.add_scalar(f"{mode}/{k}", float(arr.reshape(())), step)
            else:
                for i, x in enumerate(arr.reshape(-1)):
                    w.add_scalar(f"{mode}/{k}_{i}", float(x), step)
        w.flush()

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        self._log("train", self._global_step, logs)

    def on_eval_end(self, logs=None):
        self._log("eval", self._global_step, logs)

    def on_train_end(self, logs=None):
        for w in (self._train_writer, self._eval_writer):
            if w is not None:
                w.close()
        # a later fit/evaluate with this callback must get fresh writers
        self._train_writer = None
        self._eval_writer = None
