"""hapi — high-level API. Parity: ``/root/reference/python/paddle/hapi/``."""

from .model import Model, InputSpec  # noqa: F401
from . import callbacks  # noqa: F401
from .progressbar import ProgressBar  # noqa: F401
from .dynamic_flops import flops  # noqa: F401
