"""``paddle.hub`` — model hub surface.

Parity: ``/root/reference/python/paddle/hapi/hub.py`` (``paddle.hub.list/
help/load`` resolve a github/local ``hubconf.py`` and call its
entrypoints).  The local-source path works fully here; github sources
require network egress, which this build does not have — those raise with
guidance (the established dataset convention).
"""

from __future__ import annotations

import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

_HUBCONF = "hubconf.py"


def _load_local(repo_dir: str):
    path = os.path.join(repo_dir, _HUBCONF)
    if not os.path.exists(path):
        raise FileNotFoundError(f"no {_HUBCONF} under {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["paddle_tpu_hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _resolve(repo_dir: str, source: str):
    if source == "local":
        return _load_local(repo_dir)
    raise RuntimeError(
        f"paddle.hub source={source!r} needs network egress, which this "
        "build does not have; clone the repo and use source='local'")


def list(repo_dir: str, source: str = "github", force_reload: bool = False):
    """Entrypoint names exported by the repo's hubconf.py."""
    mod = _resolve(repo_dir, source)
    return [k for k, v in vars(mod).items()
            if callable(v) and not k.startswith("_")]


def help(repo_dir: str, model: str, source: str = "github",
         force_reload: bool = False):
    mod = _resolve(repo_dir, source)
    return getattr(mod, model).__doc__


def load(repo_dir: str, model: str, *args, source: str = "github",
         force_reload: bool = False, **kwargs):
    mod = _resolve(repo_dir, source)
    fn = getattr(mod, model, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"no callable entrypoint {model!r} in {_HUBCONF}")
    return fn(*args, **kwargs)
