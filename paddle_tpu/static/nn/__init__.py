"""``paddle.static.nn`` — static-graph layer builders + control flow.

Parity: ``/root/reference/python/paddle/static/nn/__init__.py`` (fc, control
flow re-exports from fluid.layers).
"""

from __future__ import annotations

from ..control_flow import cond, while_loop  # noqa: F401

__all__ = ["while_loop", "cond", "fc"]


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """``paddle.static.nn.fc`` (fluid.layers.fc role): y = act(x W + b)."""
    from ... import nn as _nn
    from ...nn import functional as F
    import numpy as np

    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    layer = _nn.Linear(in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr)
    out = layer(x)
    if activation:
        out = getattr(F, activation)(out)
    return out
