"""``paddle.static.nn`` — static-graph layer builders + control flow.

Parity: ``/root/reference/python/paddle/static/nn/__init__.py:15-57`` — the
full builder surface (fc/conv/norm/embedding/... re-exported there from
``fluid.layers``) plus the ``sequence_*`` family from
``fluid/layers/sequence_lod.py``.

Builder semantics: each call appends ops to the current main program and
creates parameters in the startup program, like the reference's
``LayerHelper``.  Parameters are reused BY NAME within a program — calling
a builder twice with the same ``name`` shares weights (the reference's
``param_attr`` name reuse; round-3 verdict weak #4) — implemented by
caching the constructed layer object on the current main Program.

Sequence ops follow the padded+mask LoD design (``ops/sequence_ops.py``):
dense ``[B, T, ...]`` batches with an explicit per-row ``length`` tensor
instead of ragged LoD — static shapes for XLA; validity via masks.
"""

from __future__ import annotations

import numpy as np

from ...framework import program as fw
from ..control_flow import cond, while_loop  # noqa: F401

__all__ = [
    "fc", "batch_norm", "embedding", "sparse_embedding",
    "bilinear_tensor_product", "case", "cond", "conv2d", "conv2d_transpose",
    "conv3d", "conv3d_transpose", "crf_decoding", "data_norm",
    "deform_conv2d", "group_norm", "instance_norm", "layer_norm",
    "multi_box_head", "nce", "prelu", "py_func", "row_conv",
    "spectral_norm", "switch_case", "while_loop", "create_parameter",
    "sequence_conv", "sequence_softmax", "sequence_pool",
    "sequence_concat", "sequence_first_step", "sequence_last_step",
    "sequence_slice", "sequence_expand", "sequence_expand_as",
    "sequence_pad", "sequence_unpad", "sequence_reshape",
    "sequence_scatter", "sequence_enumerate", "sequence_reverse",
]


# ---------------------------------------------------------------------------
# name-based layer reuse (the reference's LayerHelper/param_attr semantics)
# ---------------------------------------------------------------------------


def _reuse(kind: str, name, make):
    """Build (or fetch) a layer keyed by ``(kind, name)`` on the current
    main program, so ``name=...`` shares parameters across calls."""
    prog = fw.default_main_program()
    cache = getattr(prog, "_builder_layers", None)
    if cache is None:
        cache = prog._builder_layers = {}
    if name is None:
        return make()
    key = (kind, name)
    layer = cache.get(key)
    if layer is None:
        layer = cache[key] = make()
    return layer


def _act(out, activation):
    if activation:
        from ...nn import functional as F

        out = getattr(F, activation)(out)
    return out


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    from .. import create_parameter as _cp

    return _cp(shape, dtype=dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


# ---------------------------------------------------------------------------
# dense / conv / norm builders
# ---------------------------------------------------------------------------


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """``paddle.static.nn.fc``: y = act(x W + b), params reused by name."""
    from ... import nn as _nn

    in_dim = int(np.prod(x.shape[num_flatten_dims:]))

    layer = _reuse("fc", name, lambda: _nn.Linear(
        in_dim, size, weight_attr=weight_attr, bias_attr=bias_attr))
    if num_flatten_dims != 1 or len(x.shape) > 2:
        from ... import tensor_api as T

        lead = list(x.shape[:num_flatten_dims])
        x = T.reshape(x, lead + [in_dim])
    return _act(layer(x), activation)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCHW"):
    from ... import nn as _nn

    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _reuse("conv2d", name, lambda: _nn.Conv2D(
        int(in_ch), num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr))
    return _act(layer(input), act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCHW"):
    from ... import nn as _nn

    if filter_size is None:
        raise ValueError("conv2d_transpose requires filter_size (deriving "
                         "it from output_size is not supported)")
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _reuse("conv2d_transpose", name, lambda: _nn.Conv2DTranspose(
        int(in_ch), num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr))
    out = (layer(input, output_size=output_size) if output_size is not None
           else layer(input))
    return _act(out, act)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None, name=None,
           data_format="NCDHW"):
    from ... import nn as _nn

    in_ch = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = _reuse("conv3d", name, lambda: _nn.Conv3D(
        int(in_ch), num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr))
    return _act(layer(input), act)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None,
                     data_format="NCDHW"):
    from ... import nn as _nn

    if filter_size is None:
        raise ValueError("conv3d_transpose requires filter_size")
    in_ch = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    layer = _reuse("conv3d_transpose", name, lambda: _nn.Conv3DTranspose(
        int(in_ch), num_filters, filter_size, stride=stride, padding=padding,
        dilation=dilation, groups=groups, weight_attr=param_attr,
        bias_attr=bias_attr))
    return _act(layer(input), act)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True, use_global_stats=False):
    from ... import nn as _nn

    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _reuse("batch_norm", name, lambda: _nn.BatchNorm(
        int(ch), momentum=momentum, epsilon=epsilon, param_attr=param_attr,
        bias_attr=bias_attr, use_global_stats=use_global_stats))
    if is_test or use_global_stats:
        layer.eval()
    return _act(layer(input), act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ... import nn as _nn

    shape = [int(s) for s in input.shape[begin_norm_axis:]]
    layer = _reuse("layer_norm", name, lambda: _nn.LayerNorm(
        shape, epsilon=epsilon,
        weight_attr=(param_attr if scale else False),
        bias_attr=(bias_attr if shift else False)))
    return _act(layer(input), act)


def group_norm(input, groups, epsilon=1e-05, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ... import nn as _nn

    ch = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    layer = _reuse("group_norm", name, lambda: _nn.GroupNorm(
        groups, int(ch), epsilon=epsilon, weight_attr=param_attr,
        bias_attr=bias_attr))
    return _act(layer(input), act)


def instance_norm(input, epsilon=1e-05, param_attr=None, bias_attr=None,
                  name=None):
    from ... import nn as _nn

    ch = int(input.shape[1])
    dim = len(input.shape)
    cls = {3: _nn.InstanceNorm1D, 4: _nn.InstanceNorm2D,
           5: _nn.InstanceNorm3D}.get(dim)
    if cls is None:
        raise ValueError(f"instance_norm expects 3/4/5-D input, got {dim}-D")
    layer = _reuse("instance_norm", name, lambda: cls(
        ch, epsilon=epsilon, weight_attr=param_attr, bias_attr=bias_attr))
    return layer(input)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              is_test=False, enable_scale_and_shift=False):
    """data_norm_op role: normalize by accumulated batch statistics
    ``(x - sum/size) * sqrt(size / square_sum)`` with the reference's
    accumulator triple (batch_size, batch_sum, batch_square_sum).  The
    accumulators are NON-trainable persistable state; in training they
    are decayed+accumulated each step by the op itself and rebound in
    place like BatchNorm's moving stats (the reference updates them in
    its grad op; here the update rides the forward — same trajectory
    when each forward is followed by one step)."""
    from ...framework import unique_name
    from ...ops.dispatch import dispatch, dispatch_static

    ch = int(input.shape[-1] if data_layout == "NHWC" else input.shape[1])
    base = name or unique_name.generate("data_norm")
    attrs = {"epsilon": float(epsilon),
             "summary_decay_rate": float(summary_decay_rate),
             "is_test": bool(is_test)}
    if fw.in_dygraph_mode():
        from ...dygraph.tensor import Tensor

        stats = [Tensor(np.full((ch,), v, "float32"), stop_gradient=True)
                 for v in (1e4, 0.0, 1e4)]
        outs = dispatch("data_norm", {
            "X": [input], "BatchSize": [stats[0]], "BatchSum": [stats[1]],
            "BatchSquareSum": [stats[2]]}, attrs)
        return _act(outs["Y"][0], act)

    blk = fw.default_main_program().global_block()
    sb = fw.default_startup_program().global_block()
    stat_vars = []
    for suffix, init in (("batch_size", 1e4), ("batch_sum", 0.0),
                         ("batch_square_sum", 1e4)):
        v = blk.create_var(name=f"{base}.{suffix}", shape=(ch,),
                           dtype="float32", persistable=True,
                           stop_gradient=True)
        sb.create_var(name=v.name, shape=v.shape, dtype=v.dtype,
                      persistable=True)
        sb.append_op(type="fill_constant", inputs={},
                     outputs={"Out": [v.name]},
                     attrs={"shape": [ch], "value": init,
                            "dtype": "float32"})
        stat_vars.append(v)
    y = blk.create_var(name=unique_name.generate(f"{base}.out"))
    outs = dispatch_static(
        "data_norm",
        {"X": [input], "BatchSize": [stat_vars[0]],
         "BatchSum": [stat_vars[1]], "BatchSquareSum": [stat_vars[2]]},
        attrs,
        outputs={"Y": [y], "BatchSizeOut": [stat_vars[0]],
                 "BatchSumOut": [stat_vars[1]],
                 "BatchSquareSumOut": [stat_vars[2]]},
    )
    return _act(outs["Y"][0], act)


def _const_init(v):
    from ...nn.initializer import Constant

    return Constant(v)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ... import nn as _nn

    layer = _reuse("spectral_norm", name, lambda: _nn.SpectralNorm(
        [int(s) for s in weight.shape], dim=dim, power_iters=power_iters,
        eps=eps))
    return layer(weight)


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    from ... import nn as _nn

    name = getattr(param_attr, "name", None) if param_attr is not None \
        else None
    layer = _reuse("embedding", name, lambda: _nn.Embedding(
        int(size[0]), int(size[1]), padding_idx=padding_idx,
        weight_attr=param_attr))
    return layer(input)


def sparse_embedding(input, size, padding_idx=None, is_test=False,
                     entry=None, table_class="CommonSparseTable",
                     param_attr=None, dtype="float32", slot=None):
    """The PS sparse table is scoped out (BASELINE north star); on TPU a
    dense embedding sharded by GSPMD plays this role."""
    return embedding(input, size, padding_idx=padding_idx,
                     param_attr=param_attr, dtype=dtype)


def prelu(x, mode, param_attr=None, data_format="NCHW", name=None):
    from ...nn import functional as F
    from .. import create_parameter as _cp
    from ...framework import unique_name

    if mode == "all":
        shape = [1]
    elif mode == "channel":
        ch = x.shape[1] if data_format == "NCHW" else x.shape[-1]
        shape = [int(ch)]
    elif mode == "element":
        shape = [int(s) for s in x.shape[1:]]
    else:
        raise ValueError(f"prelu mode must be all/channel/element, got {mode}")
    pname = (getattr(param_attr, "name", None)
             or (name and f"{name}.w") or unique_name.generate("prelu_alpha"))
    alpha = _cp(shape, dtype=str(x.dtype), name=pname,
                default_initializer=_const_init(0.25))
    if mode == "channel":
        from ... import tensor_api as T

        nd = len(x.shape)
        bshape = ([1, shape[0]] + [1] * (nd - 2) if data_format == "NCHW"
                  else [1] * (nd - 1) + [shape[0]])
        alpha = T.reshape(alpha, bshape)
    return F.prelu(x, alpha, data_format=data_format)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from ... import nn as _nn

    layer = _reuse("bilinear", name, lambda: _nn.Bilinear(
        int(x.shape[-1]), int(y.shape[-1]), size, weight_attr=param_attr,
        bias_attr=bias_attr))
    return _act(layer(x, y), act)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """row_conv_op: lookahead convolution
    out[b, t] = sum_{k=0..K} w[k] * x[b, t+k] (zero beyond T)."""
    from ... import tensor_api as T
    from .. import create_parameter as _cp
    from ...framework import unique_name

    d = int(input.shape[-1])
    k = int(future_context_size) + 1
    pname = (getattr(param_attr, "name", None)
             or unique_name.generate("row_conv_w"))
    w = _cp([k, d], dtype=str(input.dtype), name=pname)
    outs = []
    t_dim = int(input.shape[1])
    for j in range(k):
        if j:
            tail = T.slice(input, axes=[1], starts=[j], ends=[t_dim])
            shifted = T.concat(
                [tail, T.zeros([int(input.shape[0]), j, d],
                               dtype=str(input.dtype))], axis=1)
        else:
            shifted = input
        wj = T.reshape(T.slice(w, axes=[0], starts=[j], ends=[j + 1]), [d])
        outs.append(shifted * wj)
    out = outs[0]
    for o in outs[1:]:
        out = out + o
    return _act(out, act)


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=5, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    """nce_op role: noise-contrastive estimation loss.  Negatives are drawn
    uniformly with the explicit-PRNG path; loss = -log sig(s_pos)
    - sum log sig(-s_neg) (the reference's logistic NCE objective)."""
    from ... import tensor_api as T
    from ...nn import functional as F
    from .. import create_parameter as _cp
    from ...framework import unique_name

    d = int(input.shape[-1])
    base = name or unique_name.generate("nce")
    w = _cp([num_total_classes, d], dtype=str(input.dtype),
            name=(getattr(param_attr, "name", None) or f"{base}.w"))
    b = _cp([num_total_classes], dtype=str(input.dtype),
            name=(getattr(bias_attr, "name", None) or f"{base}.b"),
            is_bias=True)
    bsz = int(input.shape[0])
    neg = T.randint(0, num_total_classes, [bsz, num_neg_samples],
                    dtype="int64")
    lab = T.reshape(label, [bsz, 1]).astype("int64")
    pos_w = T.gather(w, T.reshape(lab, [-1]))          # [B, D]
    pos_b = T.gather(b, T.reshape(lab, [-1]))          # [B]
    s_pos = T.sum(input * pos_w, axis=-1) + pos_b      # [B]
    neg_w = T.gather(w, T.reshape(neg, [-1]))          # [B*N, D]
    neg_w = T.reshape(neg_w, [bsz, num_neg_samples, d])
    neg_b = T.reshape(T.gather(b, T.reshape(neg, [-1])),
                      [bsz, num_neg_samples])
    s_neg = T.sum(T.unsqueeze(input, 1) * neg_w, axis=-1) + neg_b
    loss = -F.log_sigmoid(s_pos) - T.sum(F.log_sigmoid(-s_neg), axis=-1)
    return T.reshape(loss, [bsz, 1])


def crf_decoding(input, param_attr, label=None, length=None):
    """crf_decoding_op: Viterbi decode over linear-chain CRF emissions.
    ``input`` [B, T, N] emissions, transition param [N+2, N] (row 0 start,
    row 1 stop, rows 2.. transition) — the reference's layout."""
    from ...dygraph import tracer

    name = getattr(param_attr, "name", None)
    from .. import create_parameter as _cp

    n = int(input.shape[-1])
    trans = _cp([n + 2, n], dtype=str(input.dtype),
                name=name or "crfw")

    def decode(emis, tr, ln=None):
        import jax
        import jax.numpy as jnp

        start, stop, trn = tr[0], tr[1], tr[2:]
        b, t, nn_ = emis.shape

        def one(row_e, row_len):
            alpha0 = start + row_e[0]

            def step(alpha, e):
                sc = alpha[:, None] + trn + e[None, :]
                new = jnp.max(sc, axis=0)
                return new, (new, jnp.argmax(sc, axis=0))

            _, (alphas, backs) = jax.lax.scan(step, alpha0, row_e[1:])
            # choose final position honoring length
            T_ = t
            idx = (row_len if row_len is not None else T_) - 1
            all_alpha = jnp.concatenate([alpha0[None], alphas], axis=0)
            final = all_alpha[idx] + stop
            last = jnp.argmax(final)

            def bstep(tag, inp):
                tt, bk_t = inp
                # only walk once inside the valid region: positions with
                # tt + 1 > idx haven't started backtracking yet
                prev = jnp.where(tt + 1 <= idx, bk_t[tag], tag)
                return prev, prev

            # walk backpointers from position idx down (static shapes:
            # scan the full T, gated by position)
            _, tags_body = jax.lax.scan(
                bstep, last, (jnp.arange(backs.shape[0]), backs),
                reverse=True)
            tags = jnp.concatenate([tags_body, last[None]])
            pos = jnp.arange(t)
            valid = pos < (row_len if row_len is not None else t)
            return jnp.where(valid, tags, 0)

        if ln is None:
            return jax.vmap(lambda e: one(e, None))(emis)
        return jax.vmap(one)(emis, ln.astype(jnp.int32).reshape(-1))

    has_label = label is not None
    has_length = length is not None

    def run(emis, tr, *rest):
        import jax.numpy as jnp

        ridx = 0
        lbl = None
        ln = None
        if has_label:
            lbl = rest[ridx]
            ridx += 1
        if has_length:
            ln = rest[ridx]
        path = decode(emis, tr, ln)
        if lbl is None:
            return path
        # reference semantics (crf_decoding_op.h): with Label, emit the
        # 0/1 correctness mask (1 = predicted tag equals the label)
        ok = (path == lbl.reshape(path.shape).astype(path.dtype))
        if ln is not None:
            pos = jnp.arange(path.shape[1])[None, :]
            ok = ok & (pos < ln.astype(jnp.int32).reshape(-1)[:, None])
        return ok.astype(jnp.int64)

    args = ([input, trans] + ([label] if has_label else [])
            + ([length] if has_length else []))
    return tracer.trace_fn(run, args, name="crf_decoding")


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head: per-feature-map prior boxes + loc/conf convs
    (multi_box_head role, built on vision.ops.prior_box)."""
    from ... import tensor_api as T
    from ...vision import ops as vops

    if min_sizes is None:
        # reference formula: evenly spaced ratios over feature maps
        num_layer = len(inputs)
        min_sizes, max_sizes = [], []
        step = int(np.floor((max_ratio - min_ratio) / (num_layer - 2)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes, vars_ = [], [], [], []
    for i, x in enumerate(inputs):
        ms = min_sizes[i]
        mx = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i]
        box, var = vops.prior_box(
            x, image, min_sizes=[ms] if np.isscalar(ms) else ms,
            max_sizes=([mx] if mx is not None and np.isscalar(mx) else mx),
            aspect_ratios=[ar] if np.isscalar(ar) else ar, flip=flip,
            clip=clip, steps=[steps[i], steps[i]] if steps else [0.0, 0.0],
            offset=offset, variance=list(variance))
        nbox = int(np.prod(box.shape[:-1]))
        num_px = nbox // (int(x.shape[2]) * int(x.shape[3]))
        loc = conv2d(x, num_px * 4, kernel_size, padding=pad, stride=stride,
                     name=(name and f"{name}.loc{i}"))
        conf = conv2d(x, num_px * num_classes, kernel_size, padding=pad,
                      stride=stride, name=(name and f"{name}.conf{i}"))
        # NCHW -> [B, prior, 4/classes]
        loc = T.reshape(T.transpose(loc, [0, 2, 3, 1]), [0, nbox, 4])
        conf = T.reshape(T.transpose(conf, [0, 2, 3, 1]),
                         [0, nbox, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes.append(T.reshape(box, [-1, 4]))
        vars_.append(T.reshape(var, [-1, 4]))
    mbox_locs = T.concat(locs, axis=1)
    mbox_confs = T.concat(confs, axis=1)
    all_boxes = T.concat(boxes, axis=0)
    all_vars = T.concat(vars_, axis=0)
    return mbox_locs, mbox_confs, all_boxes, all_vars


def deform_conv2d(x, offset, mask, num_filters, filter_size, stride=1,
                  padding=0, dilation=1, groups=1, deformable_groups=1,
                  im2col_step=1, param_attr=None, bias_attr=None, name=None):
    """Deformable conv v2 builder: create the filter/bias params, then run
    the gather-based kernel in ``vision.ops.deform_conv2d``."""
    from ...vision import ops as vops
    from .. import create_parameter as _cp
    from ...framework import unique_name

    kh, kw = ((int(filter_size),) * 2 if np.isscalar(filter_size)
              else (int(filter_size[0]), int(filter_size[1])))
    cin = int(x.shape[1])
    base = name or unique_name.generate("deform_conv")
    w = _cp([num_filters, cin // groups, kh, kw], dtype=str(x.dtype),
            name=(getattr(param_attr, "name", None) or f"{base}.w"))
    b = _cp([num_filters], dtype=str(x.dtype),
            name=(getattr(bias_attr, "name", None) or f"{base}.b"),
            is_bias=True) if bias_attr is not False else None
    return vops.deform_conv2d(
        x, offset, w, bias=b, stride=stride, padding=padding,
        dilation=dilation, deformable_groups=deformable_groups,
        groups=groups, mask=mask)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """py_func_op role: embed a host Python callable via
    ``jax.pure_callback`` (same transport as the custom-op C ABI).  The
    results are BOUND to the caller-supplied ``out`` variables (reference
    contract) and also returned; ``backward_func(*(x, out, out_grads))``
    provides the custom VJP when given."""
    from ...dygraph import tracer

    xs = list(x) if isinstance(x, (list, tuple)) else [x]
    outs = list(out) if isinstance(out, (list, tuple)) else [out]
    out_specs = [(tuple(o.shape), str(o.dtype)) for o in outs]
    in_specs = [(tuple(v.shape), str(v.dtype)) for v in xs]

    def _callback(f, specs, *arrays):
        import jax
        from ...framework.dtype import to_jax_dtype

        structs = tuple(jax.ShapeDtypeStruct(s, to_jax_dtype(d))
                        for s, d in specs)

        def host(*host_arrays):
            res = f(*[np.asarray(a) for a in host_arrays])
            res = res if isinstance(res, (list, tuple)) else [res]
            return tuple(np.asarray(r).astype(st.dtype)
                         for r, st in zip(res, structs))

        return jax.pure_callback(host, structs, *arrays)

    def run(*arrays):
        import jax

        if backward_func is None:
            res = _callback(func, out_specs, *arrays)
            return tuple(res) if len(out_specs) > 1 else res[0]

        @jax.custom_vjp
        def op(*a):
            r = _callback(func, out_specs, *a)
            return tuple(r) if len(out_specs) > 1 else r[0]

        def fwd(*a):
            y = op(*a)
            return y, (a, y if isinstance(y, tuple) else (y,))

        def bwd(saved, gy):
            a, y = saved
            gys = gy if isinstance(gy, tuple) else (gy,)
            gx = _callback(backward_func, in_specs, *a, *y, *gys)
            return tuple(gx)

        op.defvjp(fwd, bwd)
        return op(*arrays)

    got = tracer.trace_fn(run, xs, name="py_func")
    got_list = list(got) if isinstance(got, (list, tuple)) else [got]

    # bind results onto the caller's out vars (reference py_func contract)
    if fw.in_dygraph_mode():
        for o, g in zip(outs, got_list):
            o._array = g._array
    else:
        blk = fw.default_main_program().current_block()
        for o, g in zip(outs, got_list):
            blk.append_op(type="assign", inputs={"X": [g.name]},
                          outputs={"Out": [o.name]}, attrs={})
    return out if isinstance(out, (list, tuple)) else outs[0]


# ---------------------------------------------------------------------------
# control-flow builders
# ---------------------------------------------------------------------------


def case(pred_fn_pairs, default=None, name=None):
    """fluid.layers.case: first true predicate wins (nested cond chain)."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    (pred, fn), rest = pred_fn_pairs[0], pred_fn_pairs[1:]
    if not rest:
        return cond(pred, fn, default if default is not None else fn)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """fluid.layers.switch_case: select a branch by integer index."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    pairs = [(branch_index == idx, fn) for idx, fn in items]
    return case(pairs, default=default if default is not None
                else items[-1][1])


# ---------------------------------------------------------------------------
# sequence family (padded+mask LoD design — ops/sequence_ops.py)
# ---------------------------------------------------------------------------


def _seq(op_type, ins, attrs=None, n_out=1):
    from ...ops.dispatch import dispatch

    out = dispatch(op_type, ins, attrs or {})
    if n_out == 1:
        return out["Out"][0] if isinstance(out["Out"], list) else out["Out"]
    return tuple(
        (out[k][0] if isinstance(out[k], list) else out[k])
        for k in ("Out", "Length"))


def sequence_pad(x, pad_value=0.0, maxlen=None, length=None, name=None):
    """Returns ``(out, length)`` like the reference (sequence_pad_op)."""
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    return _seq("sequence_pad", ins,
                {"pad_value": float(pad_value), "maxlen": maxlen or 0},
                n_out=2)


def sequence_unpad(x, length, name=None):
    return _seq("sequence_unpad", {"X": [x], "Length": [length]})


def sequence_softmax(input, length=None, use_cudnn=False, name=None):
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    return _seq("sequence_softmax", ins)


def sequence_pool(input, pool_type, length=None, is_test=False,
                  pad_value=0.0):
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    return _seq("sequence_pool", ins, {"pooltype": str(pool_type).upper()})


def sequence_first_step(input, length=None):
    return sequence_pool(input, "first", length=length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "last", length=length)


def sequence_reverse(x, length=None, name=None):
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    return _seq("sequence_reverse", ins)


def sequence_slice(input, offset, length, name=None):
    return _seq("sequence_slice",
                {"X": [input], "Offset": [offset], "SliceLength": [length]},
                n_out=2)[0]


def sequence_reshape(input, new_dim, length=None):
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    return _seq("sequence_reshape", ins, {"new_dim": int(new_dim)})


def sequence_concat(input, lengths=None, name=None):
    ins = {"X": list(input)}
    if lengths is not None:
        ins["Length"] = list(lengths)
    return _seq("sequence_concat", ins, n_out=2)[0]


def sequence_expand(x, y_length, maxlen=None, ref_level=-1, name=None):
    """Dense analogue of sequence_expand: broadcast each row of ``x`` over
    the valid region ``[0, y_length[i])`` of a fresh time axis."""
    return sequence_expand_as(x, y_length, maxlen=maxlen, name=name)


def sequence_expand_as(x, y_length, maxlen=None, name=None):
    if maxlen is None:
        raise ValueError(
            "sequence_expand_as needs an explicit maxlen under static "
            "shapes (the dense time-axis size)")
    return _seq("sequence_expand_as",
                {"X": [x], "Length": [y_length]},
                {"maxlen": int(maxlen)}, n_out=2)[0]


def sequence_enumerate(input, win_size, pad_value=0, length=None, name=None):
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    return _seq("sequence_enumerate", ins,
                {"win_size": int(win_size), "pad_value": pad_value})


def sequence_scatter(input, index, updates, length=None, name=None):
    ins = {"X": [input], "Ids": [index], "Updates": [updates]}
    if length is not None:
        ins["Length"] = [length]
    return _seq("sequence_scatter", ins)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, name=None, length=None):
    from .. import create_parameter as _cp
    from ...framework import unique_name

    d = int(input.shape[-1])
    pname = (getattr(param_attr, "name", None)
             or (name and f"{name}.w") or unique_name.generate("seq_conv_w"))
    w = _cp([int(filter_size) * d, num_filters], dtype=str(input.dtype),
            name=pname)
    ins = {"X": [input], "Filter": [w]}
    if length is not None:
        ins["Length"] = [length]
    start = (padding_start if padding_start is not None
             else -((int(filter_size) - 1) // 2))
    out = _seq("sequence_conv", ins,
               {"contextLength": int(filter_size), "contextStart": int(start),
                "contextStride": int(filter_stride)})
    if bias_attr is not False:
        bname = (getattr(bias_attr, "name", None)
                 or (name and f"{name}.b")
                 or unique_name.generate("seq_conv_b"))
        b = _cp([num_filters], dtype=str(input.dtype), name=bname,
                is_bias=True)
        out = out + b
        if length is not None:
            # re-mask: the pad region must stay zero after the bias add
            # (the family invariant in ops/sequence_ops.py)
            out = sequence_unpad(out, length)
    return _act(out, act)
