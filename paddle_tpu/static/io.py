"""Static model save/load.

Parity: ``/root/reference/python/paddle/fluid/io.py`` (``save_persistables``
:668, ``save_inference_model``:1246, ``load_inference_model``:1459,
``save``:1840, ``load_program_state``:2144) and ``python/paddle/static/io.py``.

Format: program structure as JSON (Program.to_dict), parameters as an ``.npz``
of numpy arrays — a portable, XLA-independent serialization replacing the
reference's protobuf + raw LoDTensor byte streams.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from ..framework import program as fw
from ..framework.scope import global_scope


def _state_arrays(program: fw.Program, scope) -> dict:
    out = {}
    for var in program.list_vars():
        if not var.persistable:
            continue
        val = scope.find_var(var.name)
        if val is not None:
            out[var.name] = np.asarray(val)
    return out


def save(program: fw.Program, model_path: str, scope=None):
    """Parity: ``fluid.io.save`` / ``paddle.static.save``."""
    scope = scope or global_scope()
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdmodel.json", "w") as f:
        json.dump(program.to_dict(), f)
    np.savez(model_path + ".pdparams.npz", **_state_arrays(program, scope))


def load(program: fw.Program, model_path: str, executor=None, scope=None):
    """Parity: ``fluid.io.load`` — restores persistables into the scope."""
    import jax.numpy as jnp

    scope = scope or global_scope()
    data = np.load(model_path + ".pdparams.npz", allow_pickle=False)
    for name in data.files:
        scope.set(name, jnp.asarray(data[name]))


def _prune_for_inference(program, feed_names, fetch_names):
    """Backward-slice the global block to the ops the fetch targets need.

    Parity: ``fluid/framework.py`` ``Program._prune_with_input`` /
    ``_prune_backward`` used by save_inference_model — drops loss,
    backward, and optimizer ops from the saved inference program."""
    block = program.global_block()
    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        if any(o in needed for o in op.output_arg_names):
            keep.append(op)
            needed.update(n for n in op.input_arg_names
                          if n not in feed_names)
    keep.reverse()
    block.ops = keep


def save_inference_model(
    path_prefix: str,
    feed_vars: List[fw.Variable],
    fetch_vars: List[fw.Variable],
    executor=None,
    program: Optional[fw.Program] = None,
    scope=None,
):
    """Parity: ``fluid.io.save_inference_model``:1246 — saves an inference
    program (cloned for test) + persistables."""
    program = program or fw.default_main_program()
    infer_prog = program.clone(for_test=True)
    _prune_for_inference(infer_prog, [v.name for v in feed_vars],
                         [v.name for v in fetch_vars])
    meta = {
        "program": infer_prog.to_dict(),
        "feed_names": [v.name for v in feed_vars],
        "fetch_names": [v.name for v in fetch_vars],
    }
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel.json", "w") as f:
        json.dump(meta, f)
    np.savez(path_prefix + ".pdparams.npz", **_state_arrays(program, scope or global_scope()))


def load_inference_model(path_prefix: str, executor=None, scope=None):
    """Parity: ``fluid.io.load_inference_model``:1459.

    Returns (program, feed_names, fetch_names) with persistables loaded.
    """
    import jax.numpy as jnp

    scope = scope or global_scope()
    with open(path_prefix + ".pdmodel.json") as f:
        meta = json.load(f)
    program = fw.Program.from_dict(meta["program"])
    data = np.load(path_prefix + ".pdparams.npz", allow_pickle=False)
    for name in data.files:
        scope.set(name, jnp.asarray(data[name]))
    return program, meta["feed_names"], meta["fetch_names"]
