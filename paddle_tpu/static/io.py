"""Static model save/load.

Parity: ``/root/reference/python/paddle/fluid/io.py`` (``save_persistables``
:668, ``save_inference_model``:1246, ``load_inference_model``:1459,
``save``:1840, ``load_program_state``:2144) and ``python/paddle/static/io.py``.

Format: program structure as JSON (Program.to_dict), parameters as an ``.npz``
of numpy arrays — a portable, XLA-independent serialization replacing the
reference's protobuf + raw LoDTensor byte streams.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from ..framework import program as fw
from ..framework.scope import global_scope


def _state_arrays(program: fw.Program, scope) -> dict:
    out = {}
    for var in program.list_vars():
        if not var.persistable:
            continue
        val = scope.find_var(var.name)
        if val is not None:
            out[var.name] = np.asarray(val)
    return out


def save(program: fw.Program, model_path: str, scope=None):
    """Parity: ``fluid.io.save`` / ``paddle.static.save``."""
    scope = scope or global_scope()
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    with open(model_path + ".pdmodel.json", "w") as f:
        json.dump(program.to_dict(), f)
    np.savez(model_path + ".pdparams.npz", **_state_arrays(program, scope))


def load(program: fw.Program, model_path: str, executor=None, scope=None):
    """Parity: ``fluid.io.load`` — restores persistables into the scope."""
    import jax.numpy as jnp

    scope = scope or global_scope()
    data = np.load(model_path + ".pdparams.npz", allow_pickle=False)
    for name in data.files:
        scope.set(name, jnp.asarray(data[name]))


def _prune_for_inference(program, feed_names, fetch_names):
    """Backward-slice the global block to the ops the fetch targets need.

    Parity: ``fluid/framework.py`` ``Program._prune_with_input`` /
    ``_prune_backward`` used by save_inference_model — drops loss,
    backward, and optimizer ops from the saved inference program."""
    block = program.global_block()
    needed = set(fetch_names)
    keep = []
    for op in reversed(block.ops):
        if any(o in needed for o in op.output_arg_names):
            keep.append(op)
            needed.update(n for n in op.input_arg_names
                          if n not in feed_names)
    keep.reverse()
    block.ops = keep


def save_inference_model(
    path_prefix: str,
    feed_vars: List[fw.Variable],
    fetch_vars: List[fw.Variable],
    executor=None,
    program: Optional[fw.Program] = None,
    scope=None,
):
    """Parity: ``fluid.io.save_inference_model``:1246 — saves an inference
    program (cloned for test) + persistables."""
    program = program or fw.default_main_program()
    infer_prog = program.clone(for_test=True)
    _prune_for_inference(infer_prog, [v.name for v in feed_vars],
                         [v.name for v in fetch_vars])
    meta = {
        "program": infer_prog.to_dict(),
        "feed_names": [v.name for v in feed_vars],
        "fetch_names": [v.name for v in fetch_vars],
    }
    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel.json", "w") as f:
        json.dump(meta, f)
    np.savez(path_prefix + ".pdparams.npz", **_state_arrays(program, scope or global_scope()))


def load_inference_model(path_prefix: str, executor=None, scope=None):
    """Parity: ``fluid.io.load_inference_model``:1459.

    Returns (program, feed_names, fetch_names) with persistables loaded.
    """
    import jax.numpy as jnp

    scope = scope or global_scope()
    with open(path_prefix + ".pdmodel.json") as f:
        meta = json.load(f)
    program = fw.Program.from_dict(meta["program"])
    data = np.load(path_prefix + ".pdparams.npz", allow_pickle=False)
    for name in data.files:
        scope.set(name, jnp.asarray(data[name]))
    return program, meta["feed_names"], meta["fetch_names"]


# ---------------------------------------------------------------------------
# program-state / vars surface (fluid/io.py save_vars:? load_program_state:2144
# family + 2.x static/io.py serialize_* APIs)
# ---------------------------------------------------------------------------


def load_program_state(model_path: str, var_list=None) -> dict:
    """Parity: fluid.io.load_program_state — name -> numpy dict."""
    data = np.load(model_path + ".pdparams.npz", allow_pickle=False)
    names = ({v.name for v in var_list} if var_list is not None
             else set(data.files))
    return {n: data[n] for n in data.files if n in names}


def set_program_state(program: fw.Program, state_dict: dict):
    """Parity: fluid.io.set_program_state — push numpy state into scope."""
    import jax.numpy as jnp

    scope = global_scope()
    prog_vars = {v.name for v in program.list_vars() if v.persistable}
    for name, arr in state_dict.items():
        if name in prog_vars:
            scope.set(name, jnp.asarray(arr))


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """Parity: fluid.io.save_vars — save selected persistables."""
    program = main_program or fw.default_main_program()
    allv = [v for v in program.list_vars() if v.persistable]
    if vars is not None:
        chosen = list(vars)
    elif predicate is not None:
        chosen = [v for v in allv if predicate(v)]
    else:
        chosen = allv
    scope = global_scope()
    out = {}
    for v in chosen:
        val = scope.find_var(v.name)
        if val is not None:
            out[v.name] = np.asarray(val)
    os.makedirs(dirname, exist_ok=True)
    np.savez(os.path.join(dirname, filename or "vars") + ".npz", **out)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    """Parity: fluid.io.load_vars."""
    import jax.numpy as jnp

    data = np.load(os.path.join(dirname, filename or "vars") + ".npz",
                   allow_pickle=False)
    program = main_program or fw.default_main_program()
    allv = {v.name for v in program.list_vars() if v.persistable}
    if vars is not None:
        allv = {v.name for v in vars}
    elif predicate is not None:
        allv = {v.name for v in program.list_vars()
                if v.persistable and predicate(v)}
    scope = global_scope()
    for name in data.files:
        if name in allv:
            scope.set(name, jnp.asarray(data[name]))


def normalize_program(program: fw.Program, feed_vars, fetch_vars):
    """Parity: static/io.py normalize_program — prune to the inference
    slice defined by feeds/fetches (returns the same Program, pruned)."""
    feeds = [v.name if hasattr(v, "name") else v for v in feed_vars]
    fetches = [v.name if hasattr(v, "name") else v for v in fetch_vars]
    _prune_for_inference(program, feeds, fetches)
    return program


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs) -> bytes:
    """Parity: static/io.py serialize_program — program bytes."""
    program = program or fw.default_main_program()
    feeds = [v.name if hasattr(v, "name") else v for v in feed_vars]
    fetches = [v.name if hasattr(v, "name") else v for v in fetch_vars]
    d = program.to_dict()
    d["_feed_names"] = feeds
    d["_fetch_names"] = fetches
    return json.dumps(d).encode("utf-8")


def deserialize_program(data: bytes) -> fw.Program:
    d = json.loads(bytes(data).decode("utf-8"))
    return fw.Program.from_dict(d)


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None, **kwargs) -> bytes:
    """Parity: static/io.py serialize_persistables — param bytes."""
    import io as _io

    program = program or fw.default_main_program()
    buf = _io.BytesIO()
    np.savez(buf, **_state_arrays(program, global_scope()))
    return buf.getvalue()


def deserialize_persistables(program: fw.Program, data: bytes,
                             executor=None):
    import io as _io

    import jax.numpy as jnp

    arrs = np.load(_io.BytesIO(bytes(data)), allow_pickle=False)
    scope = global_scope()
    for name in arrs.files:
        scope.set(name, jnp.asarray(arrs[name]))
