"""Static-graph reverse-mode autodiff: ``append_backward`` / ``gradients``.

Parity: ``/root/reference/python/paddle/fluid/backward.py`` —
``append_backward``:1377 (grad-op expansion via ``core.get_grad_op_desc``
:1085, duplicate-grad accumulation ``_addup_repetitive_outputs_``, no-grad
pruning) — with the per-op grad descs coming from the op registry's grad
makers (auto-``jax.vjp`` by default, see ``ops/registry.py``).

The emitted grad ops are ordinary registry ops appended to the same block, so
the executor compiles forward+backward+optimizer into one XLA computation;
recomputation inside auto-vjp grad ops is CSE'd/rematerialized by XLA.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

from ..framework import program as fw
from ..framework.dtype import is_floating
from ..framework.program import GRAD_SUFFIX, grad_var_name
from ..ops import registry

__all__ = ["append_backward", "gradients"]


def _collect_no_grad(block: fw.Block, no_grad_set: Optional[Set[str]]) -> Set[str]:
    out = set(no_grad_set or ())
    for var in block.vars.values():
        if var.stop_gradient or not is_floating(var.dtype):
            out.add(var.name)
    parent = block.parent_block
    while parent is not None:
        for var in parent.vars.values():
            if var.stop_gradient or not is_floating(var.dtype):
                out.add(var.name)
        parent = parent.parent_block
    return out


def _ensure_grad_var(block: fw.Block, fwd_name: str, grad_name: str):
    if block._has_var_recursive(grad_name):
        return block._var_recursive(grad_name)
    try:
        fwd = block._var_recursive(fwd_name)
        shape, dtype = fwd.shape, fwd.dtype
    except ValueError:
        shape, dtype = (), "float32"
    return block.create_var(name=grad_name, shape=shape, dtype=dtype, stop_gradient=True)


def append_backward(
    loss: fw.Variable,
    parameter_list: Optional[Sequence] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
):
    """Append grad ops for ``loss`` to its block; returns [(param, grad)].

    Parity: ``backward.py:1377``.
    """
    block = loss.block
    program = block.program
    no_grad = _collect_no_grad(block, no_grad_set)

    loss_grad_name = grad_var_name(loss.name)
    block.append_op(
        type="fill_any_like",
        inputs={"X": [loss.name]},
        outputs={"Out": [loss_grad_name]},
        attrs={"value": 1.0, "dtype": -1},
    )
    _ensure_grad_var(block, loss.name, loss_grad_name)

    fwd_ops = [
        op
        for op in block.ops
        if not op.type.endswith("_grad") and op.type != "fill_any_like"
    ]

    produced_grads: Set[str] = {loss_grad_name}
    rename_counter = 0

    for op in reversed(fwd_ops):
        op_def_known = registry.is_registered(op.type)
        if not op_def_known:
            continue
        op_def = registry.get_op_def(op.type)
        out_grad_names = [
            grad_var_name(n)
            for slot, names in op.outputs.items()
            if slot not in op_def.nondiff_out_slots
            for n in names
        ]
        if op_def.no_grad:
            # a dynamic while_loop on the grad path fails loudly WITH the
            # trip-count inference diagnosis instead of silently zeroing
            reason = op.attrs.get("__no_fori_reason__")
            if reason is not None and any(
                    g in produced_grads for g in out_grad_names):
                raise RuntimeError(
                    f"append_backward: op {op.type!r} is a dynamic "
                    f"lax.while_loop, which cannot be reverse-differentiated "
                    f"under static memory. Trip-count inference failed "
                    f"because: {reason}. Rewrite the loop as a counted "
                    f"``i < N`` loop with fill_constant bounds, or compute "
                    f"the loss outside the loop.")
            continue
        if not any(g in produced_grads for g in out_grad_names):
            continue
        # outputs with no incoming grad get explicit zeros (parity:
        # fill_zeros_like insertion in the reference's backward pass)
        for slot, names in op.outputs.items():
            if slot in op_def.nondiff_out_slots:
                continue
            for n in names:
                g = grad_var_name(n)
                if g not in produced_grads:
                    block.append_op(
                        type="fill_zeros_like",
                        inputs={"X": [n]},
                        outputs={"Out": [g]},
                        attrs={},
                    )
                    _ensure_grad_var(block, n, g)
                    produced_grads.add(g)

        grad_op_descs = registry.make_grad_op_descs(op, no_grad)
        for gop in grad_op_descs:
            final_outputs: Dict[str, List[str]] = {}
            accumulations = []  # (existing_name, temp_name)
            for slot, names in gop["outputs"].items():
                outs = []
                for n in names:
                    if not n:
                        outs.append("")
                        continue
                    if n in produced_grads:
                        rename_counter += 1
                        tmp = f"{n}@RENAME@{rename_counter}"
                        accumulations.append((n, tmp))
                        outs.append(tmp)
                    else:
                        outs.append(n)
                final_outputs[slot] = outs
            block.append_op(
                type=gop["type"],
                inputs=gop["inputs"],
                outputs=final_outputs,
                attrs=gop["attrs"],
            )
            for slot, names in final_outputs.items():
                for n in names:
                    if n:
                        base = n.split("@RENAME@")[0]
                        _ensure_grad_var(block, base[: -len(GRAD_SUFFIX)], n)
                        produced_grads.add(base)
            # accumulate duplicate grads: new = old + tmp, rebinding the
            # original name (parity: _addup_repetitive_outputs_)
            for orig, tmp in accumulations:
                block.append_op(
                    type="sum",
                    inputs={"X": [orig, tmp]},
                    outputs={"Out": [orig]},
                    attrs={},
                )

    # collect (param, grad) pairs
    if parameter_list is not None:
        params = [
            p if isinstance(p, fw.Variable) else block._var_recursive(str(p))
            for p in parameter_list
        ]
    else:
        params = program.all_parameters()
    result = []
    for p in params:
        if not getattr(p, "trainable", True) or p.name in no_grad:
            continue
        gname = grad_var_name(p.name)
        if block._has_var_recursive(gname):
            result.append((p, block._var_recursive(gname)))
    return result


def gradients(
    targets,
    inputs,
    target_gradients=None,
    no_grad_set: Optional[Set[str]] = None,
) -> List[fw.Variable]:
    """Parity: ``backward.py:1972`` ``paddle.static.gradients``."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    assert len(targets) == 1, "gradients() currently supports a single target"
    target = targets[0]
    append_backward(target, no_grad_set=no_grad_set)
    block = target.block
    outs = []
    for v in inputs:
        gname = grad_var_name(v.name)
        outs.append(block._var_recursive(gname) if block._has_var_recursive(gname) else None)
    return outs
