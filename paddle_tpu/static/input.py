"""``paddle.static.data`` / ``InputSpec``.

Parity: ``/root/reference/python/paddle/fluid/data.py`` and
``python/paddle/static/input.py``.
"""

from __future__ import annotations

from typing import Optional, Sequence

from ..framework import program as fw
from ..framework.dtype import convert_dtype


def data(name: str, shape: Sequence[int], dtype="float32", lod_level: int = 0) -> fw.Variable:
    """Declare a feed slot in the current main program."""
    block = fw.default_main_program().global_block()
    shape = tuple(-1 if s is None else int(s) for s in shape)
    var = block.create_var(
        name=name,
        shape=shape,
        dtype=convert_dtype(dtype),
        is_data=True,
        stop_gradient=True,
    )
    return var


class InputSpec:
    """Parity: ``paddle.static.InputSpec`` (used by jit.save / hapi Model)."""

    def __init__(self, shape, dtype="float32", name: Optional[str] = None):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or getattr(tensor, "name", None))

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"
