"""Int8 inference program rewrite — ``Config.enable_int8()``.

Role parity: the reference's TensorRT int8 engine path
(``inference/tensorrt/trt_int8_calibrator.h`` + the slim post-training →
inference flow): quantize inference-graph weights to int8 and execute the
matmuls as int8 x int8 -> int32 on the MXU.

The pass walks the loaded inference Program: every ``matmul_v2`` / ``mul``
whose ``Y`` is a persistable 2-D parameter is rewritten to the
``quantized_matmul`` op (ops/quant_ops.py) with a per-output-channel int8
weight + fp32 dequant scale materialized in the scope.  When the graph
carries calibrated activation scales (PTQ/QAT export: a
``fake_quantize_dequantize_moving_average_abs_max`` op feeding the matmul),
the frozen scale is wired in as ``XScale`` and the fake-quant node is
bypassed; otherwise activations quantize dynamically per batch.
"""

from __future__ import annotations

import numpy as np

__all__ = ["rewrite_program_int8"]

_FAKE_ACT_OPS = (
    "fake_quantize_dequantize_moving_average_abs_max",
    "fake_quantize_dequantize_abs_max",
)


def rewrite_program_int8(program, scope, fetch_names=None,
                         min_weight_elements=1 << 16,
                         quantize_convs=False) -> int:
    """Rewrite in place; returns the number of matmuls/convs quantized.

    ``min_weight_elements`` gates the rewrite to layers big enough for the
    int8 MXU path to win: the measured speedup (BENCH extras int8_matmul)
    is 1.5x at 4096^3 GEMMs, but small/bandwidth-bound layers pay the
    extra activation-quantize + dequant elementwise passes without
    enough MACs to amortize them — those keep the bf16 path.

    ``quantize_convs`` is OFF by default on measurement, not principle:
    int8 conv on v5e through the XLA conv path measured 0.79-1.13x vs
    bf16 across ResNet-shape sweeps (256ch 14x14: 0.88x, 128ch 28x28:
    0.79x, 1024ch 14x14: 1.08x) — the quantize/dequant passes eat the
    MXU win at practical shapes.  Callers who want it anyway (e.g. for
    memory, or future-chip int8 conv paths) opt in explicitly."""
    block = program.global_block()
    n = 0
    # map: activation var -> (producer fake-quant op, its frozen scale var)
    fake_out = {}
    # map: weight fake-quant output -> underlying persistable weight name
    fake_weight = {}
    for op in block.ops:
        if op.type in _FAKE_ACT_OPS:
            outs = op.output("Out")
            scales = op.output("OutScale")
            ins = op.input("InScale")
            if outs:
                fake_out[outs[0]] = (op, ins[0] if ins else
                                     (scales[0] if scales else None))
        elif op.type in ("fake_channel_wise_quantize_dequantize_abs_max",
                         "fake_quantize_dequantize_abs_max"):
            outs = op.output("Out")
            src = op.input("X")
            if outs and src:
                svar = block.vars.get(src[0])
                if svar is not None and getattr(svar, "persistable", False):
                    fake_weight[outs[0]] = src[0]

    for op in block.ops:
        if op.type == "conv2d":
            if quantize_convs:
                n += _rewrite_conv(block, scope, op, fake_out, fake_weight,
                                   min_weight_elements)
            continue
        if op.type not in ("matmul_v2", "mul", "matmul"):
            continue
        if op.attrs.get("trans_x") or op.attrs.get("transpose_X"):
            continue
        ys = op.input("Y")
        xs_in = op.input("X")
        if not ys or not xs_in:
            continue
        # PTQ/QAT export: Y is a fake-quantized view of the weight — the
        # int8 path quantizes the underlying weight itself (same channel
        # abs-max scales), so see through the fake node
        yname = fake_weight.get(ys[0], ys[0])
        yvar = block.vars.get(yname)
        if yvar is None or not getattr(yvar, "persistable", False):
            continue
        w = scope.find_var(yname)
        if w is None:
            continue
        w = np.asarray(w)
        if w.ndim != 2 or w.size < min_weight_elements:
            continue
        if op.attrs.get("trans_y") or op.attrs.get("transpose_Y"):
            w = w.T
        # per-output-channel symmetric scale
        ws = np.maximum(np.abs(w).max(axis=0), 1e-8) / 127.0
        wq = np.clip(np.round(w / ws), -127, 127).astype(np.int8)
        qname, sname = f"{yname}@int8", f"{yname}@wscale"
        scope.set(qname, wq)
        scope.set(sname, ws.astype(np.float32))
        block.create_var(name=qname, shape=wq.shape, dtype="int8",
                         persistable=True, stop_gradient=True)
        block.create_var(name=sname, shape=ws.shape, dtype="float32",
                         persistable=True, stop_gradient=True)
        new_inputs = {"X": [xs_in[0]], "Y": [qname], "WScale": [sname]}
        # calibrated activation scale: X produced by a frozen fake-quant
        src = fake_out.get(xs_in[0])
        if src is not None and src[1] is not None:
            new_inputs["X"] = [src[0].input("X")[0]]  # bypass the fake node
            new_inputs["XScale"] = [src[1]]
        op.type = "quantized_matmul"
        op.inputs = new_inputs
        op.attrs = {}
        n += 1

    if n:
        _eliminate_dead_ops(block, fetch_names)
    return n


def _rewrite_conv(block, scope, op, fake_out, fake_weight,
                  min_weight_elements) -> int:
    """conv2d -> quantized_conv2d when Filter is a persistable OIHW weight
    (the ResNet/ViT vision-inference case the matmul-only pass skipped)."""
    fs = op.input("Filter")
    xs_in = op.input("Input")
    if not fs or not xs_in:
        return 0
    wname = fake_weight.get(fs[0], fs[0])
    wvar = block.vars.get(wname)
    if wvar is None or not getattr(wvar, "persistable", False):
        return 0
    w = scope.find_var(wname)
    if w is None:
        return 0
    w = np.asarray(w)
    if w.ndim != 4 or w.size < min_weight_elements:
        return 0
    # per-output-channel symmetric scale over (I, KH, KW)
    ws = np.maximum(np.abs(w).max(axis=(1, 2, 3)), 1e-8) / 127.0
    wq = np.clip(np.round(w / ws.reshape(-1, 1, 1, 1)), -127,
                 127).astype(np.int8)
    qname, sname = f"{wname}@int8", f"{wname}@wscale"
    scope.set(qname, wq)
    scope.set(sname, ws.astype(np.float32))
    block.create_var(name=qname, shape=wq.shape, dtype="int8",
                     persistable=True, stop_gradient=True)
    block.create_var(name=sname, shape=ws.shape, dtype="float32",
                     persistable=True, stop_gradient=True)
    new_inputs = {"Input": [xs_in[0]], "Filter": [qname], "WScale": [sname]}
    src = fake_out.get(xs_in[0])
    if src is not None and src[1] is not None:
        new_inputs["Input"] = [src[0].input("X")[0]]
        new_inputs["XScale"] = [src[1]]
    op.type = "quantized_conv2d"
    op.inputs = new_inputs
    return 1


def _eliminate_dead_ops(block, fetch_names=None):
    """Drop ops whose outputs nothing consumes (the bypassed fake-quant
    nodes) — backward liveness sweep over the flat block."""
    live = set(fetch_names or [])
    for op in block.ops:
        if op.type == "fetch":
            live.update(op.input_arg_names)
    keep = []
    for op in reversed(block.ops):
        if op.type in ("feed", "fetch") or any(
                o in live for o in op.output_arg_names) or not op.outputs:
            keep.append(op)
            live.update(op.input_arg_names)
    block.ops[:] = list(reversed(keep))
