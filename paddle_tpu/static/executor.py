"""Static-graph Executor: lowers a whole Program block to ONE jitted XLA
computation.

Parity target: ``/root/reference/paddle/fluid/framework/executor.cc``
(``Executor::Run`` :166/:292 — per-op interpreter loop with scope + GC) and
its Python driver ``/root/reference/python/paddle/fluid/executor.py``
(``Executor.run``:916, ``_run_impl``:1112, ``_run_program``:1257).

TPU-first design
----------------
The reference interprets OpDescs one-by-one (op->Run per kernel launch).
Here the WHOLE block is traced once into a single JAX function and compiled
by XLA — the "AscendOptimizer pattern" (whole-ProgramDesc lowering to an
accelerator graph, cf. the reference's
``fleet/meta_optimizers/ascend/ascend_optimizer.py:213``) done natively:

* persistable vars (parameters, optimizer state, BN stats) are threaded
  through the jitted step function and **donated**, so XLA updates them
  in-place in HBM — the functional equivalent of the reference's mutable
  scope + its memory-reuse/inplace IR passes;
* dead intermediate buffers are freed by XLA buffer assignment — no garbage
  collector needed (cf. executor_gc_helper.cc);
* op fusion happens in XLA — no fusion pass zoo;
* randomness: each random op gets a PRNG key folded from (seed, step, op
  index) — stateless and reproducible, unlike the reference's global
  generator.

Compiled callables are cached per (program identity+version, feed signature,
fetch list), mirroring the reference's ExecutorPrepareContext cache
(executor.py:1257 area).
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..framework import program as fw
from ..framework.dtype import to_jax_dtype, to_numpy_dtype
from ..framework.place import Place, _get_current_place
from ..framework.scope import Scope, global_scope
from ..ops import registry

logger = logging.getLogger(__name__)

# op types handled by the runner itself (parity: feed/fetch ops appended by
# the reference's _add_feed_fetch_ops)
_SKIP_OPS = frozenset({"feed", "fetch"})


import contextlib


def _null_ctx():
    return contextlib.nullcontext()


class Executor:
    """``paddle.static.Executor`` replacement (see module docstring)."""

    def __init__(self, place: Optional[Place] = None):
        self.place = place if place is not None else _get_current_place()
        self._cache: Dict[Any, Any] = {}
        self._step_counters: Dict[int, int] = {}

    # ------------------------------------------------------------------
    def run(
        self,
        program: Optional[fw.Program] = None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        if program is None:
            program = fw.default_main_program()
        # CompiledProgram passthrough (compiler.py parity)
        inner = getattr(program, "_program", None)
        if inner is not None:
            program = inner
        feed = feed or {}
        fetch_list = list(fetch_list or [])
        scope = scope if scope is not None else global_scope()

        fetch_names = [v.name if isinstance(v, fw.Variable) else str(v) for v in fetch_list]
        block = program.global_block()

        feed_sig = tuple(
            (name, tuple(np.shape(val)), str(np.asarray(val).dtype) if not hasattr(val, "dtype") else str(val.dtype))
            for name, val in sorted(feed.items())
        )
        from ..framework import flags

        check_nan = flags.flag("FLAGS_check_nan_inf")
        key = (id(program), program._version, feed_sig, tuple(fetch_names),
               check_nan)
        entry = self._cache.get(key) if use_program_cache else None
        if entry is None:
            entry = self._compile(program, block, feed, fetch_names, scope)
            if use_program_cache:
                self._cache[key] = entry
        compiled, mut_names, const_names, op_labels = entry

        def load(names):
            st = {}
            for n in names:
                v = scope.find_var(n)
                if v is None:
                    raise RuntimeError(
                        f"Persistable variable {n!r} is not initialized; run the "
                        f"startup program first (exe.run(startup_program))"
                    )
                st[n] = v
            return st

        mut_state = load(mut_names)
        const_state = load(const_names)

        feeds = {n: self._to_device(v, block, n) for n, v in feed.items()}
        step_id = self._step_counters.get(id(program), 0)
        self._step_counters[id(program)] = step_id + 1
        seed = program.random_seed or 0
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), step_id)

        import sys

        prof = sys.modules.get("paddle_tpu.profiler")
        ctx = (prof.RecordEvent("executor_run")
               if prof is not None and prof.is_profiling() else _null_ctx())
        with ctx:
            if op_labels is None:
                out_state, fetches = compiled(mut_state, const_state, feeds, rng)
            else:
                out_state, fetches, oks = compiled(
                    mut_state, const_state, feeds, rng)
                from ..framework.nan_inf import raise_first_bad_op

                raise_first_bad_op(oks, op_labels)
        for n, v in out_state.items():
            scope.set(n, v)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return list(fetches)

    # ------------------------------------------------------------------
    def _compile(self, program, block, feed, fetch_names, scope):
        ops = [op for op in block.ops if op.type not in _SKIP_OPS]
        feed_names = set(feed)

        # counted while_loops were rewritten to fixed-trip fori_loops using
        # fill_constant values read at build time; feeding those vars would be
        # silently ignored — reject instead (ADVICE round 2)
        for op in ops:
            baked = op.attrs.get("__trip_const_vars__")
            if baked:
                clash = feed_names.intersection(baked)
                if clash:
                    raise ValueError(
                        f"Executor.run: feed overrides {sorted(clash)}, but "
                        f"op {op.type!r} statically baked those fill_constant "
                        f"values into its loop trip count at build time. "
                        f"Build the loop bound from a data tensor (not a "
                        f"fed constant), or rebuild the program per bound.")

        # classify vars: state-in = persistable inputs not fed; everything an
        # op produces that is persistable goes back to the scope.
        produced = set()
        state_in: List[str] = []
        out_state: List[str] = []
        seen_in = set()
        for op in ops:
            for n in op.input_arg_names:
                if n in feed_names or n in produced or n in seen_in:
                    continue
                var = block._var_recursive(n)
                seen_in.add(n)
                state_in.append(n)
                if not var.persistable and scope.find_var(n) is None:
                    where = (
                        f"\nOp built at (FLAGS_call_stack_level>=2):\n"
                        f"{op.callstack}" if getattr(op, "callstack", None)
                        else "")
                    raise RuntimeError(
                        f"Op {op.type} reads variable {n!r} which is neither "
                        f"fed, produced earlier, nor present in the scope"
                        + where
                    )
            for n in op.output_arg_names:
                if n:
                    produced.add(n)
        for n in sorted(produced):
            try:
                var = block._var_recursive(n)
            except ValueError:
                continue
            if var.persistable:
                out_state.append(n)

        # fetch targets served straight from the scope (e.g. inspecting a
        # parameter no op reads) become const state (parity: the reference
        # executor fetches from the scope)
        for n in fetch_names:
            if n not in produced and n not in feed_names and n not in seen_in:
                seen_in.add(n)
                state_in.append(n)

        # donate only the buffers the program rebinds (ParamOut, BN stats...);
        # read-only state (learning rate, frozen params) must survive the call
        out_set = set(out_state)
        mut_names = [n for n in state_in if n in out_set]
        const_names = [n for n in state_in if n not in out_set]

        from ..framework import flags as _flags

        check_nan = _flags.flag("FLAGS_check_nan_inf")
        op_labels = None
        if check_nan:
            from ..framework import nan_inf

            op_labels = [
                f"{op.type}({', '.join(n for ns in op.outputs.values() for n in ns if n)})"
                for op in ops
            ]

        def step(mut_state: Dict[str, Any], const_state: Dict[str, Any], feeds, rng):
            env = dict(mut_state)
            env.update(const_state)
            env.update(feeds)
            oks = []
            for i, op in enumerate(ops):
                op_def = registry.get_op_def(op.type)
                ins = {}
                for slot, names in op.inputs.items():
                    vals = [env[n] for n in names if n]
                    if vals or slot in op_def.list_slots:
                        ins[slot] = vals
                r = jax.random.fold_in(rng, i) if op_def.needs_rng else None
                try:
                    outs = registry.run_kernel(op_def, ins, op.attrs, rng=r)
                except Exception as e:
                    # tracing failure: annotate with the op + creation site
                    fw.raise_with_op_site(op, "failed to lower", e)
                if check_nan:
                    oks.append(nan_inf.op_all_finite(outs))
                for slot, names in op.outputs.items():
                    vals = outs.get(slot, [])
                    for n, v in zip(names, vals):
                        if n:
                            env[n] = v
            new_state = {n: env[n] for n in out_state if n in env}
            fetches = [env[n] for n in fetch_names]
            if check_nan:
                import jax.numpy as jnp

                return new_state, fetches, (
                    jnp.stack(oks) if oks else jnp.ones((0,), jnp.bool_))
            return new_state, fetches

        compiled = jax.jit(step, donate_argnums=(0,))
        return compiled, mut_names, const_names, op_labels

    # ------------------------------------------------------------------
    def _to_device(self, val, block, name):
        import jax.numpy as jnp

        if hasattr(val, "value") and hasattr(val, "_array"):  # dygraph Tensor
            val = val._array
        if isinstance(val, jax.Array):
            return val
        try:
            var = block._var_recursive(name)
            dtype = to_numpy_dtype(var.dtype)
        except ValueError:
            dtype = None
        arr = np.asarray(val, dtype=dtype)
        return jnp.asarray(arr)

    def close(self):
        self._cache.clear()


class CompiledProgram:
    """Parity shim for ``fluid.compiler.CompiledProgram`` — under XLA the
    plain Executor already compiles whole programs, and data parallelism is
    expressed with shard_map (see paddle_tpu.distributed), so this is a thin
    wrapper."""

    def __init__(self, program: fw.Program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, **kwargs):
        return self


def as_compiled(program):
    return CompiledProgram(program)
