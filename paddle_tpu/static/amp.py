"""Static-graph automatic mixed precision: program-rewriting bf16 casts.

Parity: ``/root/reference/python/paddle/fluid/contrib/mixed_precision/
decorator.py:1`` (``decorate`` -> OptimizerWithMixedPrecision) and
``fp16_utils.py`` (``rewrite_program``: white/black list walk inserting
cast ops; ``cast_model_to_fp16``).

TPU-first: the payoff dtype is **bfloat16** (MXU native; no loss scaling
needed — bf16 has fp32's exponent range, so the reference's
found_inf/loss-scaling machinery is unnecessary on this path, though
``decorate`` keeps the arg surface).  Parameters stay fp32 in the scope
(master weights by construction); casts are inserted per-use ahead of
white-list ops, so the optimizer update runs full precision — the
``multi_precision`` interplay the dygraph O2 path implements with explicit
master copies.
"""

from __future__ import annotations

from typing import Optional, Set

from ..framework import program as fw

__all__ = [
    "AutoMixedPrecisionLists",
    "rewrite_program",
    "cast_model_to_bf16",
    "decorate",
    "bf16_guard",
]


class AutoMixedPrecisionLists:
    """Parity: fp16_lists.py AutoMixedPrecisionLists — three-way op split.

    white: numerically safe AND MXU-profitable (run in bf16);
    black: numerically sensitive (forced fp32);
    gray: follow their inputs.
    """

    _DEFAULT_WHITE = {
        "matmul", "matmul_v2", "mul", "conv2d", "depthwise_conv2d",
        "conv2d_transpose", "addmm",
    }
    _DEFAULT_BLACK = {
        "softmax_with_cross_entropy", "cross_entropy",
        "sigmoid_cross_entropy_with_logits", "bce_loss", "c_softmax_with_cross_entropy",
        "mean", "reduce_mean", "reduce_sum", "sum",
        "exp", "log", "log2", "log10", "log1p", "rsqrt", "pow",
        "square", "squared_l2_norm", "p_norm", "norm", "cumsum",
        "softmax", "log_softmax", "layer_norm", "batch_norm",
        "group_norm", "instance_norm",
    }

    def __init__(self, custom_white_list: Optional[Set[str]] = None,
                 custom_black_list: Optional[Set[str]] = None,
                 custom_black_varnames: Optional[Set[str]] = None):
        self.white_list = set(self._DEFAULT_WHITE)
        self.black_list = set(self._DEFAULT_BLACK)
        self.black_varnames = set(custom_black_varnames or ())
        if custom_white_list:
            self.white_list |= set(custom_white_list)
            self.black_list -= set(custom_white_list)
        if custom_black_list:
            self.black_list |= set(custom_black_list)
            self.white_list -= set(custom_black_list)


_FLOAT_DTYPES = {"float32", "float64"}


def _is_float_var(block, name):
    try:
        var = block._var_recursive(name)
    except Exception:
        return False
    return str(getattr(var, "dtype", "")) in _FLOAT_DTYPES | {"bfloat16",
                                                              "float16"}


def _insert_cast(block, new_ops, cache, name, dest, src_dtype):
    """Append a cast op producing ``name.cast_<dest>`` (memoized)."""
    key = (name, dest)
    if key in cache:
        return cache[key]
    out = f"{name}.cast_{dest}"
    if out not in block.vars:
        src = block._var_recursive(name)
        block.create_var(name=out, shape=getattr(src, "shape", None),
                         dtype=dest)
    op = fw.Operator(block, "cast", inputs={"X": [name]},
                     outputs={"Out": [out]},
                     attrs={"in_dtype": src_dtype, "out_dtype": dest})
    new_ops.append(op)
    cache[key] = out
    return out


def rewrite_program(main_program, amp_lists: Optional[AutoMixedPrecisionLists]
                    = None, dest_dtype: str = "bfloat16"):
    """Parity: fp16_utils.rewrite_program — walk the global block, cast
    float inputs of white-list ops to ``dest_dtype`` and inputs of
    black-list ops back to fp32.  Gray ops run in whatever dtype reaches
    them (XLA type-propagates; outputs follow jnp promotion, so a gray
    elementwise op over bf16 inputs stays bf16)."""
    amp_lists = amp_lists or AutoMixedPrecisionLists()
    block = main_program.global_block()
    new_ops = []
    cache = {}
    low_vars = set()  # vars known to be dest_dtype at runtime
    for op in list(block.ops):
        if op.type in ("cast", "feed", "fetch"):
            new_ops.append(op)
            continue
        if op.type in amp_lists.white_list and not (
                amp_lists.black_varnames
                and any(n in amp_lists.black_varnames
                        for ns in op.outputs.values() for n in ns)):
            for slot, names in op.inputs.items():
                for i, n in enumerate(names):
                    if not _is_float_var(block, n) or n in low_vars:
                        continue
                    names[i] = _insert_cast(block, new_ops, cache, n,
                                            dest_dtype, "float32")
            new_ops.append(op)
            for ns in op.outputs.values():
                low_vars.update(ns)
        elif op.type in amp_lists.black_list:
            for slot, names in op.inputs.items():
                for i, n in enumerate(names):
                    if n in low_vars:
                        names[i] = _insert_cast(block, new_ops, cache, n,
                                                "float32", dest_dtype)
            new_ops.append(op)
        else:
            # gray: propagate low precision through elementwise/shape ops
            new_ops.append(op)
            if any(n in low_vars
                   for ns in op.inputs.values() for n in ns):
                for ns in op.outputs.values():
                    low_vars.update(ns)
    block.ops = new_ops
    return main_program


# reference alias (cast_model_to_fp16 role, bf16 flavor)
def cast_model_to_bf16(program, amp_lists=None):
    return rewrite_program(program, amp_lists, dest_dtype="bfloat16")


class _BF16GuardCtx:
    enabled = False


class bf16_guard:
    """Parity role: fp16_utils fp16_guard — scope marker; ops built inside
    are eligible for the white list rewrite (here: all ops are eligible by
    default, the guard is accepted for API compatibility)."""

    def __enter__(self):
        _BF16GuardCtx.enabled = True
        return self

    def __exit__(self, *exc):
        _BF16GuardCtx.enabled = False
        return False


class OptimizerWithMixedPrecision:
    """Parity: decorator.py OptimizerWithMixedPrecision — wraps minimize:
    rewrite forward program to bf16, then build backward + optimize ops on
    the rewritten graph (grads of casts are casts back, so param grads and
    updates stay fp32 = master weights)."""

    def __init__(self, optimizer, amp_lists=None, init_loss_scaling=1.0,
                 use_dynamic_loss_scaling=False, dest_dtype="bfloat16"):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._dest_dtype = dest_dtype
        # bf16 needs no loss scaling (fp32 exponent range); args accepted
        # for reference API compatibility
        self._loss_scaling = init_loss_scaling

    def get_loss_scaling(self):
        return self._loss_scaling

    def amp_init(self, place=None, scope=None, test_program=None,
                 use_bf16_test=False):
        if test_program is not None:
            rewrite_program(test_program, self._amp_lists, self._dest_dtype)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        program = loss.block.program
        rewrite_program(program, self._amp_lists, self._dest_dtype)
        return self._optimizer.minimize(
            loss, startup_program=startup_program, parameters=parameters,
            no_grad_set=no_grad_set)

    def backward(self, loss, **kw):
        rewrite_program(loss.block.program, self._amp_lists, self._dest_dtype)
        return self._optimizer.backward(loss, **kw)

    def __getattr__(self, name):
        return getattr(self._optimizer, name)


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=None, decr_every_n_nan_or_inf=None,
             incr_ratio=None, decr_ratio=None,
             use_dynamic_loss_scaling=False, use_pure_bf16=False,
             use_bf16_guard=None):
    """Parity: decorator.py decorate:1 — returns the wrapped optimizer."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists=amp_lists,
        init_loss_scaling=init_loss_scaling,
        use_dynamic_loss_scaling=use_dynamic_loss_scaling)
