"""``paddle.static`` equivalent: Program construction + Executor + autodiff.

Parity: ``/root/reference/python/paddle/static/`` plus the executor/backward
halves of ``python/paddle/fluid/``.
"""

from ..framework.program import (  # noqa: F401
    Program,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
)
from .backward import append_backward, gradients  # noqa: F401
from .executor import CompiledProgram, Executor  # noqa: F401
from .io import load, load_inference_model, save, save_inference_model  # noqa: F401
from .input import data, InputSpec  # noqa: F401
from . import nn  # noqa: F401
from . import amp  # noqa: F401
from .control_flow import cond, while_loop  # noqa: F401
