"""``paddle.static`` equivalent: Program construction + Executor + autodiff.

Parity: ``/root/reference/python/paddle/static/`` plus the executor/backward
halves of ``python/paddle/fluid/``.
"""

from ..framework.program import (  # noqa: F401
    Program,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
)
from .backward import append_backward, gradients  # noqa: F401
from .executor import CompiledProgram, Executor  # noqa: F401
from .io import load, load_inference_model, save, save_inference_model  # noqa: F401
from .input import data, InputSpec  # noqa: F401
from . import nn  # noqa: F401
from . import amp  # noqa: F401
from .control_flow import cond, while_loop  # noqa: F401

# -- surface-completeness batch (reference paddle/static/__init__.py) -------
from ..framework.scope import Scope, global_scope  # noqa: F401
from ..framework.program import Variable  # noqa: F401
from ..tensor_api import create_parameter  # noqa: F401
from ..nn.functional import accuracy  # noqa: F401


def scope_guard(scope):
    """Parity: paddle.static.scope_guard — run under a specific Scope."""
    import contextlib

    from ..framework import scope as _scope_mod

    @contextlib.contextmanager
    def guard():
        old = _scope_mod._global_scope
        _scope_mod._global_scope = scope
        try:
            yield
        finally:
            _scope_mod._global_scope = old

    return guard()


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Parity: layers.create_global_var — a persistable filled var."""
    from ..framework import program as _fw
    from ..framework import unique_name as _un

    block = _fw.default_main_program().global_block()
    name = name or _un.generate("global_var")
    var = block.create_var(name=name, shape=list(shape), dtype=dtype,
                           persistable=persistable)
    sb = _fw.default_startup_program().global_block()
    sb.create_var(name=name, shape=list(shape), dtype=dtype,
                  persistable=persistable)
    sb.append_op(type="fill_constant", inputs={}, outputs={"Out": [name]},
                 attrs={"shape": list(shape), "value": float(value),
                        "dtype": dtype})
    return var


def cpu_places(device_count=None):
    import os as _os

    n = device_count or int(_os.environ.get("CPU_NUM", 1))
    from ..framework.place import CPUPlace

    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """Accelerator places (CUDA-named surface resolves to TPU devices)."""
    import jax as _jax

    from ..framework.place import CUDAPlace

    ids = device_ids if device_ids is not None else range(
        len(_jax.devices()))
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..framework.place import XPUPlace

    ids = device_ids if device_ids is not None else [0]
    return [XPUPlace(i) for i in ids]


def device_guard(device=None):
    """Parity: paddle.static.device_guard — per-op device placement hint.
    One XLA program per block here, so the hint is accepted and recorded
    (XLA owns placement); the context manager exists for API parity."""
    import contextlib

    @contextlib.contextmanager
    def guard():
        yield

    return guard()


class BuildStrategy:
    """Parity: BuildStrategy (details/build_strategy.h:75) — accepted
    pass-toggle container; XLA owns fusion/memory passes, so the knobs are
    recorded but the compiled result is always the one-jit program."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0


class ExecutionStrategy:
    """Parity: ExecutionStrategy — thread/iteration knobs (XLA-managed)."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1


class ParallelExecutor:
    """Parity surface: ParallelExecutor (parallel_executor.h:51).  The
    SSA-graph multi-device runtime is subsumed by GSPMD (SURVEY §7);
    this shell delegates to the one-jit Executor over the mesh."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, scope=None,
                 share_vars_from=None):
        from ..framework import program as _fw

        self._program = main_program or _fw.default_main_program()
        self._exe = Executor()
        self._scope = scope

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed,
                             fetch_list=fetch_list, scope=self._scope,
                             return_numpy=return_numpy)


class WeightNormParamAttr:
    """Parity surface: WeightNormParamAttr — accepted; use
    nn.utils.weight_norm for the live reparameterization."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Parity: paddle.static.py_func — host-side python op. The dygraph/
    jit path covers this via jax.pure_callback in utils.cpp_extension;
    static programs run whole-block jitted, so arbitrary python in the
    middle of a block is rejected loudly."""
    raise NotImplementedError(
        "py_func inside a static Program is not supported (the whole block "
        "compiles to one XLA program); use a custom op "
        "(paddle.utils.cpp_extension.load) which runs as a host callback")


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """Parity: paddle.static.Print — debug-print pass-through (host
    callback via jax.debug.print at lowering)."""
    from ..dygraph import tracer as _tr

    def fn(a):
        import jax

        jax.debug.print((message or "") + "{x}", x=a)
        return a

    return _tr.trace_fn(fn, [input], name="print")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    """Parity: fluid.layers.auc surface — batch AUC via paddle.metric.Auc
    semantics (host-side accumulation lives in paddle.metric)."""
    from ..metric import Auc as _Auc

    import numpy as _np

    m = _Auc(num_thresholds=num_thresholds)
    m.update(_np.asarray(input.numpy()), _np.asarray(label.numpy()))
    from ..tensor_api import to_tensor

    return to_tensor(_np.asarray(m.accumulate(), "float32"))


# program state / vars IO (reference fluid/io.py surface over static/io.py)
def load_program_state(model_path, var_list=None):
    from .io import load_program_state as _f

    return _f(model_path, var_list)


def set_program_state(program, state_dict):
    from .io import set_program_state as _f

    return _f(program, state_dict)


def save_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from .io import save_vars as _f

    return _f(executor, dirname, main_program, vars, predicate, filename)


def load_vars(executor, dirname, main_program=None, vars=None,
              predicate=None, filename=None):
    from .io import load_vars as _f

    return _f(executor, dirname, main_program, vars, predicate, filename)


def serialize_program(feed_vars, fetch_vars, **kwargs):
    from .io import serialize_program as _f

    return _f(feed_vars, fetch_vars, **kwargs)


def serialize_persistables(feed_vars, fetch_vars, executor, **kwargs):
    from .io import serialize_persistables as _f

    return _f(feed_vars, fetch_vars, executor, **kwargs)


def deserialize_program(data):
    from .io import deserialize_program as _f

    return _f(data)


def deserialize_persistables(program, data, executor):
    from .io import deserialize_persistables as _f

    return _f(program, data, executor)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars):
    from .io import normalize_program as _f

    return _f(program, feed_vars, fetch_vars)
