"""Control-flow ops: ``while_loop`` and ``cond``.

Parity: ``/root/reference/paddle/fluid/operators/controlflow/while_op.cc:1``,
``conditional_block_op.cc:1`` and their surface
``python/paddle/fluid/layers/control_flow.py`` (while_loop, cond).

TPU-first design:
  * dygraph mode = plain Python control flow over eager tensors (exactly the
    reference's dygraph branch) — fully differentiable through the tape;
  * static mode captures the branch/body as a sub-op-list (the reference's
    sub-Block) and lowers it INTO the executor's single XLA program as
    ``lax.cond`` / ``lax.while_loop`` via a one-off registered op;
  * a trip-count inference pass (the role of XLA's own
    ``WhileLoopTripCountAnnotator``) rewrites counted ``i < N`` loops to
    ``lax.fori_loop`` with static bounds, which IS reverse-differentiable —
    so RNN-style counted training loops get gradients, while genuinely
    dynamic loops stay forward-only (reverse-mode through an unbounded while
    is impossible under static memory; the reference pays for it with an
    unbounded activation stack).
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..framework import program as fw
from ..ops import registry

__all__ = ["while_loop", "cond"]

_cf_counter = [0]


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _capture(fn, args):
    """Run a branch/body builder under the current block and pop the ops it
    appended (the sub-Block of while_op/conditional_block_op)."""
    block = fw.default_main_program().current_block()
    start = len(block.ops)
    outs = fn(*args)
    ops = list(block.ops[start:])
    del block.ops[start:]
    return _as_list(outs), outs if isinstance(outs, (list, tuple)) or outs is None else outs, ops


def _externals(op_lists, exclude):
    """Names read by the captured ops but produced outside them."""
    produced = set(exclude)
    ext: List[str] = []
    for ops in op_lists:
        inner = set()
        for op in ops:
            for n in op.input_arg_names:
                if n and n not in produced and n not in inner and n not in ext:
                    ext.append(n)
            for n in op.output_arg_names:
                if n:
                    inner.add(n)
        produced |= inner
    return ext


def _run_ops(ops, env):
    """Interpret captured ops on an array env (the executor's inner loop)."""
    for op in ops:
        op_def = registry.get_op_def(op.type)
        ins = {}
        for slot, names in op.inputs.items():
            vals = [env[n] for n in names if n]
            if vals or slot in op_def.list_slots:
                ins[slot] = vals
        outs = registry.run_kernel(op_def, ins, op.attrs, rng=None)
        for slot, names in op.outputs.items():
            for n, v in zip(names, outs.get(slot, [])):
                if n:
                    env[n] = v
    return env


def _const_value(name, blocks, _depth=0):
    """Constant produced for ``name``: a fill_constant (scalar or
    1-element list value), seen through ``assign``/``cast`` chains (the
    dy2static promotion path emits assign-of-fill_constant)."""
    if _depth > 8:
        return None
    for block in blocks:
        for op in block.ops:
            if name not in op.output_arg_names:
                continue
            if op.type == "fill_constant":
                v = op.attrs.get("value")
                if isinstance(v, (list, tuple)):
                    flat = np.asarray(v).reshape(-1)
                    return float(flat[0]) if flat.size == 1 else None
                return float(v)
            if op.type in ("assign", "cast"):
                src = (op.inputs.get("X") or [None])[0]
                if src:
                    return _const_value(src, blocks, _depth + 1)
    return None


def _infer_trip_count(cond_ops, cond_out_name, body_ops, body_out_names,
                      loop_names):
    """Static trip count for counted loops.

    Recognized forms (role of XLA's WhileLoopTripCountAnnotator):
      cond:  ``less_than(i, N)`` / ``less_equal(i, N)`` with ``i`` a loop var
             and both ``i``'s init and ``N`` produced by ``fill_constant``;
      body:  ``i = scale(i, scale=1, bias=step)`` or
             ``i = elementwise_add(i, fill_constant(step))`` with step > 0.

    Returns ``(trip_count, const_var_names, None)`` on success or
    ``(None, [], reason)`` explaining why the loop stays dynamic — the reason
    is surfaced by ``append_backward`` when a gradient is requested.
    """
    producer = {n: op for op in cond_ops for n in op.output_arg_names}
    last = producer.get(cond_out_name)
    if last is None:
        return None, [], "loop condition is not produced inside cond_fn"
    if last.type not in ("less_than", "less_equal"):
        return None, [], (
            f"loop condition op is {last.type!r}; only less_than/less_equal "
            f"comparisons against a constant bound are recognized as counted")
    inclusive = last.type == "less_equal"
    x = (last.inputs.get("X") or [None])[0]
    y = (last.inputs.get("Y") or [None])[0]
    if x not in loop_names:
        return None, [], (
            f"comparison LHS {x!r} is not a loop variable — the counter must "
            f"be one of loop_vars")
    blocks = [fw.default_main_program().global_block(),
              fw.default_startup_program().global_block(),
              _FakeBlock(cond_ops)]  # bound may be built inside cond_fn
    bound = _const_value(y, blocks)
    init = _const_value(x, blocks)
    if bound is None or init is None:
        missing = y if bound is None else x
        return None, [], (
            f"{missing!r} is not a fill_constant — counter init and bound "
            f"must be compile-time constants for a static trip count")
    idx = loop_names.index(x)
    out_name = body_out_names[idx]
    step = None
    for op in body_ops:
        if out_name in op.output_arg_names:
            if op.type == "scale" and (op.inputs.get("X") or [None])[0] == x:
                if float(op.attrs.get("scale", 1.0)) == 1.0:
                    step = float(op.attrs.get("bias", 0.0))
            elif op.type in ("elementwise_add", "elementwise_sub"):
                a = (op.inputs.get("X") or [None])[0]
                b = (op.inputs.get("Y") or [None])[0]
                other = b if a == x else (a if b == x else None)
                if other is not None:
                    c = _const_value(other, blocks + [_FakeBlock(body_ops)])
                    if c is not None:
                        step = -c if op.type == "elementwise_sub" else c
            break
    if step is None:
        return None, [], (
            f"counter update for {x!r} is not ``scale(bias=step)`` or "
            f"``elementwise_add(i, const)`` — cannot derive a static step")
    if step <= 0:
        return None, [], f"counter step {step} is not positive"
    trips = math.ceil((bound + (1 if inclusive else 0) - init) / step)
    return max(int(trips), 0), [x, y], None


class _FakeBlock:
    """Adapter so _const_value can also scan body ops for constants."""

    def __init__(self, ops):
        self.ops = ops


def _register_one_off(op_type, kernel, no_grad=False, **kw):
    """Ephemeral registration: the OpDef dies with the owning Operator, which
    must keep a strong ref via ``op._ephemeral_def`` (registry weak-holds it).
    Fixes the per-program-build permanent-registry leak (ADVICE round 2)."""
    return registry.register_ephemeral(registry.OpDef(
        type=op_type, kernel=kernel,
        list_slots=kw.pop("list_slots", {"X", "Captured", "Out"}),
        no_grad=no_grad, **kw,
    ))


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name: Optional[str] = None):
    """``paddle.static.nn.while_loop`` parity (control_flow.py while_loop)."""
    loop_vars = _as_list(loop_vars)
    if not loop_vars:
        raise ValueError("loop_vars must not be empty")

    if fw.in_dygraph_mode():
        pred = cond(*loop_vars)
        while bool(np.asarray(pred._array).reshape(-1)[0]):
            out = _as_list(body(*loop_vars))
            if len(out) != len(loop_vars):
                raise ValueError(
                    f"body returned {len(out)} vars, expected {len(loop_vars)}")
            loop_vars = out
            pred = cond(*loop_vars)
        return loop_vars

    from ..ops.dispatch import dispatch_static

    block = fw.default_main_program().current_block()
    cond_outs, _, cond_ops = _capture(cond, loop_vars)
    body_outs, _, body_ops = _capture(body, loop_vars)
    if len(body_outs) != len(loop_vars):
        raise ValueError(
            f"body returned {len(body_outs)} vars, expected {len(loop_vars)}")
    loop_names = [v.name for v in loop_vars]
    body_out_names = [v.name for v in body_outs]
    cond_out_name = cond_outs[0].name
    ext_names = _externals([cond_ops, body_ops], set(loop_names))
    ext_vars = [block._var_recursive(n) for n in ext_names]

    if is_test:
        trip, const_vars, why = None, [], "is_test=True loops stay dynamic"
    else:
        trip, const_vars, why = _infer_trip_count(
            cond_ops, cond_out_name, body_ops, body_out_names, loop_names)

    n_loop = len(loop_vars)

    def kernel(kins, attrs):
        import jax.numpy as jnp
        from jax import lax

        xs = tuple(kins["X"])
        exts = dict(zip(ext_names, kins.get("Captured", [])))

        def run_body(carry):
            env = dict(exts)
            env.update(zip(loop_names, carry))
            env = _run_ops(body_ops, env)
            return tuple(env[n] for n in body_out_names)

        def run_cond(carry):
            env = dict(exts)
            env.update(zip(loop_names, carry))
            env = _run_ops(cond_ops, env)
            return jnp.reshape(env[cond_out_name], ())

        if trip is not None:
            # counted loop -> fori with static bounds (reverse-differentiable)
            out = lax.fori_loop(0, trip, lambda i, c: run_body(c), xs)
        else:
            out = lax.while_loop(run_cond, run_body, xs)
        return {"Out": list(out)}

    _cf_counter[0] += 1
    op_type = f"__while_{_cf_counter[0]}"
    # dynamic while cannot be reverse-differentiated — mark no_grad so
    # append_backward raises a clear error (carrying ``why``) instead of a
    # jax internal one
    od = _register_one_off(op_type, kernel, no_grad=(trip is None))
    attrs = {"trip_count": -1 if trip is None else trip}
    if trip is None:
        attrs["__no_fori_reason__"] = why
    else:
        # the fori rewrite baked these fill_constant values in; feeding them
        # at run time would be silently ignored — the executor rejects that
        # (ADVICE round 2)
        attrs["__trip_const_vars__"] = list(const_vars)
    outs = dispatch_static(
        op_type, {"X": loop_vars, "Captured": ext_vars}, attrs,
    )["Out"]
    block.ops[-1]._ephemeral_def = od
    return outs[:n_loop]


def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name: Optional[str] = None):
    """``paddle.static.nn.cond`` parity (conditional_block_op role)."""
    if fw.in_dygraph_mode():
        taken = bool(np.asarray(pred._array).reshape(-1)[0])
        fn = true_fn if taken else false_fn
        return fn() if fn is not None else None

    from ..ops.dispatch import dispatch_static

    block = fw.default_main_program().current_block()
    true_outs, _, true_ops = _capture(true_fn, ()) if true_fn else ([], None, [])
    false_outs, _, false_ops = _capture(false_fn, ()) if false_fn else ([], None, [])
    if len(true_outs) != len(false_outs):
        raise ValueError(
            f"true_fn returned {len(true_outs)} vars but false_fn returned "
            f"{len(false_outs)} — branch outputs must match")
    if not true_outs:
        return None
    t_names = [v.name for v in true_outs]
    f_names = [v.name for v in false_outs]
    ext_names = _externals([true_ops, false_ops], set())
    # identity pass-throughs: a branch may RETURN a pre-existing var without
    # creating any op (e.g. the untaken side of a converted break-flag if);
    # those names must ride in as captured externals too
    for names, ops in ((t_names, true_ops), (f_names, false_ops)):
        produced = {n for op in ops for n in op.output_arg_names}
        for n in names:
            if n not in produced and n not in ext_names:
                ext_names.append(n)
    ext_vars = [block._var_recursive(n) for n in ext_names]
    single = len(true_outs) == 1

    def kernel(kins, attrs):
        import jax.numpy as jnp
        from jax import lax

        p = jnp.reshape(kins["Cond"][0], ())
        exts = tuple(kins.get("Captured", []))

        def tbr(ext_t):
            env = _run_ops(true_ops, dict(zip(ext_names, ext_t)))
            return tuple(env[n] for n in t_names)

        def fbr(ext_t):
            env = _run_ops(false_ops, dict(zip(ext_names, ext_t)))
            return tuple(env[n] for n in f_names)

        out = lax.cond(p, tbr, fbr, exts)
        return {"Out": list(out)}

    _cf_counter[0] += 1
    op_type = f"__cond_{_cf_counter[0]}"
    od = _register_one_off(
        op_type, kernel, list_slots={"Cond", "Captured", "Out"},
        nondiff_slots={"Cond"},
    )
    outs = dispatch_static(
        op_type, {"Cond": [pred], "Captured": ext_vars}, {})["Out"]
    block.ops[-1]._ephemeral_def = od
    return outs[0] if single else outs
