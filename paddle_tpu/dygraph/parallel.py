"""``paddle.DataParallel``.

Parity: ``/root/reference/python/paddle/fluid/dygraph/parallel.py:382``
(DataParallel wrapping the C++ Reducer — bucketed overlapped allreduce,
``reducer.cc`` 1,091 LoC).

TPU-first: the Reducer is unnecessary (SURVEY.md §7 layer 6) — inputs are
sharded over the 'dp' mesh axis and parameters replicated, so the gradient
of a replicated param over a sharded batch IS the allreduced gradient; XLA
emits and overlaps the reduction.  scale_loss / apply_collective_grads are
kept as no-op parity shims.
"""

from __future__ import annotations

from ..distributed.fleet.meta_parallel.parallel_wrappers import DataParallelSPMD
from ..distributed import mesh as mesh_mod


class DataParallel(DataParallelSPMD):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        mesh_mod.ensure_default_mesh()
        super().__init__(layers, hcg=None, strategy=None)
