"""Backward engine: topo-ordered tape replay.

Parity: ``BasicEngine::Execute``
(`/root/reference/paddle/fluid/imperative/basic_engine.cc:305`) — queue over
grad nodes with gradient accumulation (GradientAccumulator), and
``partial_grad_engine.cc`` for ``paddle.grad``.  Grad kernels are the same
registry auto-vjp/grad-maker ops the static path uses, executed through the
tracer's jit cache (and re-taped when ``create_graph=True`` — double grad).

Gradients are keyed by tensor IDENTITY (id()), matching the reference's
per-VarBase accumulators — names are only used to wire grad-op descs.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import jax
import jax.numpy as jnp

from ..ops import registry
from . import tracer


def _collect_records(roots) -> List:
    """All tape nodes reachable from roots, newest-first (seq desc)."""
    seen = set()
    stack = [t.grad_node for t in roots if t.grad_node is not None]
    out = []
    while stack:
        rec = stack.pop()
        if id(rec) in seen:
            continue
        seen.add(id(rec))
        out.append(rec)
        if isinstance(rec, (tracer.PyFuncRecord, tracer.PyLayerRecord)):
            ins = rec.inputs_list
        else:
            ins = [t for ts in rec.inputs.values() for t in ts]
        for t in ins:
            if t.grad_node is not None and id(t.grad_node) not in seen:
                stack.append(t.grad_node)
    out.sort(key=lambda r: r.seq, reverse=True)
    return out


def _accum(grad_map: Dict[int, object], tensor, g):
    from .tensor import Tensor

    key = id(tensor)
    old = grad_map.get(key)
    if old is None:
        grad_map[key] = g
    elif isinstance(old, Tensor) or isinstance(g, Tensor):
        # create_graph path: stay on the tape through Tensor arithmetic
        old_t = old if isinstance(old, Tensor) else Tensor(old)
        g_t = g if isinstance(g, Tensor) else Tensor(g)
        grad_map[key] = old_t + g_t
    else:
        grad_map[key] = old + g


def _get_grad(grad_map, tensor):
    return grad_map.get(id(tensor))


def _run_record_backward(
    rec, grad_map: Dict[int, object], create_graph: bool, no_grad_ids: Set[int]
):
    """Compute input grads for one tape node and accumulate."""
    from .tensor import Tensor

    if isinstance(rec, tracer.PyLayerRecord):
        # user-defined backward (autograd/py_layer.py parity): output grads
        # in, input grads out; taped when create_graph for double-grad
        cts = []
        for t in rec.outputs_list:
            g = _get_grad(grad_map, t)
            if g is None:
                g = jnp.zeros(t._array.shape, t._array.dtype)
            if not isinstance(g, Tensor):
                g = Tensor(g, stop_gradient=not create_graph)
            cts.append(g)
        old_grad = tracer.set_grad_enabled(create_graph)
        try:
            grads = rec.cls.backward(rec.ctx, *cts)
        finally:
            tracer.set_grad_enabled(old_grad)
        if not isinstance(grads, (list, tuple)):
            grads = [grads]
        if len(grads) != len(rec.inputs_list):
            raise ValueError(
                f"{rec.cls.__name__}.backward returned {len(grads)} gradients "
                f"for {len(rec.inputs_list)} tensor inputs")
        for t, g in zip(rec.inputs_list, grads):
            if g is None or t.stop_gradient or id(t) in no_grad_ids:
                continue
            _accum(grad_map, t, g if isinstance(g, Tensor) else Tensor(g, stop_gradient=True))
        return

    if isinstance(rec, tracer.PyFuncRecord):
        outs = rec.outputs_list
        if create_graph:
            n_in = len(rec.inputs_list)
            # substitute detached snapshots for any input mutated since trace
            # (value-correct; the mutated tensor's history was re-homed to a
            # clone, so the live object is the wrong node anyway)
            ins_list = [
                t if t._array is arr else Tensor(arr, stop_gradient=True)
                for t, arr in zip(rec.inputs_list, rec.in_arrays)
            ]
            ct_tensors = []
            for t in outs:
                g = _get_grad(grad_map, t)
                if g is None:
                    ct_tensors.append(
                        Tensor(jnp.zeros(t._array.shape, t._array.dtype), stop_gradient=True)
                    )
                elif isinstance(g, Tensor):
                    ct_tensors.append(g)
                else:
                    ct_tensors.append(Tensor(g, stop_gradient=True))

            def _bfn(*arrays, _fn=rec.fn, _n=n_in, _single=rec.single):
                prim, cts = arrays[:_n], arrays[_n:]
                _, vjp_fn = jax.vjp(_fn, *prim)
                return vjp_fn(cts[0] if _single else tuple(cts))

            grads = tracer.trace_fn(_bfn, ins_list + ct_tensors, name="pyfunc_grad")
            if not isinstance(grads, (list, tuple)):
                grads = [grads]
            for t, g in zip(rec.inputs_list, grads):
                if not t.stop_gradient and id(t) not in no_grad_ids and g is not None:
                    _accum(grad_map, t, g)
            return
        arrays = rec.in_arrays  # trace-time snapshots (inplace-safe)
        _, vjp_fn = jax.vjp(rec.fn, *arrays)
        cts = []
        for t in outs:
            g = _get_grad(grad_map, t)
            if g is None:
                g = jnp.zeros(t._array.shape, t._array.dtype)
            elif isinstance(g, Tensor):
                g = g._array
            cts.append(jnp.asarray(g, t._array.dtype))
        in_grads = vjp_fn(cts[0] if rec.single else tuple(cts))
        for t, g in zip(rec.inputs_list, in_grads):
            if not t.stop_gradient and id(t) not in no_grad_ids and g is not None:
                _accum(grad_map, t, g)
        return

    op_def = registry.get_op_def(rec.type)
    grad_descs = registry.make_grad_op_descs(rec, set())
    # name -> Tensor env from the record's tensors (originals — tape intact).
    # Names are unique within one record's op desc by construction.
    env: Dict[str, Tensor] = {}
    for ts in list(rec.inputs.values()) + list(rec.outputs.values()):
        for t in ts:
            env[t.name] = t
    for gop in grad_descs:
        ins_t: Dict[str, List[Tensor]] = {}
        missing_out_grad = False
        for slot, names in gop["inputs"].items():
            vals = []
            for n in names:
                if n.endswith(registry.GRAD_SUFFIX):
                    base = n[: -len(registry.GRAD_SUFFIX)]
                    ref = env.get(base)
                    if ref is None:
                        missing_out_grad = True
                        break
                    g = _get_grad(grad_map, ref)
                    if g is None:
                        # zero-fill missing output grads (parity: the
                        # reference's fill_zeros_like insertion)
                        g = jnp.zeros(ref._array.shape, ref._array.dtype)
                    if not isinstance(g, Tensor):
                        g = Tensor(g, stop_gradient=True)
                    vals.append(g)
                else:
                    vals.append(env[n])
            if missing_out_grad:
                break
            if vals or slot in op_def.list_slots:
                ins_t[slot] = vals
        if missing_out_grad:
            continue
        grad_def = registry.get_op_def(gop["type"])
        attrs = gop["attrs"]
        if create_graph:
            # run the grad kernel through trace_fn so grad-of-grad is taped
            # (vjp-of-vjp; works to arbitrary order)
            order = [(slot, i) for slot, vals in ins_t.items() for i in range(len(vals))]

            def _snap_t(t):
                arr = rec.snap.get(id(t))
                if arr is None or arr is t._array:
                    return t
                return Tensor(arr, stop_gradient=True)

            tensors = [_snap_t(ins_t[s][i]) for s, i in order]
            out_slots = list(gop["outputs"])

            def _fn(*arrays, _order=order, _attrs=attrs, _gd=grad_def, _rng=rec.rng, _os=out_slots):
                kins: Dict[str, List] = {}
                for (s, _), a in zip(_order, arrays):
                    kins.setdefault(s, []).append(a)
                res = registry.run_kernel(_gd, kins, _attrs, rng=_rng)
                return tuple(v for s in _os for v in res.get(s, []))

            flat = tracer.trace_fn(_fn, tensors, name=gop["type"])
            if not isinstance(flat, (list, tuple)):
                flat = [flat]
            outs = {}
            k = 0
            for s in out_slots:
                n_out = len(gop["outputs"][s])
                outs[s] = flat[k : k + n_out]
                k += n_out
        else:
            # read forward tensors through the record's trace-time snapshots
            # so in-place mutation after the op cannot corrupt its grads
            ins = {s: [rec.snap.get(id(t), t._array) for t in vals]
                   for s, vals in ins_t.items()}
            outs = tracer.run_eager_kernel(gop["type"], ins, attrs, rng=rec.rng)
        for slot, names in gop["outputs"].items():
            vals = outs.get(slot, [])
            for n, g in zip(names, vals):
                if not n or g is None:
                    continue
                base = n[: -len(registry.GRAD_SUFFIX)]
                tgt = env.get(base)
                if tgt is None or tgt.stop_gradient or id(tgt) in no_grad_ids:
                    continue
                _accum(grad_map, tgt, g)


def _seed_roots(roots, grad_tensors, grad_map):
    from .tensor import Tensor

    for i, t in enumerate(roots):
        g = None if grad_tensors is None else grad_tensors[i]
        if g is None:
            g = jnp.ones(t._array.shape, t._array.dtype)
        else:
            g = g._array if isinstance(g, Tensor) else jnp.asarray(g)
        _accum(grad_map, t, g)


def run_backward(
    tensors: Sequence,
    grad_tensors: Optional[Sequence] = None,
    retain_graph: bool = False,
):
    """``Tensor.backward()`` entry — writes ``.grad`` on leaf tensors."""
    from .tensor import Tensor

    roots = list(tensors)
    grad_map: Dict[int, object] = {}
    _seed_roots(roots, grad_tensors, grad_map)

    records = _collect_records(roots)
    # leaves = tensors appearing as inputs with no grad_node
    leaves: Dict[int, Tensor] = {}
    for rec in records:
        ins = (
            rec.inputs_list
            if isinstance(rec, (tracer.PyFuncRecord, tracer.PyLayerRecord))
            else [t for ts in rec.inputs.values() for t in ts]
        )
        for t in ins:
            if t.grad_node is None and not t.stop_gradient:
                leaves[id(t)] = t
    for t in roots:
        if t.grad_node is None and not t.stop_gradient:
            leaves[id(t)] = t

    with jax.named_scope("backward"):
        for rec in records:
            _run_record_backward(rec, grad_map, create_graph=False, no_grad_ids=set())

    for key, t in leaves.items():
        g = grad_map.get(key)
        if g is None:
            continue
        g_arr = g._array if isinstance(g, Tensor) else g
        # inplace-mutation clones route their grad to the user's tensor
        while getattr(t, "_alias_of", None) is not None:
            t = t._alias_of
        if t._grad is None:
            t._grad = Tensor(g_arr, stop_gradient=True)
        else:
            t._grad = Tensor(t._grad._array + g_arr, stop_gradient=True)

    if not retain_graph:
        for rec in records:
            _release(rec)
        for t in roots:
            t.grad_node = None


def _release(rec):
    if isinstance(rec, (tracer.PyFuncRecord, tracer.PyLayerRecord)):
        for t in rec.outputs_list:
            t.grad_node = None
        rec.inputs_list = []
        rec.outputs_list = []
        rec.in_arrays = []
    else:
        for ts in rec.outputs.values():
            for t in ts:
                t.grad_node = None
        rec.inputs = {}
        rec.outputs = {}
        rec.snap = {}


def calc_gradient(
    outputs: Sequence,
    inputs: Sequence,
    grad_outputs: Optional[Sequence] = None,
    retain_graph: Optional[bool] = None,
    create_graph: bool = False,
    allow_unused: bool = False,
    no_grad_vars: Optional[Sequence] = None,
):
    """``paddle.grad`` (partial_grad_engine.cc parity).  Returns grads wrt
    ``inputs`` without touching ``.grad``; supports double grad via
    ``create_graph``."""
    from .tensor import Tensor

    roots = list(outputs)
    grad_map: Dict[int, object] = {}
    _seed_roots(roots, grad_outputs, grad_map)
    no_grad_ids = {id(t) for t in (no_grad_vars or ())}

    records = _collect_records(roots)
    if retain_graph is None:
        retain_graph = create_graph
    for rec in records:
        _run_record_backward(rec, grad_map, create_graph=create_graph, no_grad_ids=no_grad_ids)

    result = []
    for t in inputs:
        g = grad_map.get(id(t))
        if g is None:
            if not allow_unused:
                raise ValueError(
                    f"Tensor {t.name} is unreachable from outputs; pass "
                    f"allow_unused=True to get None instead"
                )
            result.append(None)
        elif isinstance(g, Tensor):
            result.append(g)
        else:
            result.append(Tensor(g, stop_gradient=not create_graph))
    if not retain_graph:
        for rec in records:
            _release(rec)
    return result
