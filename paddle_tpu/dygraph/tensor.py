"""Eager Tensor: a named jax.Array with tape-autograd metadata.

Parity: ``VarBase`` (`/root/reference/paddle/fluid/imperative/layer.h:66`) and
its Python monkey-patches (`fluid/dygraph/varbase_patch_methods.py`,
`math_op_patch.py`).  Most ``paddle.*`` tensor functions are attached as
methods by :mod:`paddle_tpu.tensor_api` (math_op_patch parity).

LoD note: the reference's ragged ``LoDTensor`` (``lod_tensor.h:109``) has
no TPU-native equivalent on purpose — XLA requires static shapes, so
variable-length data is carried as padded dense tensors + masks (the
``sequence_mask`` op, masked criterions in ``models/``, and
``paddle.text`` datasets returning per-item arrays the DataLoader pads);
the ``LoDTensorArray`` surface lives in ``tensor_api.create_array`` et al.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import unique_name
from ..framework.dtype import convert_dtype, to_jax_dtype
from . import tracer
from .engine import run_backward


class Tensor:
    def __init__(
        self,
        data: Any,
        dtype: Any = None,
        stop_gradient: bool = True,
        name: Optional[str] = None,
        persistable: bool = False,
    ):
        if isinstance(data, Tensor):
            data = data._array
        if not isinstance(data, jax.Array):
            arr = np.asarray(data)
            if arr.dtype == np.float64 and dtype is None:
                # python float literals land on the configurable default
                # float dtype (paddle.set_default_dtype), not raw float64
                from ..framework.dtype import get_default_dtype, to_numpy_dtype

                arr = arr.astype(to_numpy_dtype(get_default_dtype()))
            data = jnp.asarray(arr)
        if dtype is not None:
            data = data.astype(to_jax_dtype(convert_dtype(dtype)))
        self._array = data
        self.name = name or unique_name.generate("eager_tmp")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.grad_node = None  # producing tape record
        self._grad: Optional["Tensor"] = None

    # -- basic metadata --------------------------------------------------
    @property
    def shape(self):
        return list(self._array.shape)

    @property
    def dtype(self) -> str:
        return str(self._array.dtype)

    @property
    def ndim(self) -> int:
        return self._array.ndim

    def dim(self) -> int:
        return self._array.ndim

    @property
    def size(self) -> int:
        return int(self._array.size)

    def numel(self) -> int:
        return int(self._array.size)

    @property
    def place(self):
        from ..framework.place import _get_current_place

        return _get_current_place()

    @property
    def is_leaf(self) -> bool:
        return self.grad_node is None

    # -- value access ----------------------------------------------------
    def numpy(self) -> np.ndarray:
        return np.asarray(self._array)

    def item(self, *args):
        return self._array.item(*args)

    def tolist(self):
        return np.asarray(self._array).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._array)
        return a.astype(dtype) if dtype is not None else a

    def __float__(self):
        return float(self._array)

    def __int__(self):
        return int(self._array)

    def __index__(self):
        # lets a 1-element integer tensor drive range()/indexing, matching
        # the reference's eager-tensor int conversion
        return int(np.asarray(self._array).reshape(-1)[0])

    def __bool__(self):
        return bool(self._array)

    def __len__(self):
        if self._array.ndim == 0:
            raise TypeError("len() of a 0-D tensor")
        return self._array.shape[0]

    def __repr__(self):
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype}, "
            f"stop_gradient={self.stop_gradient},\n       {np.asarray(self._array)})"
        )

    __str__ = __repr__

    # -- autograd --------------------------------------------------------
    @property
    def grad(self) -> Optional["Tensor"]:
        return self._grad

    @grad.setter
    def grad(self, value):
        self._grad = value if (value is None or isinstance(value, Tensor)) else Tensor(value)

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        run_backward([self], [grad_tensor] if grad_tensor is not None else None, retain_graph)

    def clear_grad(self):
        self._grad = None

    clear_gradient = clear_grad

    def detach(self) -> "Tensor":
        t = Tensor(self._array, stop_gradient=True, name=self.name + ".detached")
        return t

    def detach_(self) -> "Tensor":
        self.grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        return tracer.trace_op("assign", {"X": [self]}, {})["Out"][0]

    # -- mutation (parity: VarBase set_value / optimizer in-place ops) ----
    def _taped_inplace(self, fn, tensor_inputs, name="set_value"):
        """Version-bump an in-place update through the tape: the pre-mutation
        value becomes a clone that carries the old history, the update is a
        recorded op whose OUTPUT is this tensor, so downstream consumers and
        backward both see consistent values (parity: the reference's
        set_value grad op + inplace version counters, which catch exactly the
        silent-wrong-gradient mutation this prevents)."""
        old = Tensor(self._array, stop_gradient=self.stop_gradient)
        prev = self.grad_node
        old.grad_node = prev
        # if self was a LEAF, the clone inherits leaf-ness — route its .grad
        # back to the user-visible tensor at backward time (engine follows
        # _alias_of when writing leaf grads)
        old._alias_of = self

        def _swap(ts):
            return [old if t is self else t for t in ts]

        if prev is not None:
            # the producing record must now emit the CLONE, so its output
            # gradient is read from the pre-mutation value's accumulator
            if isinstance(prev, tracer.PyFuncRecord):
                prev.outputs_list = _swap(prev.outputs_list)
            else:
                for slot, ts in prev.outputs.items():
                    prev.outputs[slot] = _swap(ts)
        # records that consumed the pre-mutation value now consume the clone
        cons = self.__dict__.pop("_consumers", None)
        if cons:
            for wr in cons:
                r = wr()
                if r is None:
                    continue
                if isinstance(r, tracer.PyFuncRecord):
                    r.inputs_list = _swap(r.inputs_list)
                else:
                    for slot, ts in r.inputs.items():
                        r.inputs[slot] = _swap(ts)
            old._consumers = cons
        out = tracer.trace_fn(fn, [old] + list(tensor_inputs), name=name)
        rec = out.grad_node
        if rec is not None:
            rec.outputs_list = [self]
        self._array = out._array
        self.grad_node = rec
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._array
        # Full overwrite: no gradient flows INTO the old value, but consumers
        # that already read the old value must keep their tape intact — a
        # mutated non-leaf intermediate would otherwise silently mis-
        # differentiate (producers get no grad, the intermediate a bogus one;
        # the reference catches this with inplace version counters).
        if tracer.has_grad() and self.grad_node is not None:
            varr = jnp.asarray(value, self._array.dtype).reshape(self._array.shape)
            self._taped_inplace(lambda a: varr, [], name="set_value")
            return
        self.grad_node = None
        self._array = jnp.asarray(value, self._array.dtype).reshape(self._array.shape)

    def copy_(self, other, blocking=True):
        self.set_value(other)
        return self

    def fill_(self, value):
        if tracer.has_grad() and self.grad_node is not None:
            return self._taped_inplace(
                lambda a: jnp.full_like(a, value), [], name="fill_")
        self.grad_node = None
        self._array = jnp.full_like(self._array, value)
        return self

    def zero_(self):
        if tracer.has_grad() and self.grad_node is not None:
            return self._taped_inplace(jnp.zeros_like, [], name="zero_")
        self.grad_node = None
        self._array = jnp.zeros_like(self._array)
        return self

    def scale_(self, scale):
        if tracer.has_grad() and self.grad_node is not None:
            return self._taped_inplace(lambda a: a * scale, [], name="scale_")
        self._array = self._array * scale
        return self

    # -- dtype / shape helpers -------------------------------------------
    def astype(self, dtype) -> "Tensor":
        return tracer.trace_op(
            "cast", {"X": [self]}, {"out_dtype": convert_dtype(dtype)}
        )["Out"][0]

    cast = astype

    def cpu(self):
        return self

    def cuda(self, *a, **k):
        return self

    def pin_memory(self):
        return self

    def to(self, *args, **kwargs):
        for a in args:
            try:
                return self.astype(convert_dtype(a))
            except Exception:
                continue
        return self

    @property
    def T(self):
        axes = list(range(self.ndim))[::-1]
        return tracer.trace_op("transpose2", {"X": [self]}, {"axis": axes})["Out"][0]

    # -- indexing --------------------------------------------------------
    def __getitem__(self, idx):
        idx = _normalize_index(idx)
        return tracer.trace_fn(lambda a: a[idx], [self], name="getitem")

    def __setitem__(self, idx, value):
        idx = _normalize_index(idx)
        vt = value if isinstance(value, Tensor) else None
        # tape the write when this tensor is already an autograd intermediate
        # or the value itself needs grad — otherwise grads would silently be
        # computed against the post-mutation buffer (ADVICE round 1)
        if tracer.has_grad() and (
                self.grad_node is not None
                or (vt is not None and not vt.stop_gradient)):
            if vt is not None:
                self._taped_inplace(
                    lambda a, v: a.at[idx].set(v.astype(a.dtype)), [vt])
            else:
                varr = jnp.asarray(value, self._array.dtype)
                self._taped_inplace(lambda a: a.at[idx].set(varr), [])
            return
        v = vt._array if vt is not None else jnp.asarray(value)
        self._array = self._array.at[idx].set(v.astype(self._array.dtype))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __hash__(self):
        return id(self)

    def __deepcopy__(self, memo):
        """Copy with a FRESH unique name (parity: ParamBase.__deepcopy__) —
        name collisions would corrupt optimizer accumulators keyed by name."""
        cls = self.__class__
        new = cls.__new__(cls)
        memo[id(self)] = new
        new._array = self._array  # jax arrays are immutable
        new.name = unique_name.generate(self.name.split("_")[0] or "eager_tmp")
        new.stop_gradient = self.stop_gradient
        new.persistable = self.persistable
        new.grad_node = None
        new._grad = None
        for k, v in self.__dict__.items():
            if k not in new.__dict__:
                import copy as _copy

                new.__dict__[k] = _copy.deepcopy(v, memo)
        return new


def _normalize_index(idx):
    def conv(i):
        if isinstance(i, Tensor):
            return i._array
        return i

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


def to_tensor(data, dtype=None, place=None, stop_gradient=True) -> Tensor:
    """Parity: ``paddle.to_tensor``."""
    if isinstance(data, Tensor):
        t = Tensor(data._array, dtype=dtype, stop_gradient=stop_gradient)
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
