"""Dygraph autograd context managers + ``paddle.grad``.

Parity: ``fluid/dygraph/base.py`` (``no_grad``:89 area, ``grad``), and
``paddle/autograd/backward_mode.py``.
"""

from __future__ import annotations

import contextlib
import functools

from . import tracer
from .engine import calc_gradient


def is_grad_enabled() -> bool:
    return tracer.has_grad()


def set_grad_enabled(flag: bool):
    @contextlib.contextmanager
    def guard():
        old = tracer.set_grad_enabled(flag)
        try:
            yield
        finally:
            tracer.set_grad_enabled(old)

    return guard()


class no_grad:
    """Usable as decorator or context manager (parity: paddle.no_grad)."""

    def __enter__(self):
        self._old = tracer.set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        tracer.set_grad_enabled(self._old)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._old = tracer.set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        tracer.set_grad_enabled(self._old)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with enable_grad():
                return fn(*args, **kwargs)

        return wrapper


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """Parity: ``paddle.grad`` (autograd/backward_mode.py + partial_grad_engine)."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is not None and not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    return calc_gradient(
        outputs,
        inputs,
        grad_outputs=grad_outputs,
        retain_graph=retain_graph,
        create_graph=create_graph,
        allow_unused=allow_unused,
        no_grad_vars=no_grad_vars,
    )
