"""Dygraph (eager) engine: Tensor over jax.Array + tape autograd.

Capability parity with the reference's imperative engine
(`/root/reference/paddle/fluid/imperative/` — `Tracer::TraceOp` tracer.cc:144,
`VarBase` layer.h:66, `BasicEngine::Execute` basic_engine.cc:305), built
TPU-first: every eager op runs through a jit-cached XLA executable keyed by
(op, attrs, shapes) instead of a per-op CUDA kernel dispatch.
"""

from .tensor import Tensor, to_tensor  # noqa: F401
from .base import no_grad, enable_grad, grad, is_grad_enabled, set_grad_enabled  # noqa: F401
from . import tracer  # noqa: F401
