"""Eager op tracer with tape autograd.

Parity: ``Tracer::TraceOp`` (`/root/reference/paddle/fluid/imperative/tracer.cc:144`)
— runs the kernel, wraps outputs in Tensors, and creates a grad node when any
input requires grad (tracer.cc:231 CreateGradOpNode).  Backward execution
lives in :mod:`engine` (BasicEngine parity).

TPU-first: each (op, attrs) pair is compiled ONCE by XLA via ``jax.jit`` and
re-dispatched by shape — the eager fast path the reference gets from its
generated ``core.ops.*`` C functions, but with kernel fusion inside each op
and no Python→C++ marshalling layer.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..framework import unique_name
from ..ops import registry

_state = threading.local()


def _records() -> List:
    if not hasattr(_state, "records"):
        _state.records = []
    return _state.records


def has_grad() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(flag: bool) -> bool:
    old = has_grad()
    _state.grad_enabled = flag
    return old


# AMP state (parity: imperative/amp_auto_cast.* — tracer-level autocast)
def amp_state():
    return getattr(_state, "amp", None)


def set_amp_state(st) -> None:
    _state.amp = st


class GradRecord:
    """One taped forward op (parity: OpBase + GradOpNode, op_base.h:33,202).

    ``snap`` pins the array VALUES of every involved tensor at trace time
    (free: jax arrays are immutable, this stores references) so later
    in-place mutation of a tensor cannot corrupt backward — the version-
    counter guarantee the reference gets from VarBase inplace_version."""

    __slots__ = ("seq", "type", "inputs", "outputs", "attrs", "rng", "snap",
                 "__weakref__")

    _counter = [0]

    def __init__(self, type: str, inputs, outputs, attrs, rng=None):
        GradRecord._counter[0] += 1
        self.seq = GradRecord._counter[0]
        self.type = type
        self.inputs = inputs  # slot -> list[Tensor]
        self.outputs = outputs  # slot -> list[Tensor]
        self.attrs = attrs
        self.rng = rng
        self.snap = {}
        for ts in list(inputs.values()) + list(outputs.values()):
            for t in ts:
                self.snap[id(t)] = t._array

    # Operator-duck-type for registry.make_grad_op_descs
    def input(self, slot):
        return [t.name for t in self.inputs.get(slot, [])]

    def output(self, slot):
        return [t.name for t in self.outputs.get(slot, [])]


# ---------------------------------------------------------------------------
# jit-cached eager kernel execution
# ---------------------------------------------------------------------------

# ops whose output shape depends on input VALUES — cannot jit eagerly
_NONJIT = frozenset({"where_index", "unique", "masked_select", "bincount", "histogram"})

_jit_cache: Dict[Any, Any] = {}

# When True, kernels run inline (no per-op inner-jit wrapper) so the whole
# traced program is ONE flat jaxpr.  Measured: the inner-jit grouping wins
# on transformers (+4.4 MFU GPT, +5.7 BERT) and is neutral on ResNet-50
# (XLA reaches the same conv+BN+ReLU fusion either way) — so False is the
# right default; the toggle exists for per-workload experiments.
_INLINE_KERNELS = False


def set_inline_kernels(flag: bool) -> bool:
    """Toggle per-op inner-jit wrapping; returns the previous value."""
    global _INLINE_KERNELS
    old = _INLINE_KERNELS
    _INLINE_KERNELS = bool(flag)
    return old


_HAS_ABSTRACT_MESH = hasattr(jax.sharding, "get_abstract_mesh")


def _in_manual_mesh_context(ins, rng) -> bool:
    """True inside a shard_map manual region (axis_types carry Manual).

    Older jax without get_abstract_mesh: fall back to treating ANY traced
    input as manual-context — conservative (loses the inner-jit fusion win
    under plain jit there) but never reuses an inner-jit trace across
    Manual/Auto contexts."""
    if _HAS_ABSTRACT_MESH:
        m = jax.sharding.get_abstract_mesh()
        return any("Manual" in str(t) for t in getattr(m, "axis_types", ()))
    return (any(isinstance(a, jax.core.Tracer)
                for vs in ins.values() for a in vs)
            or isinstance(rng, jax.core.Tracer))


def run_eager_kernel(op_type: str, ins: Dict[str, List[Any]], attrs: Dict[str, Any], rng=None):
    """Execute a registered kernel eagerly through a jit cache."""
    op_def = registry.get_op_def(op_type)
    if op_type in _NONJIT:
        return registry.run_kernel(op_def, ins, attrs, rng=rng)
    # Inside a shard_map MANUAL region (pipeline stages, ring attention):
    # run the kernel inline.  jax >= 0.9 avals carry the mesh axis types, so
    # reusing an inner-jit trace across Manual/Auto contexts is unsound.
    # Under plain jit/grad the inner-jit wrapper is KEPT deliberately: the
    # nested pjit boundaries guide XLA's fusion grouping — measured +4.4 MFU
    # points on the GPT bench vs inlining every op into one flat jaxpr.
    if _INLINE_KERNELS or _in_manual_mesh_context(ins, rng):
        return registry.run_kernel(op_def, ins, attrs, rng=rng)
    try:
        key = (op_type, registry._freeze(attrs))
        hash(key)
    except TypeError:
        return registry.run_kernel(op_def, ins, attrs, rng=rng)
    fn = _jit_cache.get(key)
    if fn is None:
        frozen_attrs = dict(attrs)

        def _call(kins, rng_):
            return registry.run_kernel(op_def, kins, frozen_attrs, rng=rng_)

        fn = jax.jit(_call)
        _jit_cache[key] = fn
    return fn(ins, rng)


# ---------------------------------------------------------------------------
# trace_op: the dygraph dispatch entry
# ---------------------------------------------------------------------------


def _to_array(v):
    from .tensor import Tensor

    if isinstance(v, Tensor):
        return v._array
    if isinstance(v, (jax.Array, np.ndarray)):
        return v
    return np.asarray(v)


def _prof_active() -> bool:
    """True when paddle_tpu.profiler is collecting op-level host events."""
    import sys

    prof = sys.modules.get("paddle_tpu.profiler")
    return prof is not None and prof.is_profiling()


def trace_op(op_type: str, inputs: Dict[str, Any], attrs: Dict[str, Any]):
    """Run one op eagerly; returns slot -> list[Tensor]."""
    from .tensor import Tensor

    op_def = registry.get_op_def(op_type)

    norm: Dict[str, List[Tensor]] = {}
    for slot, vals in inputs.items():
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        ts = []
        for v in vals:
            if v is None:
                continue
            if not isinstance(v, Tensor):
                v = Tensor(_to_array(v), stop_gradient=True)
            ts.append(v)
        if ts or slot in op_def.list_slots:
            norm[slot] = ts

    amp = amp_state()
    if amp is not None:
        from ..amp.auto_cast import maybe_autocast_inputs

        norm, attrs = maybe_autocast_inputs(amp, op_type, norm, attrs)

    ins_arrays = {slot: [t._array for t in ts] for slot, ts in norm.items()}

    rng = None
    if op_def.needs_rng:
        from ..framework.random import next_rng_key

        rng = next_rng_key()

    from ..framework import flags

    if flags.flag("FLAGS_benchmark") or _prof_active():
        from ..profiler import RecordEvent

        with RecordEvent(op_type):
            outs = run_eager_kernel(op_type, ins_arrays, attrs, rng=rng)
            if flags.flag("FLAGS_benchmark"):
                jax.block_until_ready(outs)
    else:
        outs = run_eager_kernel(op_type, ins_arrays, attrs, rng=rng)

    if flags.flag("FLAGS_check_nan_inf"):
        from ..framework.nan_inf import assert_all_finite_eager

        assert_all_finite_eager(op_type, outs)

    requires_grad = (
        has_grad()
        and not op_def.no_grad
        and any(
            not t.stop_gradient
            for slot, ts in norm.items()
            if slot not in op_def.nondiff_slots
            for t in ts
        )
    )

    out_tensors: Dict[str, List[Tensor]] = {}
    for slot, vals in outs.items():
        stop = (not requires_grad) or (slot in op_def.nondiff_out_slots)
        out_tensors[slot] = [Tensor(v, stop_gradient=stop) for v in vals]

    if requires_grad:
        rec = GradRecord(op_type, norm, out_tensors, dict(attrs), rng=rng)
        for slot, ts in out_tensors.items():
            if slot not in op_def.nondiff_out_slots:
                for t in ts:
                    t.grad_node = rec
        _register_consumers(rec, (t for ts in norm.values() for t in ts))
    return out_tensors


def _register_consumers(rec, tensors):
    """Weakly index which records consume each tensor, so taped in-place
    mutation (Tensor._taped_inplace) can re-point prior consumers at the
    pre-mutation clone (the reference's inplace_version bookkeeping role)."""
    import weakref

    wr = weakref.ref(rec)
    for t in tensors:
        lst = t.__dict__.get("_consumers")
        if lst is None:
            lst = t._consumers = []
        lst.append(wr)
        # compact dead refs at power-of-two sizes — keeps long-lived params'
        # consumer lists O(live records), not O(total ops ever)
        n = len(lst)
        if n >= 64 and (n & (n - 1)) == 0:
            lst[:] = [w for w in lst if w() is not None]


def trace_fn(fn, tensors: List, name: str = "pyfunc"):
    """Trace an arbitrary jax-traceable python function of tensor arrays.

    Used for composite surface ops (indexing, custom PyLayer-like closures).
    Gradients come from ``jax.vjp`` of ``fn`` replayed at backward time —
    the dygraph analogue of the registry's auto-vjp grad ops.

    In STATIC mode the closure is registered as a one-off op and appended to
    the program (auto-vjp grads apply), so composite surface functions work
    in both modes.
    """
    from .tensor import Tensor
    from ..framework import program as fw

    if not fw.in_dygraph_mode():
        return _trace_fn_static(fn, tensors, name)

    arrays = [t._array for t in tensors]
    out_arrays = fn(*arrays)
    single = not isinstance(out_arrays, (list, tuple))
    if single:
        out_arrays = [out_arrays]
    requires_grad = has_grad() and any(not t.stop_gradient for t in tensors)
    outs = [Tensor(a, stop_gradient=not requires_grad) for a in out_arrays]
    if requires_grad:
        rec = PyFuncRecord(fn, tensors, outs, single)
        for t in outs:
            t.grad_node = rec
        _register_consumers(rec, tensors)
    return outs[0] if single else outs


_pyfunc_counter = [0]


def _trace_fn_static(fn, tensors, name):
    """Static-mode trace_fn: register the closure as a one-off op type and
    append it to the current block (grads come from the auto-vjp maker)."""
    from ..ops.dispatch import dispatch_static

    _pyfunc_counter[0] += 1
    op_type = f"__pyfunc_{name}_{_pyfunc_counter[0]}"

    def kernel(kins, attrs):
        xs = kins["X"]
        if not isinstance(xs, list):
            xs = [xs]
        out = fn(*xs)
        if isinstance(out, (list, tuple)):
            return {"Out": list(out)}
        return {"Out": [out]}

    od = registry.register_ephemeral(registry.OpDef(
        type=op_type, kernel=kernel, list_slots={"X", "Out"}
    ))
    outs = dispatch_static(op_type, {"X": list(tensors)}, {})
    # the appended Operator keeps the ephemeral OpDef (and its captured
    # closure) alive exactly as long as the Program that owns it
    from ..framework import program as fw

    fw.default_main_program().current_block().ops[-1]._ephemeral_def = od
    res = outs["Out"]
    return res[0] if len(res) == 1 else res


class PyLayerRecord:
    """Tape node for user-defined PyLayer forward/backward pairs
    (parity: imperative/py_layer_fwd.h + autograd/py_layer.py:1).  Shares the
    PyFuncRecord interface (inputs_list/outputs_list) so collection/release
    logic applies; backward calls the user's staticmethod instead of vjp."""

    __slots__ = ("seq", "cls", "ctx", "inputs_list", "outputs_list",
                 "in_arrays", "__weakref__")

    def __init__(self, cls, ctx, inputs_list, outputs_list):
        GradRecord._counter[0] += 1
        self.seq = GradRecord._counter[0]
        self.cls = cls
        self.ctx = ctx
        self.inputs_list = inputs_list
        self.outputs_list = outputs_list
        self.in_arrays = [t._array for t in inputs_list]


class PyFuncRecord:
    """Tape node for trace_fn closures (PyLayer-style custom autograd).
    ``in_arrays`` snapshots input values at trace time (see GradRecord.snap)."""

    __slots__ = ("seq", "fn", "inputs_list", "outputs_list", "single",
                 "in_arrays", "__weakref__")

    def __init__(self, fn, inputs_list, outputs_list, single):
        GradRecord._counter[0] += 1
        self.seq = GradRecord._counter[0]
        self.fn = fn
        self.inputs_list = inputs_list
        self.outputs_list = outputs_list
        self.single = single
        self.in_arrays = [t._array for t in inputs_list]
