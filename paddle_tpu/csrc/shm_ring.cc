// Shared-memory slot ring for DataLoader worker->main bulk transport.
//
// Role parity: /root/reference/paddle/fluid/memory/allocation/mmap_allocator.*
// (shared-memory blocks that carry DataLoader batches between processes) +
// operators/reader/lod_tensor_blocking_queue.h (the bounded queue).  The
// reference pushes LoDTensors through pybind into a blocking queue backed by
// mmap'd refcounted blocks; here a fixed arena of POSIX-shm slots carries the
// raw batch bytes and the (tiny) control messages stay on the existing
// multiprocessing queue — one memcpy into shm in the worker, one out in the
// main process, no pickling of bulk array data and no 64KB-chunked pipe
// writes.
//
// Concurrency model: each slot has an atomic state flag (FREE/BUSY).  A
// producer claims a slot with a CAS loop (any number of producers may share
// an arena), fills it, and sends the slot index out of band; the consumer
// copies out and CAS-releases.  No futexes needed — claiming only contends
// when the arena is full, where the producer backs off with sched_yield.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sched.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0x53524e47;  // "SRNG"
constexpr uint32_t kFree = 0;
constexpr uint32_t kBusy = 1;

struct Header {
  uint32_t magic;
  uint32_t nslots;
  uint64_t slot_bytes;
  // flags[] follows, then page-aligned slot data
};

struct Handle {
  void* base;
  size_t map_bytes;
  Header* hdr;
  std::atomic<uint32_t>* flags;
  uint8_t* data;
};

size_t align_up(size_t v, size_t a) { return (v + a - 1) & ~(a - 1); }

size_t data_offset(uint32_t nslots) {
  return align_up(sizeof(Header) + nslots * sizeof(std::atomic<uint32_t>),
                  4096);
}

Handle* wrap(void* base, size_t bytes) {
  Handle* h = new Handle;
  h->base = base;
  h->map_bytes = bytes;
  h->hdr = static_cast<Header*>(base);
  h->flags = reinterpret_cast<std::atomic<uint32_t>*>(
      static_cast<uint8_t*>(base) + sizeof(Header));
  h->data = static_cast<uint8_t*>(base) + data_offset(h->hdr->nslots);
  return h;
}

}  // namespace

extern "C" {

void* srb_create(const char* name, uint32_t nslots, uint64_t slot_bytes) {
  if (nslots == 0 || slot_bytes == 0) return nullptr;
  shm_unlink(name);  // stale arena from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t bytes = data_offset(nslots) + nslots * slot_bytes;
  if (ftruncate(fd, static_cast<off_t>(bytes)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Header* hdr = static_cast<Header*>(base);
  hdr->nslots = nslots;
  hdr->slot_bytes = slot_bytes;
  auto* flags = reinterpret_cast<std::atomic<uint32_t>*>(
      static_cast<uint8_t*>(base) + sizeof(Header));
  for (uint32_t i = 0; i < nslots; ++i)
    flags[i].store(kFree, std::memory_order_relaxed);
  hdr->magic = kMagic;  // publish last
  return wrap(base, bytes);
}

void* srb_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < (off_t)sizeof(Header)) {
    close(fd);
    return nullptr;
  }
  void* base =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Header* hdr = static_cast<Header*>(base);
  if (hdr->magic != kMagic) {
    munmap(base, st.st_size);
    return nullptr;
  }
  return wrap(base, st.st_size);
}

// Claim a FREE slot (CAS to BUSY); block up to timeout_ms. Returns slot
// index or -1 on timeout.
int srb_acquire(void* vh, int timeout_ms) {
  Handle* h = static_cast<Handle*>(vh);
  struct timespec start, now;
  clock_gettime(CLOCK_MONOTONIC, &start);
  for (;;) {
    for (uint32_t i = 0; i < h->hdr->nslots; ++i) {
      uint32_t expect = kFree;
      if (h->flags[i].compare_exchange_strong(expect, kBusy,
                                              std::memory_order_acquire))
        return static_cast<int>(i);
    }
    clock_gettime(CLOCK_MONOTONIC, &now);
    long ms = (now.tv_sec - start.tv_sec) * 1000 +
              (now.tv_nsec - start.tv_nsec) / 1000000;
    if (timeout_ms >= 0 && ms > timeout_ms) return -1;
    sched_yield();
  }
}

unsigned char* srb_data(void* vh, int slot) {
  Handle* h = static_cast<Handle*>(vh);
  if (slot < 0 || static_cast<uint32_t>(slot) >= h->hdr->nslots)
    return nullptr;
  return h->data + static_cast<uint64_t>(slot) * h->hdr->slot_bytes;
}

unsigned long long srb_slot_bytes(void* vh) {
  return static_cast<Handle*>(vh)->hdr->slot_bytes;
}

unsigned int srb_nslots(void* vh) {
  return static_cast<Handle*>(vh)->hdr->nslots;
}

void srb_release(void* vh, int slot) {
  Handle* h = static_cast<Handle*>(vh);
  if (slot < 0 || static_cast<uint32_t>(slot) >= h->hdr->nslots) return;
  h->flags[slot].store(kFree, std::memory_order_release);
}

void srb_close(void* vh) {
  Handle* h = static_cast<Handle*>(vh);
  munmap(h->base, h->map_bytes);
  delete h;
}

void srb_unlink(const char* name) { shm_unlink(name); }

}  // extern "C"
