"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities (reference: lw921014/Paddle), built on JAX/XLA/Pallas.

Top-level surface parity: ``/root/reference/python/paddle/__init__.py`` —
``paddle.*`` tensor ops, ``paddle.nn``, ``paddle.optimizer``,
``paddle.static``, ``paddle.distributed``, ``paddle.amp``, ``paddle.io``,
``paddle.vision``, ``paddle.jit``, ``paddle.metric``.

Architecture (TPU-first, see SURVEY.md §7):
  static Programs lower to single jitted XLA computations (static/executor);
  dygraph runs a tape over jax Arrays (dygraph/); distributed = mesh axes +
  XLA collectives (distributed/); hot kernels in Pallas (kernels/).
"""

import jax as _jax

# int64/float64 parity with the reference API (ids are int64 in paddle).
# Compute-path dtypes are managed explicitly (float32/bfloat16 everywhere);
# python-float data is still downcast to float32 at Tensor creation.
_jax.config.update("jax_enable_x64", True)

from . import framework  # noqa: F401
from .framework import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
    disable_static,
    enable_static,
    get_device,
    in_dygraph_mode,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)
from .framework.dtype import (  # noqa: F401
    bfloat16,
    bool,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    int8,
    int16,
    int32,
    int64,
    uint8,
)
from . import ops  # noqa: F401  (registers all kernels)
from . import static  # noqa: F401

__version__ = "0.1.0"

# Surface modules import UNCONDITIONALLY — a missing module is a loud
# regression, not a silently absent attribute (round-1 verdict fix).
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import amp  # noqa: F401
from . import metric  # noqa: F401
from . import vision  # noqa: F401
from . import jit  # noqa: F401
from . import distributed  # noqa: F401
from . import autograd  # noqa: F401
from . import hapi  # noqa: F401
from . import text  # noqa: F401
from . import inference  # noqa: F401
from . import incubate  # noqa: F401
from . import onnx  # noqa: F401
from . import profiler  # noqa: F401
from . import dataset  # noqa: F401  (legacy reader-creator surface)
from . import linalg  # noqa: F401
from .framework.flags import get_flags, set_flags  # noqa: F401

from .dygraph.tensor import Tensor, to_tensor  # noqa: F401
from .dygraph.base import (  # noqa: F401
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from .tensor_api import *  # noqa: F401,F403
from .framework.random import get_rng_state, seed, set_rng_state  # noqa: F401

from .io_api import batch, load, save  # noqa: F401
from .hapi import Model  # noqa: F401
from .dygraph.parallel import DataParallel  # noqa: F401
