"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities (reference: lw921014/Paddle), built on JAX/XLA/Pallas.

Top-level surface parity: ``/root/reference/python/paddle/__init__.py`` —
``paddle.*`` tensor ops, ``paddle.nn``, ``paddle.optimizer``,
``paddle.static``, ``paddle.distributed``, ``paddle.amp``, ``paddle.io``,
``paddle.vision``, ``paddle.jit``, ``paddle.metric``.

Architecture (TPU-first, see SURVEY.md §7):
  static Programs lower to single jitted XLA computations (static/executor);
  dygraph runs a tape over jax Arrays (dygraph/); distributed = mesh axes +
  XLA collectives (distributed/); hot kernels in Pallas (kernels/).
"""

import jax as _jax

# int64/float64 parity with the reference API (ids are int64 in paddle).
# Compute-path dtypes are managed explicitly (float32/bfloat16 everywhere);
# python-float data is still downcast to float32 at Tensor creation.
_jax.config.update("jax_enable_x64", True)

from . import framework  # noqa: F401
from .framework import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    TPUPlace,
    XPUPlace,
    disable_static,
    enable_static,
    get_device,
    in_dygraph_mode,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
)
from .framework.dtype import (  # noqa: F401
    bfloat16,
    bool,
    complex64,
    complex128,
    float16,
    float32,
    float64,
    int8,
    int16,
    int32,
    int64,
    uint8,
)
from . import ops  # noqa: F401  (registers all kernels)
from . import static  # noqa: F401

from . import version  # noqa: F401
__version__ = "0.1.0"

# Surface modules import UNCONDITIONALLY — a missing module is a loud
# regression, not a silently absent attribute (round-1 verdict fix).
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import amp  # noqa: F401
from . import metric  # noqa: F401
from . import vision  # noqa: F401
from . import jit  # noqa: F401
from . import distributed  # noqa: F401
from . import autograd  # noqa: F401
from . import hapi  # noqa: F401
from . import text  # noqa: F401
from . import inference  # noqa: F401
from . import incubate  # noqa: F401
from . import onnx  # noqa: F401
from . import profiler  # noqa: F401
from . import dataset  # noqa: F401  (legacy reader-creator surface)
from . import linalg  # noqa: F401
from . import distribution  # noqa: F401
from . import compat  # noqa: F401
from . import sysconfig  # noqa: F401
from . import reader  # noqa: F401
from . import device  # noqa: F401
from . import utils  # noqa: F401

# ``paddle.tensor`` module alias (reference exposes the tensor function
# namespace as a real submodule): make ``import paddle_tpu.tensor`` work
# and point it at tensor_api, where those functions live here.
import sys as _sys

from . import tensor_api as tensor  # noqa: F401

_sys.modules[__name__ + ".tensor"] = tensor
from .framework.flags import get_flags, set_flags  # noqa: F401

from .dygraph.tensor import Tensor, to_tensor  # noqa: F401
from .dygraph.base import (  # noqa: F401
    enable_grad,
    grad,
    is_grad_enabled,
    no_grad,
    set_grad_enabled,
)
from .tensor_api import *  # noqa: F401,F403
from .framework.random import get_rng_state, seed, set_rng_state  # noqa: F401

from .io_api import batch, load, save  # noqa: F401
from .hapi import Model  # noqa: F401
from .dygraph.parallel import DataParallel  # noqa: F401

# -- top-level surface completeness (reference python/paddle/__init__.py) --
from . import hub  # noqa: F401
from . import fluid  # noqa: F401  (v2.1 compat namespace; reference
#                     python/paddle/__init__.py re-exports fluid too)
from .nn import ParamAttr  # noqa: F401
from .framework.dtype import DataType as dtype  # noqa: F401
from .framework.place import NPUPlace  # noqa: F401
from .hapi import callbacks  # noqa: F401

VarBase = Tensor  # legacy alias (pre-2.2 name for the eager tensor)

in_dynamic_mode = in_dygraph_mode
enable_dygraph = disable_static
disable_dygraph = enable_static

# CUDA-named RNG surface maps onto the device-agnostic seed chain
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def get_cudnn_version():
    """No cuDNN on TPU (reference returns None when not compiled with it)."""
    return None


def is_compiled_with_npu():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def set_default_dtype(d):
    """Parity: paddle.set_default_dtype — governs float-literal creation."""
    from .framework import dtype as _dt

    _dt.set_default_dtype(d)


def get_default_dtype():
    from .framework import dtype as _dt

    return _dt.get_default_dtype()


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Parity: paddle.set_printoptions — numpy-backed display options."""
    import numpy as _np

    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def summary(net, input_size=None, dtypes=None):
    """Parity: paddle.summary — layer/param table for a Layer."""
    import numpy as _np

    total = 0
    trainable = 0
    lines = ["-" * 64,
             f"{'Layer (type)':<38}{'Param shape':<16}{'Param #':>10}",
             "=" * 64]
    for name, p in net.named_parameters():
        n = int(_np.prod(p.shape))
        total += n
        if not p.stop_gradient:
            trainable += n
        lines.append(f"{name:<38}{str(tuple(p.shape)):<16}{n:>10,}")
    lines += ["=" * 64, f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}", "-" * 64]
    print("\n".join(lines))
    return {"total_params": total, "trainable_params": trainable}


def check_shape(shape):
    """Parity: paddle.check_shape — validate a shape list."""
    for s in shape:
        if s is not None and not isinstance(s, (int,)):
            raise TypeError(f"shape entries must be ints/None, got {s!r}")


def monkey_patch_math_varbase():
    """No-op: operator overloads are built into Tensor here (the reference
    patches VarBase at import time; exported for import parity)."""


def monkey_patch_variable():
    """No-op: Variable operator overloads are built in (import parity)."""
