"""``paddle.autograd`` — PyLayer custom autograd + backward-mode entry.

Parity: ``/root/reference/python/paddle/autograd/py_layer.py:1`` (PyLayer,
PyLayerContext, LayerMeta/apply machinery over ``core.pylayer_apply``) and
``autograd/backward_mode.py`` (``paddle.autograd.backward``).

TPU-first: instead of a C++ ``py_layer`` op (imperative/py_layer_fwd.h), the
custom pair is a :class:`~paddle_tpu.dygraph.tracer.PyLayerRecord` tape node
— the backward engine calls the user's ``backward`` staticmethod directly,
re-taping it when ``create_graph`` so double-grad through a PyLayer works.
"""

from __future__ import annotations

from ..dygraph import tracer
from ..dygraph.engine import run_backward, calc_gradient
from ..dygraph.tensor import Tensor

__all__ = ["PyLayer", "PyLayerContext", "backward", "grad"]


class PyLayerContext:
    """Context passed as the first argument of forward/backward
    (py_layer.py:21).  ``save_for_backward``/``saved_tensor`` move tensors
    across; arbitrary attributes may be attached (``ctx.foo = ...``)."""

    def __init__(self):
        self.container = None

    def save_for_backward(self, *tensors):
        self.container = tensors

    def saved_tensor(self):
        return self.container


class LayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=LayerMeta):
    """Custom autograd block: subclass with ``forward(ctx, *args)`` and
    ``backward(ctx, *output_grads)`` staticmethods, run via ``apply``
    (py_layer.py:189 contract: #backward inputs == #forward tensor outputs,
    #backward outputs == #forward tensor inputs)."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [
            a for a in list(args) + list(kwargs.values()) if isinstance(a, Tensor)
        ]
        requires_grad = tracer.has_grad() and any(
            not t.stop_gradient for t in tensor_inputs
        )
        old = tracer.set_grad_enabled(False)
        try:
            outputs = cls.forward(ctx, *args, **kwargs)
        finally:
            tracer.set_grad_enabled(old)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        tensor_outs = [t for t in outs if isinstance(t, Tensor)]
        if requires_grad and tensor_outs:
            rec = tracer.PyLayerRecord(cls, ctx, tensor_inputs, tensor_outs)
            for t in tensor_outs:
                t.stop_gradient = False
                t.grad_node = rec
        return outputs


def backward(tensors, grad_tensors=None, retain_graph=False):
    """``paddle.autograd.backward`` (backward_mode.py:20): accumulate grads
    of ``tensors`` into their leaves' ``.grad``."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    tensors = list(tensors)
    assert len({id(t) for t in tensors}) == len(tensors), (
        "tensors must not contain the same tensor twice")
    if grad_tensors is not None:
        if isinstance(grad_tensors, Tensor):
            grad_tensors = [grad_tensors]
        grad_tensors = list(grad_tensors)
        assert len(grad_tensors) == len(tensors), (
            "grad_tensors must match tensors in length")
    run_backward(tensors, grad_tensors, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """``paddle.grad`` — partial_grad_engine.cc parity (re-export)."""
    single_out = isinstance(outputs, Tensor)
    single_in = isinstance(inputs, Tensor)
    outs = [outputs] if single_out else list(outputs)
    ins = [inputs] if single_in else list(inputs)
    if grad_outputs is not None and isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    res = calc_gradient(
        outs, ins, grad_outputs, retain_graph=retain_graph,
        create_graph=create_graph, allow_unused=allow_unused,
        no_grad_vars=no_grad_vars,
    )
    return res
