"""Weight-decay regularizers.

Parity: ``/root/reference/python/paddle/fluid/regularizer.py`` (L1Decay /
L2Decay appended as ops into the grad stream).  Here a regularizer is a
callable ``(param, grad) -> grad`` built from dispatch ops, so it works in
both modes (static: appends ops; dygraph: eager).
"""

from __future__ import annotations

from . import tensor_api as T

__all__ = ["L1Decay", "L2Decay"]


class L1Decay:
    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    def __call__(self, param, grad):
        return T.add(grad, T.scale(T.sign(param), self.coeff))

    def __str__(self):
        return f"L1Decay({self.coeff})"


class L2Decay:
    def __init__(self, coeff: float = 0.0):
        self.coeff = float(coeff)

    def __call__(self, param, grad):
        return T.add(grad, T.scale(param, self.coeff))

    def __str__(self):
        return f"L2Decay({self.coeff})"
