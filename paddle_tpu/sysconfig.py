"""Paths to the package's headers/libraries — ``paddle.sysconfig``.

Role parity: ``/root/reference/python/paddle/sysconfig.py`` (get_include:20,
get_lib:37).  Here the include dir carries the custom-op C ABI header
(``extension/paddle_tpu_ext.h``) and the lib dir holds runtime-built
shared objects (e.g. the DataLoader shm ring).
"""

import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory containing the C/C++ headers (the custom-op ABI)."""
    root = os.path.abspath(os.path.dirname(__file__))
    return os.path.join(root, "extension")


def get_lib():
    """Directory containing runtime-built shared libraries (the
    content-hash build cache used by ``utils.cpp_extension``)."""
    import tempfile

    return os.path.join(tempfile.gettempdir(), "paddle_tpu_extensions")
