"""Version info — ``paddle.version`` (reference generates this file at
build time; ``python/paddle/__init__.py:15`` imports full_version)."""

full_version = "2.1.0+tpu.0.1.0"
major = "2"
minor = "1"
patch = "0"
rc = "0"
istaged = True
commit = "tpu-native"
with_mkl = "OFF"
cuda_version = "False"
cudnn_version = "False"


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"commit: {commit}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
