"""Env-driven automatic checkpointing for elastic jobs.

Parity: ``/root/reference/python/paddle/fluid/incubate/checkpoint/
auto_checkpoint.py`` (``AutoCheckpointChecker``:71, ``train_epoch_range``)
— a relaunched job (same ``PADDLE_JOB_ID``) resumes at epoch granularity
from periodic snapshots, keyed entirely by environment so user code needs
no changes beyond wrapping the epoch loop::

    for epoch in acp.train_epoch_range(10):
        train_one_epoch(...)

Environment protocol (reference names):
  * ``PADDLE_RUNNING_ENV=PADDLE_EDL_AUTO_CHECKPOINT`` — enables the system;
  * ``PADDLE_JOB_ID`` — stable job identity across relaunches;
  * ``PADDLE_EDL_HDFS_CHECKPOINT_PATH`` — checkpoint directory (served by
    ``fleet.utils.fs`` — LocalFS here, HDFSClient where configured);
  * ``PADDLE_EDL_SAVE_CHECKPOINT_INTER`` — min seconds between snapshots.

TPU-native state capture: instead of hooking ``Executor.run`` per program
(the reference's approach), a snapshot saves (a) every persistable array
in the global scope (the static-graph state the reference captures) and
(b) any (layer / optimizer / LRScheduler) objects registered with
``register`` (the dygraph state).  Under the single-controller SPMD model
only process 0 writes.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

__all__ = ["AutoCheckpointChecker", "train_epoch_range", "register",
           "_get_train_epoch_range"]


class AutoCheckpointChecker:
    """Reads the env protocol (reference AutoCheckpointChecker:71)."""

    def __init__(self):
        self.running_env = os.environ.get("PADDLE_RUNNING_ENV", "")
        self.job_id = os.environ.get("PADDLE_JOB_ID", "")
        self.ckpt_path = os.environ.get("PADDLE_EDL_HDFS_CHECKPOINT_PATH", "")
        try:
            self.save_checkpoint_inter = int(
                os.environ.get("PADDLE_EDL_SAVE_CHECKPOINT_INTER", "900"))
        except ValueError:
            self.save_checkpoint_inter = 900
        self.trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))

    def valid(self) -> bool:
        return (self.running_env == "PADDLE_EDL_AUTO_CHECKPOINT"
                and bool(self.job_id) and bool(self.ckpt_path))

    @property
    def job_dir(self) -> str:
        return os.path.join(self.ckpt_path, f"job_{self.job_id}")


_registered: List[tuple] = []
_current_range: Optional["TrainEpochRange"] = None


def register(*objects):
    """Attach dygraph state (Layers, Optimizers, LRSchedulers — anything
    with state_dict/set_state_dict) to the auto-checkpoint snapshots."""
    _registered.extend(objects)


def _get_train_epoch_range():
    return _current_range


class TrainEpochRange:
    def __init__(self, max_epoch_num: int, name: str = "train",
                 checker: Optional[AutoCheckpointChecker] = None,
                 save_checkpoint_inter: Optional[int] = None):
        self._checker = checker or AutoCheckpointChecker()
        self.name = name
        self.max_epoch_num = max_epoch_num
        self._inter = (self._checker.save_checkpoint_inter
                       if save_checkpoint_inter is None
                       else save_checkpoint_inter)
        self._last_save = 0.0
        self.restored_from = None
        self._start = 0
        if self._checker.valid():
            self._start = self._restore()

    # -- persistence ------------------------------------------------------
    def _meta_path(self):
        return os.path.join(self._checker.job_dir, f"{self.name}.meta.json")

    def _state_path(self, epoch):
        return os.path.join(self._checker.job_dir,
                            f"{self.name}.epoch{epoch}")

    def _restore(self) -> int:
        from ..io_api import load

        meta_path = self._meta_path()
        if not os.path.exists(meta_path):
            return 0
        with open(meta_path) as f:
            meta = json.load(f)
        epoch = int(meta.get("epoch_no", -1))
        if epoch < 0:
            return 0
        state = load(self._state_path(epoch))
        from ..framework.scope import global_scope

        scope = global_scope()
        for name, arr in state.get("scope", {}).items():
            scope.set(name, arr)
        objects = state.get("objects", [])
        if len(objects) != len(_registered):
            # positional restore requires the relaunch to have registered
            # the same objects in the same order; a silent partial restore
            # would load state into the wrong object
            raise RuntimeError(
                f"auto_checkpoint: snapshot holds state for {len(objects)} "
                f"registered object(s) but {len(_registered)} are "
                f"registered now — register() the same objects in the same "
                f"order before train_epoch_range()")
        for obj, sd in zip(_registered, objects):
            obj.set_state_dict(sd)
        self.restored_from = epoch
        return epoch + 1

    def save(self, epoch: int):
        """Snapshot scope persistables + registered objects (trainer 0)."""
        import numpy as np

        from ..io_api import save
        from ..framework.scope import global_scope

        if self._checker.trainer_id != 0:
            return
        os.makedirs(self._checker.job_dir, exist_ok=True)
        scope = global_scope()
        scope_state = {}
        for name in scope.local_names():
            arr = scope.find_var(name)
            if arr is not None:
                scope_state[name] = np.asarray(arr)
        objects = [o.state_dict() for o in _registered]
        save({"scope": scope_state, "objects": objects},
             self._state_path(epoch))
        prev = None
        if os.path.exists(self._meta_path()):
            with open(self._meta_path()) as f:
                prev = json.load(f).get("epoch_no")
        tmp = self._meta_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"epoch_no": epoch, "name": self.name,
                       "time": time.time()}, f)
        os.replace(tmp, self._meta_path())  # meta commit is the atomic step
        if prev is not None and prev != epoch:
            # superseded snapshot: delete AFTER the meta commit so a crash
            # between the two steps still leaves one loadable checkpoint
            try:
                os.remove(self._state_path(prev))
            except OSError:
                pass
        self._last_save = time.time()

    # -- iteration --------------------------------------------------------
    def __iter__(self):
        global _current_range
        _current_range = self
        try:
            for epoch in range(self._start, self.max_epoch_num):
                yield epoch
                if (self._checker.valid()
                        and (time.time() - self._last_save >= self._inter
                             or epoch == self.max_epoch_num - 1)):
                    self.save(epoch)
        finally:
            _current_range = None


def train_epoch_range(max_epoch_num: int,
                      save_checkpoint_inter: Optional[int] = None):
    """Reference surface: iterate epochs with automatic resume+snapshot.
    When the env protocol is absent this is a plain ``range``-like loop."""
    return TrainEpochRange(max_epoch_num,
                           save_checkpoint_inter=save_checkpoint_inter)
