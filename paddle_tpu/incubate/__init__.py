"""``paddle.incubate`` — wrapper optimizers.

Parity: ``/root/reference/python/paddle/fluid/optimizer.py``:
ExponentialMovingAverage (:3883), ModelAverage (:3574), LookaheadOptimizer
(:6088), GradientMergeOptimizer (:6260) — re-built for the dygraph tape
(the reference versions rewrite static programs; here they are array-state
wrappers over the eager optimizer, the paddle 2.x incubate flavor).
"""

from __future__ import annotations

import contextlib
from typing import List, Optional

import jax.numpy as jnp
import numpy as np

from . import asp  # noqa: F401
from . import auto_checkpoint  # noqa: F401
from . import quant  # noqa: F401

__all__ = [
    "ExponentialMovingAverage", "LookAhead", "ModelAverage",
    "GradientMergeOptimizer", "asp", "quant",
]


def _unique(params):
    seen, out = set(), []
    for p in params:
        if id(p) not in seen:
            seen.add(id(p))
            out.append(p)
    return out


class ExponentialMovingAverage:
    """shadow = decay * shadow + (1 - decay) * param after each update.

    Parity: fluid/optimizer.py:3883 — ``update()`` after every optimizer
    step; ``apply()`` context swaps the EMA weights in for evaluation and
    restores on exit (or call ``restore()`` manually)."""

    def __init__(self, parameters, decay: float = 0.999,
                 thres_steps: Optional[int] = None, name=None):
        self._params = _unique(parameters)
        self._decay = float(decay)
        self._thres_steps = thres_steps
        self._step = 0
        self._shadow = {id(p): p._array.astype(jnp.float32)
                        for p in self._params}
        self._backup = None

    def update(self):
        self._step += 1
        decay = self._decay
        if self._thres_steps is not None:
            # dynamic decay warmup: min(decay, (1+t)/(10+t))
            decay = min(decay, (1.0 + self._step) / (10.0 + self._step))
        for p in self._params:
            sh = self._shadow[id(p)]
            self._shadow[id(p)] = (decay * sh
                                   + (1.0 - decay) * p._array.astype(jnp.float32))

    @contextlib.contextmanager
    def apply(self, need_restore: bool = True):
        self._backup = {id(p): p._array for p in self._params}
        for p in self._params:
            p._array = self._shadow[id(p)].astype(p._array.dtype)
        try:
            yield self
        finally:
            if need_restore:
                self.restore()

    def restore(self):
        if self._backup is None:
            return
        for p in self._params:
            p._array = self._backup[id(p)]
        self._backup = None


class LookAhead:
    """k fast steps, then slow += alpha * (fast - slow); fast = slow.

    Parity: fluid/optimizer.py:6088 LookaheadOptimizer (paddle 2.x
    ``paddle.incubate.LookAhead`` wrapper form)."""

    def __init__(self, inner_optimizer, alpha: float = 0.5, k: int = 5,
                 name=None):
        assert inner_optimizer is not None
        assert 0.0 <= alpha <= 1.0
        assert k >= 1
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._params = _unique(inner_optimizer._parameter_list or [])
        self._slow = {id(p): p._array for p in self._params}
        self._step = 0

    def step(self):
        self.inner_optimizer.step()
        self._step += 1
        if self._step % self.k == 0:
            for p in self._params:
                slow = self._slow[id(p)].astype(jnp.float32)
                fast = p._array.astype(jnp.float32)
                new_slow = slow + self.alpha * (fast - slow)
                self._slow[id(p)] = new_slow.astype(p._array.dtype)
                p._array = self._slow[id(p)]

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        self.clear_grad()

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def state_dict(self):
        return self.inner_optimizer.state_dict()

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)


class ModelAverage:
    """Running average of parameters over a sliding window.

    Parity: fluid/optimizer.py:3574 ModelAverage /
    ``paddle.incubate.ModelAverage`` — ``step()`` accumulates; ``apply()``
    swaps the averaged weights in for evaluation; ``restore()`` undoes."""

    def __init__(self, average_window_rate: float, parameters=None,
                 min_average_window: int = 10000,
                 max_average_window: int = 10000, name=None):
        self._params = _unique(parameters or [])
        self._rate = average_window_rate
        self._min_w = min_average_window
        self._max_w = max_average_window
        self._sum = {id(p): jnp.zeros_like(p._array, dtype=jnp.float32)
                     for p in self._params}
        self._count = 0
        self._backup = None

    def step(self):
        self._count += 1
        window = max(self._min_w,
                     min(self._max_w, int(self._count * self._rate) or 1))
        for p in self._params:
            s = self._sum[id(p)] + p._array.astype(jnp.float32)
            # keep the sum bounded to the window by exponential forgetting
            if self._count > window:
                s = s * (window / (window + 1.0))
            self._sum[id(p)] = s

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore: bool = True):
        self._backup = {id(p): p._array for p in self._params}
        n = max(min(self._count,
                    max(self._min_w, int(self._count * self._rate) or 1)), 1)
        for p in self._params:
            p._array = (self._sum[id(p)] / n).astype(p._array.dtype)
        try:
            yield self
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._array = self._backup[id(p)]
        self._backup = None


class GradientMergeOptimizer:
    """Accumulate gradients for k_steps micro-batches, then apply one real
    optimizer step with the averaged gradient.

    Parity: fluid/optimizer.py:6260 GradientMergeOptimizer — the
    large-effective-batch path when memory caps the per-step batch."""

    def __init__(self, inner_optimizer, k_steps: int = 1, avg: bool = True):
        self.inner_optimizer = inner_optimizer
        self.k_steps = max(int(k_steps), 1)
        self.avg = avg
        self._params = _unique(inner_optimizer._parameter_list or [])
        self._acc = {}
        self._step = 0

    def step(self):
        self._step += 1
        for p in self._params:
            if p.grad is None:
                continue
            g = p.grad._array.astype(jnp.float32)
            self._acc[id(p)] = self._acc.get(id(p), 0.0) + g
        if self._step % self.k_steps == 0:
            scale = 1.0 / self.k_steps if self.avg else 1.0
            for p in self._params:
                if id(p) in self._acc:
                    p.grad._array = (self._acc[id(p)] * scale).astype(
                        p.grad._array.dtype)
            self.inner_optimizer.step()
            self._acc = {}
            self.inner_optimizer.clear_grad()
        else:
            # grads consumed into the accumulator; clear for the next micro
            self.inner_optimizer.clear_grad()

    def clear_grad(self):
        pass  # handled inside step()

    clear_gradients = clear_grad

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)


# -- fused softmax+mask (incubate/operators/softmax_mask_fuse.py) -----------


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) in one fused op (fused_softmax_mask role —
    XLA fuses the add into the softmax on TPU; the op exists so traced
    programs carry the fused node like the reference's)."""
    from ..ops.dispatch import dispatch

    return dispatch("fused_softmax_mask", {"X": [x], "Mask": [mask]},
                    {})["Out"][0]


def softmax_mask_fuse_upper_triangle(x, name=None):
    """softmax with the upper-triangle (future positions) masked — the
    causal-attention fused op (fused_softmax_mask_upper_triangle role)."""
    from ..ops.dispatch import dispatch

    return dispatch("fused_softmax_mask_upper_triangle", {"X": [x]},
                    {})["Out"][0]


# reference exposes the auto-checkpoint package as incubate.checkpoint
from . import auto_checkpoint as checkpoint  # noqa: E402,F401

__all__ += ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle",
            "checkpoint"]
