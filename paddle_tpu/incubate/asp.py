"""ASP — automatic 2:4 structured sparsity.

Parity: ``/root/reference/python/paddle/fluid/contrib/sparsity/`` (asp.py:
``prune_model``, ``decorate``; utils.py: ``get_mask_1d``,
``check_sparsity``, ``calculate_density``).  TPU note: v5e MXUs do not
accelerate 2:4 sparsity the way sparse tensor cores do, so here ASP is a
MODEL-QUALITY tool (train-time structured pruning with masks maintained
across optimizer steps); the mask math and API match the reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

__all__ = [
    "calculate_density", "check_sparsity", "get_mask_1d", "prune_model",
    "decorate", "reset_excluded_layers", "set_excluded_layers", "ASPHelper",
]

_EXCLUDED: set = set()


def calculate_density(x) -> float:
    """Parity: sparsity/utils.py calculate_density."""
    a = np.asarray(x)
    return float(np.count_nonzero(a)) / a.size


def get_mask_1d(mat: np.ndarray, n: int = 2, m: int = 4) -> np.ndarray:
    """n:m mask along the last dim: keep the n largest |values| of every
    group of m (parity: utils.py get_mask_1d)."""
    a = np.asarray(mat)
    shape = a.shape
    assert shape[-1] % m == 0, f"last dim {shape[-1]} not divisible by {m}"
    g = np.abs(a).reshape(-1, m)
    order = np.argsort(g, axis=1)  # ascending
    mask = np.zeros_like(g, dtype=bool)
    np.put_along_axis(mask, order[:, m - n:], True, axis=1)
    return mask.reshape(shape)


def check_sparsity(mat, n: int = 2, m: int = 4) -> bool:
    """True when every m-group along the last dim has <= n non-zeros."""
    a = np.asarray(mat)
    if a.shape[-1] % m:
        return False
    g = (np.abs(a.reshape(-1, m)) > 0).sum(axis=1)
    return bool((g <= n).all())


def set_excluded_layers(param_names: List[str], main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def _prunable(p, m: int = 4) -> bool:
    return (p._array.ndim == 2 and p.shape[-1] % m == 0
            and not getattr(p, "stop_gradient", False)
            and p.name not in _EXCLUDED)


class ASPHelper:
    """Holds the masks for a set of parameters (asp.py ASPHelper role)."""

    def __init__(self):
        import weakref

        # weak refs: a pruned-then-discarded model must not stay alive (or
        # keep being re-masked) through this registry
        self._masks: Dict[int, jnp.ndarray] = {}
        self._params: "weakref.WeakValueDictionary[int, object]" = (
            weakref.WeakValueDictionary())

    def prune(self, params, n=2, m=4):
        for p in params:
            if not _prunable(p, m):
                continue
            mask = jnp.asarray(get_mask_1d(np.asarray(p._array), n, m),
                               dtype=p._array.dtype)
            p._array = p._array * mask
            self._masks[id(p)] = mask  # re-prune replaces, never duplicates
            self._params[id(p)] = p
        return self

    def apply_masks(self):
        dead = [k for k in self._masks if k not in self._params]
        for k in dead:
            del self._masks[k]
        for k, p in list(self._params.items()):
            p._array = p._array * self._masks[k]

    def reset(self):
        self._masks.clear()
        self._params = type(self._params)()


_helper = ASPHelper()


def prune_model(model, n: int = 2, m: int = 4, mask_algo: str = "mask_1d",
                with_mask: bool = True) -> ASPHelper:
    """Parity: asp.py prune_model — mask every prunable 2-D weight of the
    Layer (or parameter list) to n:m sparsity.  Only the 1-D mask family
    is implemented; unknown algorithms raise instead of silently running
    mask_1d.  ``with_mask=False`` prunes once without registering masks
    (so ``decorate`` will not keep re-applying them)."""
    if mask_algo not in ("mask_1d",):
        raise NotImplementedError(
            f"mask_algo {mask_algo!r} not implemented (supported: mask_1d); "
            f"the reference's mask_2d_greedy/best search is CUDA-sparse-"
            f"tensor-core oriented")
    params = list(model.parameters() if hasattr(model, "parameters")
                  else model)
    if not with_mask:
        tmp = ASPHelper()
        tmp.prune(params, n, m)
        return tmp
    return _helper.prune(params, n, m)


class DecoratedASPOptimizer:
    """Re-applies the sparsity masks after every optimizer step (parity:
    asp.py ASPHelper._insert_sparse_mask_ops / OptimizerWithSparsityGuarantee)."""

    def __init__(self, optimizer, helper: Optional[ASPHelper] = None):
        self._inner = optimizer
        self._helper = helper or _helper

    def step(self):
        self._inner.step()
        self._helper.apply_masks()

    def minimize(self, loss, **kw):
        out = self._inner.minimize(loss, **kw)
        self._helper.apply_masks()
        return out

    def clear_grad(self):
        self._inner.clear_grad()

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self._inner, name)


def decorate(optimizer) -> DecoratedASPOptimizer:
    return DecoratedASPOptimizer(optimizer)
