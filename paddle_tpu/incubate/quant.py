"""Imperative quantization-aware training (QAT).

Parity: ``/root/reference/python/paddle/fluid/contrib/slim/quantization/
imperative/qat.py`` (``ImperativeQuantAware``: wrap Linear/Conv2D with
fake-quant on weights + activations; straight-through backward).

TPU note: v5e serving gains come from bf16/int8 matmuls — QAT here trains
the model THROUGH int8 rounding (fake quant in fp) so an int8 deployment
(via the Predictor's precision knobs or an external converter) keeps
accuracy; the fake-quant kernels live in ``ops/quant_ops.py``.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .. import nn

__all__ = ["ImperativeQuantAware", "QuantizedLinear", "QuantizedConv2D"]


def _fake_quant(x, kind: str, bits: int, layer, state_name: str,
                moving_rate: float = 0.9):
    from ..dygraph import tracer
    from ..dygraph.tensor import Tensor

    if kind == "channel":
        outs = tracer.trace_op(
            "fake_channel_wise_quantize_dequantize_abs_max", {"X": [x]},
            {"bit_length": bits, "quant_axis": x.ndim - 1})
        return outs["Out"][0]
    if kind == "abs_max":
        outs = tracer.trace_op(
            "fake_quantize_dequantize_abs_max", {"X": [x]},
            {"bit_length": bits})
        return outs["Out"][0]
    # moving-average activation quant: the scale is a persistable BUFFER so
    # the trained value round-trips through state_dict (a plain attribute
    # would silently drop it on save/load)
    scale = getattr(layer, state_name, None)
    if scale is None:
        scale = Tensor(np.asarray([float(np.abs(np.asarray(x._array)).max()
                                         or 1.0)], "float32"),
                       stop_gradient=True)
        layer.register_buffer(state_name, scale)
    outs = tracer.trace_op(
        "fake_quantize_dequantize_moving_average_abs_max",
        {"X": [x], "InScale": [scale]},
        {"bit_length": bits, "moving_rate": moving_rate,
         "is_test": not layer.training})
    if layer.training:
        scale._array = outs["OutScale"][0]._array
    return outs["Out"][0]


class QuantizedLinear(nn.Layer):
    """Linear with channel-wise weight fake-quant + moving-avg activation
    fake-quant (qat.py QuantizedLinear role)."""

    def __init__(self, inner, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = inner
        self._wbits, self._abits = weight_bits, activation_bits
        self._rate = moving_rate

    def forward(self, x):
        from ..nn import functional as F
        from .. import tensor_api as T

        xq = _fake_quant(x, "moving", self._abits, self, "_in_scale",
                         self._rate)
        wq = _fake_quant(self.inner.weight, "channel", self._wbits, self,
                         "_w_scale")
        out = T.matmul(xq, wq)
        if self.inner.bias is not None:
            out = T.add(out, self.inner.bias)
        return out


class QuantizedConv2D(nn.Layer):
    def __init__(self, inner, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = inner
        self._wbits, self._abits = weight_bits, activation_bits
        self._rate = moving_rate

    def forward(self, x):
        from ..dygraph import tracer

        xq = _fake_quant(x, "moving", self._abits, self, "_in_scale",
                         self._rate)
        wq = _fake_quant(self.inner.weight, "abs_max", self._wbits, self,
                         "_w_scale")
        pad = self.inner._padding
        pad = [pad, pad] if isinstance(pad, int) else list(pad)
        attrs = {"strides": list(self.inner._stride),
                 "paddings": pad,
                 "dilations": list(self.inner._dilation),
                 "groups": self.inner._groups}
        outs = tracer.trace_op("conv2d", {"Input": [xq], "Filter": [wq]},
                               attrs)
        out = outs["Output"][0]
        if self.inner.bias is not None:
            from .. import tensor_api as T

            b = self.inner.bias
            out = T.add(out, T.reshape(b, [1, -1, 1, 1]))
        return out


_WRAPPERS = {"Linear": QuantizedLinear, "Conv2D": QuantizedConv2D}


class ImperativeQuantAware:
    """Parity: qat.py ImperativeQuantAware — in-place layer replacement."""

    def __init__(self, quantizable_layer_type: List[str] = ("Linear",
                                                            "Conv2D"),
                 weight_bits: int = 8, activation_bits: int = 8,
                 moving_rate: float = 0.9, **kw):
        self._types = tuple(quantizable_layer_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate

    def quantize(self, model: nn.Layer) -> nn.Layer:
        """Replace every quantizable sublayer with its fake-quant wrapper
        (in place, like the reference)."""
        for name, sub in list(model._sub_layers.items()):
            cls = type(sub).__name__
            if cls in self._types and cls in _WRAPPERS:
                model._sub_layers[name] = _WRAPPERS[cls](
                    sub, self._wbits, self._abits, self._rate)
            else:
                self.quantize(sub)
        return model
