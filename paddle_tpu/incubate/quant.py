"""Imperative quantization: QAT + post-training (PTQ).

Parity: ``/root/reference/python/paddle/fluid/contrib/slim/quantization/
imperative/qat.py`` (``ImperativeQuantAware``: wrap Linear/Conv2D with
fake-quant on weights + activations; straight-through backward) and
``imperative/ptq.py`` (``ImperativePTQ``: observer-based calibration,
then frozen scales — no training).

TPU note: v5e serving gains come from bf16/int8 matmuls — QAT here trains
the model THROUGH int8 rounding (fake quant in fp) so an int8 deployment
(via the Predictor's precision knobs or an external converter) keeps
accuracy; the fake-quant kernels live in ``ops/quant_ops.py``.
"""

from __future__ import annotations

from typing import List

import numpy as np

from .. import nn

__all__ = ["ImperativeQuantAware", "ImperativePTQ", "QuantizedLinear",
           "QuantizedConv2D"]


def _fake_quant(x, kind: str, bits: int, layer, state_name: str,
                moving_rate: float = 0.9):
    from ..dygraph.tensor import Tensor
    from ..framework import program as fw
    from ..ops.dispatch import dispatch

    ndim = len(x.shape)
    if kind == "channel":
        outs = dispatch(
            "fake_channel_wise_quantize_dequantize_abs_max", {"X": [x]},
            {"bit_length": bits, "quant_axis": ndim - 1})
        return outs["Out"][0]
    if kind == "abs_max":
        outs = dispatch(
            "fake_quantize_dequantize_abs_max", {"X": [x]},
            {"bit_length": bits})
        return outs["Out"][0]
    # moving-average activation quant: the scale is a persistable BUFFER so
    # the trained value round-trips through state_dict (a plain attribute
    # would silently drop it on save/load)
    scale = getattr(layer, state_name, None)
    if scale is None:
        if not fw.in_dygraph_mode():
            raise RuntimeError(
                "fake-quant scale buffer missing under a static trace — "
                "calibrate/train the quantized model eagerly before "
                "jit.save / to_static")
        scale = Tensor(np.asarray([float(np.abs(np.asarray(x._array)).max()
                                         or 1.0)], "float32"),
                       stop_gradient=True)
        layer.register_buffer(state_name, scale)
        # accumulation states for the reference moving-average recurrence
        # (state_t = rate*state + 1, accum_t = rate*accum + cur,
        # scale = accum/state); starting both at 0 makes the first scale
        # exactly the first batch's abs-max — no warm-up bias
        layer.register_buffer(state_name + "_state", Tensor(
            np.zeros((1,), "float32"), stop_gradient=True))
        layer.register_buffer(state_name + "_accum", Tensor(
            np.zeros((1,), "float32"), stop_gradient=True))
    sc_in = scale
    if not fw.in_dygraph_mode():
        # static trace: address the buffer through its bound program var
        # (jit._bind_params created it and pushed the value to the scope)
        blk = fw.default_main_program().global_block()
        v = blk.vars.get(scale.name)
        if v is None:
            v = blk.create_var(name=scale.name, shape=(1,),
                               dtype="float32", persistable=True)
        sc_in = v
    ins = {"X": [x], "InScale": [sc_in]}
    state = getattr(layer, state_name + "_state", None)
    accum = getattr(layer, state_name + "_accum", None)
    training = layer.training and fw.in_dygraph_mode()
    if training and state is not None and accum is not None:
        # thread the accumulators so the kernel runs the stateful
        # (bias-corrected) recurrence instead of the legacy one-buffer EMA
        ins["InState"] = [state]
        ins["InAccum"] = [accum]
    outs = dispatch(
        "fake_quantize_dequantize_moving_average_abs_max", ins,
        {"bit_length": bits, "moving_rate": moving_rate,
         "is_test": not layer.training})
    if training:
        scale._array = outs["OutScale"][0]._array
        if "OutState" in outs:
            state._array = outs["OutState"][0]._array
            accum._array = outs["OutAccum"][0]._array
    return outs["Out"][0]


class QuantizedLinear(nn.Layer):
    """Linear with channel-wise weight fake-quant + moving-avg activation
    fake-quant (qat.py QuantizedLinear role)."""

    def __init__(self, inner, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = inner
        self._wbits, self._abits = weight_bits, activation_bits
        self._rate = moving_rate

    def forward(self, x):
        from ..nn import functional as F
        from .. import tensor_api as T

        xq = _fake_quant(x, "moving", self._abits, self, "_in_scale",
                         self._rate)
        wq = _fake_quant(self.inner.weight, "channel", self._wbits, self,
                         "_w_scale")
        out = T.matmul(xq, wq)
        if self.inner.bias is not None:
            out = T.add(out, self.inner.bias)
        return out


class QuantizedConv2D(nn.Layer):
    def __init__(self, inner, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.inner = inner
        self._wbits, self._abits = weight_bits, activation_bits
        self._rate = moving_rate

    def forward(self, x):
        from ..dygraph import tracer

        xq = _fake_quant(x, "moving", self._abits, self, "_in_scale",
                         self._rate)
        wq = _fake_quant(self.inner.weight, "abs_max", self._wbits, self,
                         "_w_scale")
        pad = self.inner._padding
        pad = [pad, pad] if isinstance(pad, int) else list(pad)
        attrs = {"strides": list(self.inner._stride),
                 "paddings": pad,
                 "dilations": list(self.inner._dilation),
                 "groups": self.inner._groups}
        outs = tracer.trace_op("conv2d", {"Input": [xq], "Filter": [wq]},
                               attrs)
        out = outs["Output"][0]
        if self.inner.bias is not None:
            from .. import tensor_api as T

            b = self.inner.bias
            out = T.add(out, T.reshape(b, [1, -1, 1, 1]))
        return out


_WRAPPERS = {"Linear": QuantizedLinear, "Conv2D": QuantizedConv2D}


class ImperativeQuantAware:
    """Parity: qat.py ImperativeQuantAware — in-place layer replacement."""

    def __init__(self, quantizable_layer_type: List[str] = ("Linear",
                                                            "Conv2D"),
                 weight_bits: int = 8, activation_bits: int = 8,
                 moving_rate: float = 0.9, **kw):
        self._types = tuple(quantizable_layer_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate

    def quantize(self, model: nn.Layer) -> nn.Layer:
        """Replace every quantizable sublayer with its fake-quant wrapper
        (in place, like the reference)."""
        for name, sub in list(model._sub_layers.items()):
            cls = type(sub).__name__
            if cls in self._types and cls in _WRAPPERS:
                model._sub_layers[name] = _WRAPPERS[cls](
                    sub, self._wbits, self._abits, self._rate)
            else:
                self.quantize(sub)
        return model


# ---------------------------------------------------------------------------
# Post-training quantization (PTQ)
# ---------------------------------------------------------------------------


class _AbsMaxObserver:
    """Running abs-max over calibration batches (ptq_quantizer.py
    AbsmaxQuantizer role)."""

    def __init__(self):
        self.scale = 0.0

    def update(self, arr):
        m = float(np.abs(np.asarray(arr)).max()) if arr.size else 0.0
        self.scale = max(self.scale, m)


class _AvgAbsMaxObserver(_AbsMaxObserver):
    """Mean of per-batch abs-max (smoother than the global max when
    calibration data has outliers)."""

    def __init__(self):
        self.scale = 0.0
        self._n = 0

    def update(self, arr):
        m = float(np.abs(np.asarray(arr)).max()) if arr.size else 0.0
        self._n += 1
        self.scale += (m - self.scale) / self._n


_PTQ_OBSERVERS = {"abs_max": _AbsMaxObserver, "avg_abs_max": _AvgAbsMaxObserver}


class _ObservedLayer(nn.Layer):
    """Pass-through wrapper recording input-activation statistics."""

    def __init__(self, inner, observer_cls):
        super().__init__()
        self.inner = inner
        self.observer = observer_cls()

    def forward(self, *args, **kw):
        if args:
            self.observer.update(args[0]._array)
        return self.inner(*args, **kw)


class ImperativePTQ:
    """Post-training quantization: calibrate with forward passes only, then
    freeze fake-quant scales — no training involved.

    Parity: ``/root/reference/python/paddle/fluid/contrib/slim/quantization/
    imperative/ptq.py`` (``ImperativePTQ.quantize`` installs per-layer
    quantizers that collect activation stats; ``save_quantized_model``
    converts).  Flow::

        ptq = ImperativePTQ(algo="avg_abs_max")
        model = ptq.quantize(model)
        for batch in calib_loader: model(batch)     # calibration
        model = ptq.convert(model)                  # frozen fake-quant

    After ``convert`` each Linear/Conv2D runs with the calibrated
    activation scale (moving-average kernel in is_test mode) and
    channel-wise weight fake-quant — the same inference math QAT produces,
    minus the fine-tuning.
    """

    def __init__(self, quantizable_layer_type: List[str] = ("Linear",
                                                            "Conv2D"),
                 algo: str = "avg_abs_max", weight_bits: int = 8,
                 activation_bits: int = 8):
        if algo not in _PTQ_OBSERVERS:
            raise ValueError(
                f"algo must be one of {sorted(_PTQ_OBSERVERS)}, got {algo!r}")
        self._types = tuple(quantizable_layer_type)
        self._observer = _PTQ_OBSERVERS[algo]
        self._wbits = weight_bits
        self._abits = activation_bits

    def quantize(self, model: nn.Layer) -> nn.Layer:
        for name, sub in list(model._sub_layers.items()):
            cls = type(sub).__name__
            if cls in self._types and cls in _WRAPPERS:
                model._sub_layers[name] = _ObservedLayer(sub, self._observer)
            else:
                self.quantize(sub)
        return model

    def convert(self, model: nn.Layer) -> nn.Layer:
        """Swap observers for fake-quant wrappers seeded with the calibrated
        scales; the returned model is inference-ready (call ``.eval()``)."""
        from ..dygraph.tensor import Tensor

        for name, sub in list(model._sub_layers.items()):
            if isinstance(sub, _ObservedLayer):
                wrapped = _WRAPPERS[type(sub.inner).__name__](
                    sub.inner, self._wbits, self._abits)
                scale = sub.observer.scale or 1.0
                wrapped.register_buffer("_in_scale", Tensor(
                    np.asarray([scale], "float32"), stop_gradient=True))
                # frozen calibration: eval mode keeps the moving-average
                # kernel in is_test so a forward pass can never drift the
                # calibrated scale (reference PTQ emits frozen scales)
                wrapped.eval()
                model._sub_layers[name] = wrapped
            else:
                self.convert(sub)
        return model
