"""``paddle.inference`` — the deployment Predictor/Config API.

Parity: ``/root/reference/paddle/fluid/inference/api/analysis_predictor.h:82``
(AnalysisPredictor) and ``paddle_analysis_config.h`` (AnalysisConfig) — the
C++ engine the reference builds for serving (47k LoC: IR passes, memory
optimization, TensorRT/MKLDNN backends).

TPU-first: the saved inference Program lowers to ONE cached XLA executable
(the static Executor), so the reference's IR-pass pipeline, memory reuse
passes, and kernel selection are all delegated to the XLA compiler; the
Predictor is a thin stateful handle with the reference's zero-copy tensor
API surface.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["Config", "Predictor", "Tensor", "create_predictor",
           "PrecisionType", "PlaceType"]


class PrecisionType:
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


class PlaceType:
    CPU = 0
    GPU = 1
    TPU = 2
    XPU = 3


class Config:
    """Parity: AnalysisConfig — model path + toggles.  Most reference
    knobs configure subsystems XLA owns here; they are accepted and
    recorded so deployment scripts run unmodified."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        # paddle 2.x convention: Config("path/prefix") with combined files
        self._prog_file = prog_file
        self._params_file = params_file
        self._prefix = None
        if prog_file is not None and params_file is None:
            self._prefix = prog_file
        elif prog_file is not None and prog_file.endswith(".pdmodel.json"):
            self._prefix = prog_file[: -len(".pdmodel.json")]
        self._device = "tpu"
        self._device_id = 0
        self._amp = None
        self._opts: Dict[str, object] = {}

    # -- model location -------------------------------------------------
    def set_model(self, prog_file, params_file=None):
        self.__init__(prog_file, params_file)

    def prog_file(self):
        return self._prog_file

    def params_file(self):
        return self._params_file

    def model_dir(self):
        return self._prefix

    # -- device ----------------------------------------------------------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._device, self._device_id = "gpu", device_id

    def enable_tpu(self, device_id=0):
        self._device, self._device_id = "tpu", device_id

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device == "gpu"

    # -- precision / graph options (owned by XLA; recorded) ---------------
    def enable_memory_optim(self, *a, **k):
        self._opts["memory_optim"] = True

    def switch_ir_optim(self, flag=True):
        self._opts["ir_optim"] = flag

    def enable_mkldnn(self):
        self._opts["mkldnn"] = True

    def set_cpu_math_library_num_threads(self, n):
        self._opts["cpu_threads"] = n

    def enable_tensorrt_engine(self, *a, precision_mode=PrecisionType.Float32,
                               **k):
        # TRT role ≙ XLA fusion; bf16 precision maps to an AMP rewrite,
        # int8 to the quantized-matmul program rewrite
        self._amp = ("bfloat16" if precision_mode in
                     (PrecisionType.Half, PrecisionType.Bfloat16) else None)
        if precision_mode == PrecisionType.Int8:
            # TRT-engine parity path: the user explicitly chose the int8
            # engine, so no size gate (TRT's own min_subgraph_size governs
            # granularity there); enable_int8() keeps the measured gate
            self._int8 = True
            self._int8_min_elements = 0

    def enable_bf16(self):
        self._amp = "bfloat16"

    def enable_int8(self, min_weight_elements: int = 1 << 16,
                    quantize_convs: bool = False):
        """Execute weight matmuls (and optionally convs) as int8 x int8 ->
        int32 on the MXU (static/quant_int8.py rewrite; the TRT int8
        engine role).

        ``min_weight_elements`` keeps small, bandwidth-bound layers on the
        bf16 path — the int8 GEMM win (1.5x at 4096^3, BENCH extras) needs
        enough MACs to amortize the quantize/dequant passes.  Pass 0 to
        quantize every matmul.  ``quantize_convs`` defaults OFF on
        measurement: int8 conv through XLA on v5e is 0.79-1.13x vs bf16
        at ResNet shapes (see quant_int8.rewrite_program_int8)."""
        self._int8 = True
        self._int8_min_elements = int(min_weight_elements)
        self._int8_convs = bool(quantize_convs)

    def summary(self):
        return {"model": self._prefix, "device": self._device,
                "amp": self._amp, "int8": getattr(self, "_int8", False),
                **self._opts}


class Tensor:
    """Parity: ZeroCopyTensor — named input/output handle."""

    def __init__(self, name: str, predictor: "Predictor", is_input: bool):
        self.name = name
        self._pred = predictor
        self._is_input = is_input

    def copy_from_cpu(self, arr: np.ndarray) -> None:
        assert self._is_input, f"{self.name} is an output handle"
        self._pred._feeds[self.name] = np.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        assert not self._is_input, f"{self.name} is an input handle"
        return np.asarray(self._pred._results[self.name])

    def shape(self) -> List[int]:
        if self._is_input:
            a = self._pred._feeds.get(self.name)
        else:
            a = self._pred._results.get(self.name)
        return list(a.shape) if a is not None else []

    def reshape(self, shape) -> None:  # reference API; shapes are dynamic
        pass


class Predictor:
    """Parity: AnalysisPredictor:82 — run() over named zero-copy handles.

    The loaded inference Program compiles once per feed-shape set through
    the whole-block XLA Executor (program cache keyed on shapes)."""

    def __init__(self, config: Config):
        from ..framework.scope import Scope
        from ..static.executor import Executor
        from ..static.io import load_inference_model

        self._config = config
        self._scope = Scope()
        self._exe = Executor()
        prefix = config.model_dir() or config.prog_file()
        if prefix is None:
            raise ValueError("Config has no model path; call set_model()")
        self._program, self._feed_names, self._fetch_names = \
            load_inference_model(prefix, self._exe, scope=self._scope)
        if config._amp == "bfloat16":
            from ..static.amp import rewrite_program

            rewrite_program(self._program)
        if getattr(config, "_int8", False):
            from ..static.quant_int8 import rewrite_program_int8

            self._n_int8 = rewrite_program_int8(
                self._program, self._scope,
                fetch_names=list(self._fetch_names),
                min_weight_elements=getattr(
                    config, "_int8_min_elements", 1 << 16),
                quantize_convs=getattr(config, "_int8_convs", False))
        self._feeds: Dict[str, np.ndarray] = {}
        self._results: Dict[str, np.ndarray] = {}

    # -- handles ----------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def get_input_handle(self, name: str) -> Tensor:
        assert name in self._feed_names, name
        return Tensor(name, self, is_input=True)

    def get_output_handle(self, name: str) -> Tensor:
        assert name in self._fetch_names, name
        return Tensor(name, self, is_input=False)

    # -- execution --------------------------------------------------------
    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """Reference dual API: ``run()`` after copy_from_cpu, or
        ``run([arr, ...])`` returning the outputs directly."""
        if inputs is not None:
            for name, arr in zip(self._feed_names, inputs):
                self._feeds[name] = np.asarray(arr)
        missing = [n for n in self._feed_names if n not in self._feeds]
        if missing:
            raise RuntimeError(f"inputs not set: {missing}")
        outs = self._exe.run(self._program, feed=dict(self._feeds),
                             fetch_list=list(self._fetch_names),
                             scope=self._scope)
        self._results = dict(zip(self._fetch_names, outs))
        return [self._results[n] for n in self._fetch_names]

    def clone(self) -> "Predictor":
        return Predictor(self._config)


def create_predictor(config: Config) -> Predictor:
    """Parity: paddle_infer.create_predictor."""
    return Predictor(config)
