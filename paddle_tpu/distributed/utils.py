"""``paddle.distributed.utils`` — launcher helper surface.

Parity: ``/root/reference/python/paddle/distributed/utils.py`` (Cluster/
Pod descriptors + process helpers used by launch).  The live
implementations are in ``launch_utils.py``; this module re-exports the
stable names under the reference's module path."""

from .launch_utils import (  # noqa: F401
    Cluster, TrainerProc, find_free_port, rank_env, start_local_trainers,
    watch_local_trainers,
)

__all__ = ["Cluster", "TrainerProc", "find_free_port", "rank_env",
           "start_local_trainers", "watch_local_trainers", "get_cluster"]


def get_cluster(node_ips, node_ip=None, trainer_endpoints=None,
                device_mode=None, devices_per_proc=None):
    """Reference-shaped constructor: build a Cluster from node ips +
    per-node proc count (endpoint details derive from the master)."""
    ips = list(node_ips) if not isinstance(node_ips, str) else \
        node_ips.split(",")
    nproc = (len(devices_per_proc) if devices_per_proc is not None else 1)
    return Cluster(ips=ips, nproc_per_node=nproc, master=ips[0],
                   master_port=find_free_port(),
                   node_rank=ips.index(node_ip) if node_ip in ips else 0)
