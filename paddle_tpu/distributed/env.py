"""Distributed environment introspection.

Parity: ``/root/reference/python/paddle/distributed/parallel.py``
(get_rank/get_world_size reading PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM set
by the launcher) — extended with jax.process_index for multi-host TPU pods.
"""

from __future__ import annotations

import os


def get_rank() -> int:
    r = os.environ.get("PADDLE_TRAINER_ID")
    if r is not None:
        return int(r)
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def get_world_size() -> int:
    w = os.environ.get("PADDLE_TRAINERS_NUM")
    if w is not None:
        return int(w)
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1
