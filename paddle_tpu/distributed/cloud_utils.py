"""``paddle.distributed.cloud_utils`` — cluster discovery from cloud env.

Parity: ``/root/reference/python/paddle/distributed/cloud_utils.py`` —
derives the cluster layout from PaddleCloud-style env vars; here the same
PADDLE_* env protocol feeds the launch_utils Cluster."""

import os

from .launch_utils import Cluster, find_free_port

__all__ = ["get_cluster_and_pod", "get_trainers_num"]


def get_trainers_num():
    return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))


def get_cluster_and_pod(args=None):
    ips = os.environ.get("PADDLE_TRAINERS", "127.0.0.1").split(",")
    nproc = int(os.environ.get("PADDLE_TRAINER_PROCS", 1))
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0))
    cluster = Cluster(ips=ips, nproc_per_node=nproc, master=ips[0],
                      master_port=int(os.environ.get(
                          "PADDLE_MASTER_PORT", find_free_port())),
                      node_rank=min(rank, len(ips) - 1))
    return cluster, cluster.local_ranks()
