"""``python -m paddle_tpu.distributed.launch`` — the fleet launcher CLI.

Parity: ``/root/reference/python/paddle/distributed/fleet/launch.py:441``
(``launch_collective``) and its arg surface (``--ips``, ``--gpus``→
``--devices``, ``--log_dir``, training_script + args).  Produces the
``PADDLE_*`` env protocol consumed by
``paddle_tpu.distributed.parallel.init_parallel_env``; rendezvous is
``jax.distributed.initialize`` against ``PADDLE_MASTER:MASTER_PORT``
(replacing the reference's gen_endpoints + NCCL id broadcast).

Usage:
    python -m paddle_tpu.distributed.launch --nproc_per_node=2 train.py --lr 0.1
    python -m paddle_tpu.distributed.launch --ips=10.0.0.1,10.0.0.2 train.py
"""

from __future__ import annotations

import argparse
import os
import sys

from .launch_utils import (
    Cluster,
    find_free_port,
    start_local_trainers,
    watch_local_trainers,
)


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch distributed trainers (fleet launch parity)")
    p.add_argument("--ips", type=str, default="127.0.0.1",
                   help="comma list of node IPs; this node must appear in it")
    p.add_argument("--nproc_per_node", type=int, default=None,
                   help="trainers per node (default: one, or one per entry "
                        "in --devices)")
    p.add_argument("--devices", "--gpus", "--tpus", dest="devices", type=str,
                   default=None,
                   help="comma list of device ids to bind, one trainer each")
    p.add_argument("--master", type=str, default=None,
                   help="coordinator host[:port] (default: first ip)")
    p.add_argument("--node_rank", type=int, default=None,
                   help="this node's index in --ips (default: inferred)")
    p.add_argument("--log_dir", type=str, default=None,
                   help="write per-rank workerlog.N files here")
    p.add_argument("--timeout", type=float, default=None,
                   help="seconds to wait before killing trainers")
    p.add_argument("--elastic", action="store_true",
                   help="restart-the-world on rank failure / stale "
                        "heartbeat, resuming via auto_checkpoint")
    p.add_argument("--max_restarts", type=int, default=3,
                   help="elastic: restart budget before giving up")
    p.add_argument("--job_id", type=str, default=None,
                   help="elastic: stable job id for checkpoint resume "
                        "(exported as PADDLE_JOB_ID)")
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_from_args(args) -> tuple:
    ips = [ip.strip() for ip in args.ips.split(",") if ip.strip()]
    devices = ([d.strip() for d in args.devices.split(",")]
               if args.devices else None)
    nproc = args.nproc_per_node or (len(devices) if devices else 1)
    if args.master:
        host, _, port = args.master.partition(":")
        # bare host: every NODE must agree on the port, so use the fixed
        # default — a per-node find_free_port() could never rendezvous
        master, master_port = host, int(port or 8476)
    else:
        master = ips[0]
        master_port = find_free_port() if ips == ["127.0.0.1"] else 8476
    node_rank = args.node_rank
    if node_rank is None:
        import socket

        names = {"127.0.0.1", "localhost", socket.gethostname()}
        try:
            names.add(socket.gethostbyname(socket.gethostname()))
        except OSError:
            pass
        node_rank = next((i for i, ip in enumerate(ips) if ip in names), 0)
    cluster = Cluster(ips=ips, nproc_per_node=nproc, master=master,
                      master_port=master_port, node_rank=node_rank)
    return cluster, devices


def launch(argv=None) -> int:
    args = _parse_args(argv)
    cluster, devices = get_cluster_from_args(args)
    cmd = [sys.executable, args.training_script] + args.training_script_args
    base_env = dict(os.environ)
    if args.job_id:
        base_env["PADDLE_JOB_ID"] = args.job_id
    print(f"launch: {cluster.nproc_per_node} local trainer(s), world size "
          f"{cluster.world_size}, master {cluster.master}:{cluster.master_port}"
          + (" [elastic]" if args.elastic else ""),
          flush=True)
    if args.elastic or os.environ.get("PADDLE_ELASTIC_STORE"):
        from .launch_utils import run_elastic

        return run_elastic(cluster, cmd, base_env=base_env,
                           log_dir=args.log_dir, devices=devices,
                           max_restarts=args.max_restarts,
                           timeout=args.timeout)
    procs = start_local_trainers(cluster, cmd, base_env=base_env,
                                 log_dir=args.log_dir, devices=devices)
    return watch_local_trainers(procs, timeout=args.timeout)


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
