"""Parallel environment bootstrap.

Parity: ``/root/reference/python/paddle/distributed/parallel.py``
(``init_parallel_env``:58 — env parsing, TCP store, NCCLParallelContext init)
— mapped to ``jax.distributed.initialize`` + a device mesh (SURVEY.md §2.4):
no ring ids, no comm streams, no TCP id exchange.
"""

from __future__ import annotations

import os
from typing import Optional

from . import env as dist_env


class ParallelEnv:
    """Parity: fluid/dygraph/parallel.py ParallelEnv."""

    def __init__(self):
        self._rank = dist_env.get_rank()
        self._world_size = dist_env.get_world_size()
        from ..framework import flags as _flags

        sel = _flags.flag("FLAGS_selected_tpus") or os.environ.get(
            "FLAGS_selected_tpus", "0")
        self._device_id = int(str(sel).split(",")[0] or 0)

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return self._rank

    @property
    def world_size(self):
        return self._world_size

    @property
    def nranks(self):
        return self._world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def current_endpoint(self):
        eps = self.trainer_endpoints
        return eps[self._rank] if self._rank < len(eps) else ""

    @property
    def trainer_endpoints(self):
        return os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")


_initialized = False


def init_parallel_env() -> ParallelEnv:
    """Initialize multi-host jax.distributed when launched by the fleet
    launcher (PADDLE_* env present) or TPU pod env; idempotent."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = os.environ.get("PADDLE_TRAINERS_NUM")
    pid = os.environ.get("PADDLE_TRAINER_ID")
    if coord and nprocs and int(nprocs) > 1:
        import jax

        # Multi-PROCESS collectives on the CPU backend need the gloo
        # transport (the default CPU client only wires intra-process
        # device collectives and fails jitted collectives with
        # "Multiprocess computations aren't implemented on the CPU
        # backend").  Must be set before the backend initializes, so key
        # off the configured platform rather than jax.default_backend().
        plats = (jax.config.jax_platforms or os.environ.get(
            "JAX_PLATFORMS", "")).split(",")
        if plats and plats[0].strip() == "cpu":
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass  # older/newer jax without the option: keep defaults

        port = os.environ.get("MASTER_PORT", "8476")
        jax.distributed.initialize(
            coordinator_address=f"{coord}:{port}" if ":" not in coord else coord,
            num_processes=int(nprocs),
            process_id=int(pid or 0),
        )
    _initialized = True
    # default mesh over all devices (1-D data-parallel) unless fleet topology
    # installs a hybrid mesh later
    from . import mesh as mesh_mod

    mesh_mod.ensure_default_mesh()
    return ParallelEnv()


def get_rank():
    return dist_env.get_rank()


def get_world_size():
    return dist_env.get_world_size()
