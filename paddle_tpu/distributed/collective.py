"""``paddle.distributed`` collective API.

Parity: ``/root/reference/python/paddle/distributed/collective.py``
(all_reduce, all_gather, broadcast, reduce, scatter, alltoall, send/recv,
barrier, new_group:209, split:1283, _c_identity:748, _mp_allreduce:882).

TPU-first semantics (SURVEY.md §2.4):
  * a Group names a MESH AXIS (ring_id -> axis registered with the kernel
    layer), so collectives called while tracing under shard_map lower to
    lax.psum / all_gather / ppermute on ICI;
  * called eagerly on global (sharded or replicated) jax arrays, data is
    already globally consistent — the cross-RANK part degenerates to the
    cross-PROCESS case, served by multihost utils when process_count > 1;
  * in static mode the call appends the corresponding ``c_*`` op, preserving
    the reference's program-rewriting architecture.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..framework import program as fw
from ..ops.dispatch import dispatch, single
from ..ops import collective_ops
from . import env as dist_env
from . import mesh as mesh_mod

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce", "all_gather",
    "broadcast", "reduce", "scatter", "alltoall", "send", "recv", "barrier",
    "wait", "split", "get_rank", "get_world_size", "is_initialized",
]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3


class Group:
    """Parity: collective.py Group — here bound to a mesh axis name."""

    def __init__(self, rank: int, nranks: int, id: int = 0,
                 ranks: Optional[List[int]] = None, axis_name: Optional[str] = None):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.axis_name = axis_name
        if axis_name is not None:
            collective_ops.set_ring_axis(id, axis_name)

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(id={self.id}, nranks={self.nranks}, axis={self.axis_name})"


_GROUPS = {}
_GROUP_COUNTER = [0]


def _default_group() -> Group:
    if 0 not in _GROUPS:
        _GROUPS[0] = Group(
            dist_env.get_rank(), max(dist_env.get_world_size(), 1), 0,
            axis_name=None,
        )
    return _GROUPS[0]


def get_group(gid: int = 0) -> Group:
    return _GROUPS.get(gid) or _default_group()


def is_initialized() -> bool:
    return True


def new_group(ranks: Optional[List[int]] = None, backend=None, axis_name=None) -> Group:
    """Parity: collective.py:209 new_group — allocates a ring id; here the
    ring is (optionally) bound to a mesh axis for in-graph collectives."""
    _GROUP_COUNTER[0] += 1
    gid = _GROUP_COUNTER[0]
    rank = dist_env.get_rank()
    ranks = ranks if ranks is not None else list(range(dist_env.get_world_size()))
    g = Group(ranks.index(rank) if rank in ranks else -1, len(ranks), gid,
              ranks=ranks, axis_name=axis_name)
    _GROUPS[gid] = g
    return g


def get_rank():
    return dist_env.get_rank()


def get_world_size():
    return dist_env.get_world_size()


def _ring(group) -> int:
    return 0 if group is None else group.id


def _is_static() -> bool:
    return not fw.in_dygraph_mode()


def _eager_value(tensor):
    return tensor


def all_reduce(tensor, op=ReduceOp.SUM, group=None, use_calc_stream=True, sync_op=True):
    op_type = {
        ReduceOp.SUM: "c_allreduce_sum", ReduceOp.MAX: "c_allreduce_max",
        ReduceOp.MIN: "c_allreduce_min", ReduceOp.PROD: "c_allreduce_prod",
    }[op]
    out = single(dispatch(op_type, {"X": [tensor]}, {"ring_id": _ring(group)}))
    if not _is_static():
        # in-place semantics (parity: reference mutates the input tensor)
        tensor._array = out._array
        return tensor
    return out


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    out = single(dispatch("c_allgather", {"X": [tensor]},
                          {"ring_id": _ring(group),
                           "nranks": (group or _default_group()).nranks}))
    if not _is_static():
        n = (group or _default_group()).nranks
        if n <= 1:
            tensor_list.append(out)
        else:
            from .. import tensor_api as T

            chunks = T.split(out, n, axis=0)
            tensor_list.extend(chunks)
        return
    return out


def broadcast(tensor, src=0, group=None, sync_op=True):
    out = single(dispatch("c_broadcast", {"X": [tensor]},
                          {"ring_id": _ring(group), "root": src}))
    if not _is_static():
        tensor._array = out._array
        return tensor
    return out


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    # on mesh axes reduce==allreduce (every shard gets the value); parity ok
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    n = (group or _default_group()).nranks
    if n <= 1:
        if tensor_list:
            tensor._array = tensor_list[0]._array
        return tensor
    raise NotImplementedError(
        "eager scatter across ranks is expressed by sharding the source "
        "array over the mesh (paddle_tpu.distributed.mesh.shard_batch)"
    )


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    if isinstance(in_tensor_list, (list, tuple)):
        n = (group or _default_group()).nranks
        if n <= 1:
            out_tensor_list.extend(in_tensor_list)
            return
        raise NotImplementedError(
            "eager list-based alltoall across ranks maps to mesh resharding; "
            "inside shard_map use the 'alltoall' op"
        )
    return single(dispatch("alltoall", {"X": [in_tensor_list]}, {"ring_id": _ring(group)}))


def send(tensor, dst=0, group=None, sync_op=True):
    if (group or _default_group()).nranks <= 1:
        return
    raise NotImplementedError(
        "p2p send/recv maps to ppermute inside the pipeline engine "
        "(paddle_tpu.distributed.fleet pipeline parallel)"
    )


def recv(tensor, src=0, group=None, sync_op=True):
    if (group or _default_group()).nranks <= 1:
        return
    raise NotImplementedError(
        "p2p send/recv maps to ppermute inside the pipeline engine "
        "(paddle_tpu.distributed.fleet pipeline parallel)"
    )


def barrier(group=None):
    import jax

    (jax.device_put(0) + 0).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    if hasattr(tensor, "_array"):
        tensor._array.block_until_ready()


# -- model-parallel helpers (parity: collective.py:748-1283) -----------------


def _c_identity(tensor, group=None):
    return single(dispatch("c_identity", {"X": [tensor]}, {"ring_id": _ring(group)}))


def _mp_allreduce(tensor, op=ReduceOp.SUM, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    return single(dispatch("mp_allreduce_sum", {"X": [tensor]}, {"ring_id": _ring(group)}))


def _c_concat(tensor, group=None):
    g = group or _default_group()
    return single(dispatch("c_concat", {"X": [tensor]},
                           {"ring_id": _ring(group), "nranks": g.nranks}))


def _c_split(tensor, group=None):
    g = group or _default_group()
    return single(dispatch("c_split", {"X": [tensor]},
                           {"ring_id": _ring(group), "nranks": g.nranks}))


def _c_softmax_with_cross_entropy(logits, label, group=None, return_softmax=False):
    outs = dispatch(
        "c_softmax_with_cross_entropy",
        {"Logits": [logits], "Label": [label]},
        {"ring_id": _ring(group)},
    )
    if return_softmax:
        return outs["Loss"][0], outs["Softmax"][0]
    return outs["Loss"][0]


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Parity: collective.py:1283 paddle.distributed.split — builds a
    row/column-sharded linear or vocab-sharded embedding."""
    from .fleet import meta_parallel as mpp

    if operation == "embedding":
        layer = mpp.VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
        return layer(x)
    if operation == "linear":
        if axis == 0:
            layer = mpp.RowParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                          has_bias=bias_attr is not False)
        else:
            layer = mpp.ColumnParallelLinear(size[0], size[1], weight_attr=weight_attr,
                                             has_bias=bias_attr is not False,
                                             gather_output=gather_out)
        return layer(x)
    raise ValueError(f"unknown operation {operation!r}")
