"""Global device-mesh registry — the TPU-native replacement for the
reference's communicator registry.

Parity role: ``/root/reference/paddle/fluid/platform/collective_helper.h:69``
(per-ring NCCLComm map) + ``fleet/base/topology.py`` rank arithmetic.  Here a
"ring" is a NAMED MESH AXIS of one global ``jax.sharding.Mesh``; groups are
axis names, shardings are PartitionSpecs, and XLA lowers collectives onto ICI.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_MESH: Optional[Mesh] = None

# canonical hybrid axis order (outermost..innermost): dp, pp, sharding, mp
# — mp innermost so tensor-parallel collectives ride the fastest ICI links,
# matching the reference's HybridCommunicateGroup order (topology.py:36).
HYBRID_AXES = ("dp", "pp", "sharding", "mp")


def set_mesh(mesh: Mesh) -> Mesh:
    global _MESH
    _MESH = mesh
    return mesh


def get_mesh() -> Optional[Mesh]:
    return _MESH


def ensure_default_mesh() -> Mesh:
    global _MESH
    if _MESH is None:
        devs = np.array(jax.devices())
        _MESH = Mesh(devs.reshape(-1), axis_names=("dp",))
    return _MESH


def build_hybrid_mesh(dp: int = 1, mp: int = 1, pp: int = 1, sharding: int = 1,
                      devices=None) -> Mesh:
    """Create (and install) the 4-axis hybrid mesh ``(dp, pp, sharding, mp)``.

    Parity: HybridCommunicateGroup's rank mesh (topology.py:117); degrees from
    DistributedStrategy.hybrid_configs (distributed_strategy.py:835-847).
    """
    devices = np.array(devices if devices is not None else jax.devices())
    need = dp * mp * pp * sharding
    if devices.size < need:
        raise ValueError(
            f"hybrid topology dp={dp} mp={mp} pp={pp} sharding={sharding} "
            f"needs {need} devices, have {devices.size}"
        )
    devices = devices[:need].reshape(dp, pp, sharding, mp)
    return set_mesh(Mesh(devices, axis_names=HYBRID_AXES))


def sharding_for(*spec) -> NamedSharding:
    return NamedSharding(ensure_default_mesh(), P(*spec))


def replicate(x):
    """Place an array replicated across the mesh."""
    mesh = ensure_default_mesh()
    return jax.device_put(x, NamedSharding(mesh, P()))


def shard_batch(x, axis_names: Tuple[str, ...] = ("dp", "sharding")):
    """Shard the leading (batch) dim over the given mesh axes.

    'sharding' is included by default: ZeRO's sharding group IS a
    data-parallel group (each sharding rank consumes different data; only
    optimizer state/grads/params are partitioned — reference
    fleet/meta_optimizers/sharding_optimizer.py semantics)."""
    mesh = ensure_default_mesh()
    names = tuple(a for a in axis_names if a in mesh.axis_names and mesh.shape[a] > 1)
    if not names:
        return jax.device_put(x, NamedSharding(mesh, P()))
    spec = P(names if len(names) > 1 else names[0])
    return jax.device_put(x, NamedSharding(mesh, spec))


def axis_size(name: str) -> int:
    mesh = get_mesh()
    if mesh is None or name not in mesh.axis_names:
        return 1
    return int(mesh.shape[name])
