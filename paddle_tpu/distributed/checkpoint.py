"""Distributed (sharded) checkpoint save/load.

Parity role: the reference's fleet checkpoint utilities
(``fleet/utils/fs.py`` + ``fleet/meta_optimizers/dygraph_optimizer``
sharded state save; ``paddle.distributed.save_state_dict`` in later
paddles).  TPU-first: every process writes ONLY its addressable shards of
each ``jax.Array`` (no gather to host 0 — a 13B checkpoint never
materializes on one host), with a JSON manifest describing the global
layout; load reassembles whichever shards are visible and re-shards onto
the CURRENT mesh (topology changes between save and load are fine).
"""

from __future__ import annotations

import json
import os
from typing import Dict

import numpy as np

import jax

__all__ = ["save_state_dict", "load_state_dict"]


def _arr(v):
    from ..dygraph.tensor import Tensor

    return v._array if isinstance(v, Tensor) else v


def _index_to_spec(idx, shape):
    """Serialize an addressable-shard index (tuple of slices)."""
    out = []
    for sl, n in zip(idx, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = n if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_state_dict(state_dict: Dict[str, object], path: str) -> None:
    """Write this process's shards of every entry + a manifest.

    Layout: ``{path}/meta.json`` (global shapes/dtypes),
    ``{path}/shards_{proc}.npz`` (key ``{name}::{k}`` per local shard) and
    ``{path}/shards_{proc}.idx.json`` (the slice spec per key)."""
    os.makedirs(path, exist_ok=True)
    proc = jax.process_index()
    meta, shards, index = {}, {}, {}
    for name, v in state_dict.items():
        a = _arr(v)
        if not isinstance(a, jax.Array):
            a = jax.numpy.asarray(a)
        meta[name] = {"shape": list(a.shape), "dtype": str(a.dtype)}
        for k, shard in enumerate(a.addressable_shards):
            key = f"{name}::{k}"
            shards[key] = np.asarray(shard.data)
            index[key] = {"name": name,
                          "slices": _index_to_spec(shard.index, a.shape)}
    if proc == 0:
        with open(os.path.join(path, "meta.json"), "w") as f:
            json.dump(meta, f)
    np.savez(os.path.join(path, f"shards_{proc}.npz"), **shards)
    with open(os.path.join(path, f"shards_{proc}.idx.json"), "w") as f:
        json.dump(index, f)


def load_state_dict(state_dict: Dict[str, object], path: str) -> None:
    """Fill ``state_dict`` IN PLACE from a sharded checkpoint.

    Each entry is reassembled from all shard files present, then placed
    with the entry's CURRENT sharding (device_put re-shards, so the saved
    and loading meshes may differ)."""
    from ..dygraph.tensor import Tensor

    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    full: Dict[str, np.ndarray] = {}
    filled: Dict[str, np.ndarray] = {}
    for fn in sorted(os.listdir(path)):
        if not (fn.startswith("shards_") and fn.endswith(".npz")):
            continue
        proc = fn[len("shards_"):-len(".npz")]
        data = np.load(os.path.join(path, fn))
        with open(os.path.join(path, f"shards_{proc}.idx.json")) as f:
            index = json.load(f)
        for key in data.files:
            name = index[key]["name"]
            if name not in meta:
                continue
            if name not in full:
                full[name] = np.empty(meta[name]["shape"],
                                      dtype=meta[name]["dtype"])
                filled[name] = np.zeros(meta[name]["shape"], dtype=bool)
            slices = tuple(slice(a, b) for a, b in index[key]["slices"])
            full[name][slices] = data[key]
            filled[name][slices] = True
    for name, v in state_dict.items():
        if name not in full:
            raise KeyError(f"checkpoint at {path!r} has no entry {name!r}")
        if not filled[name].all():
            raise RuntimeError(
                f"checkpoint entry {name!r} is incomplete: only "
                f"{int(filled[name].sum())}/{filled[name].size} elements "
                f"present (missing shard files for another host?)")
        a = _arr(v)
        sharding = getattr(a, "sharding", None)
        new = jax.numpy.asarray(full[name])
        if sharding is not None and isinstance(a, jax.Array):
            new = jax.device_put(new, sharding)
        if isinstance(v, Tensor):
            v._array = new
        else:
            state_dict[name] = new
