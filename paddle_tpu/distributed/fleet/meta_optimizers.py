"""Fleet meta-optimizers: LocalSGD + DGC momentum.

Parity: ``/root/reference/python/paddle/distributed/fleet/meta_optimizers/
{localsgd_optimizer.py, dgc_optimizer.py}``.

TPU-first notes:
  * LocalSGD: each process steps locally for ``k_steps`` then the params
    are averaged ACROSS PROCESSES (multi-controller path launched by
    ``paddle_tpu.distributed.launch``).  In single-program SPMD, grads are
    already globally reduced, so the averaging is a no-op by construction.
  * DGC: the ALGORITHM (top-k gradient sparsification with local gradient
    accumulation + momentum correction, Lin et al. 2018) is preserved AND
    the cross-process transport is genuinely sparse: each rank ships only
    its top-k (value, index) pairs — static [world, k] shapes — via
    ``process_allgather``, and the received updates scatter-sum into a
    dense apply.  Per-step traffic is ``2k x world`` words instead of the
    dense ``n`` (k = (1-sparsity) x n, e.g. 0.1% at sparsity 0.999).
    Within one SPMD program (single controller) grads are already reduced
    by XLA, so the sparse exchange only engages on the multi-process
    launcher path.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["LocalSGDOptimizer", "DGCMomentumOptimizer"]


def _unique(params):
    seen, out = set(), []
    for p in params:
        if id(p) not in seen:
            seen.add(id(p))
            out.append(p)
    return out


class LocalSGDOptimizer:
    """Parity: localsgd_optimizer.py — k local steps, then parameter
    averaging across the data-parallel world."""

    def __init__(self, optimizer, k_steps: int = 1):
        self._inner = optimizer
        self.k_steps = max(int(k_steps), 1)
        self._params = _unique(optimizer._parameter_list or [])
        self._step = 0

    def step(self):
        self._inner.step()
        self._step += 1
        if self._step % self.k_steps == 0:
            self._average_params()

    def _average_params(self):
        if jax.process_count() <= 1:
            return  # SPMD single-controller: grads were already global
        from jax.experimental import multihost_utils

        for p in self._params:
            g = multihost_utils.process_allgather(p._array)
            p._array = jnp.mean(g, axis=0).astype(p._array.dtype)

    def clear_grad(self):
        self._inner.clear_grad()

    clear_gradients = clear_grad

    def get_lr(self):
        return self._inner.get_lr()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class DGCMomentumOptimizer:
    """Deep Gradient Compression momentum (parity: dgc_optimizer.py /
    fluid DGCMomentumOptimizer; Lin et al. 2018).

    Before ``rampup_begin_step`` this is plain momentum.  After it, only
    the top ``(1-sparsity)`` fraction of gradient magnitudes update the
    velocity each step; the rest ACCUMULATE locally (with momentum
    correction) until they grow large enough to be selected."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 rampup_begin_step: int = 0,
                 rampup_step: int = 1,
                 sparsity: Optional[List[float]] = None,
                 grad_clip=None, name=None):
        from ... import optimizer as opt_mod

        self._momentum = momentum
        self._sparsity = list(sparsity or [0.999])
        self.rampup_begin_step = int(rampup_begin_step)
        self.rampup_step = max(int(rampup_step), 1)
        self._inner = opt_mod.Momentum(
            learning_rate=learning_rate, momentum=momentum,
            parameters=parameters, grad_clip=grad_clip)
        self._params = _unique(self._inner._parameter_list or [])
        self._u = {}  # momentum-corrected local accumulation
        self._step = 0

    def _current_sparsity(self) -> float:
        k = (self._step - self.rampup_begin_step - 1) // self.rampup_step
        return self._sparsity[min(max(k, 0), len(self._sparsity) - 1)]

    def step(self):
        self._step += 1
        if self._step <= self.rampup_begin_step:
            self._inner.step()
            return
        s = self._current_sparsity()
        lr = float(self._inner.get_lr())
        clip = getattr(self._inner, "_grad_clip", None)
        if clip is not None:
            # the inner optimizer is bypassed post-rampup, so apply its
            # clip here — otherwise grad clipping silently stops at rampup
            pgs = [(p, p.grad) for p in self._params if p.grad is not None]
            for (p, _), (_, g2) in zip(pgs, clip(pgs)):
                p.grad._array = g2._array
        world = jax.process_count()
        for p in self._params:
            if p.grad is None:
                continue
            g = p.grad._array.astype(jnp.float32)
            u = self._u.get(id(p), jnp.zeros_like(g))
            # momentum correction: u IS the velocity, accumulated locally
            u = self._momentum * u + g
            flat_u = u.reshape(-1)
            n = flat_u.size
            k = max(int(n * (1.0 - s)), 1)
            _, idx = jax.lax.top_k(jnp.abs(flat_u), k)
            vals = flat_u[idx]
            mask = jnp.zeros((n,), u.dtype).at[idx].set(1.0).reshape(
                u.shape)
            self._u[id(p)] = u * (1.0 - mask)  # keep the residual
            if world > 1:
                # SPARSE transport: 2k words per rank instead of dense n
                # (the reference's sparse NCCL allgather role)
                from jax.experimental import multihost_utils

                g_vals = multihost_utils.process_allgather(vals)
                g_idx = multihost_utils.process_allgather(idx)
                send = jnp.zeros((n,), u.dtype).at[
                    g_idx.reshape(-1)].add(g_vals.reshape(-1))
                send = (send / world).reshape(u.shape)  # DP mean semantics
            else:
                send = (u * mask)
            # plain-SGD apply of the selected velocity — the reference's
            # dgc_momentum op does the same post-rampup; feeding `send`
            # through the inner Momentum would apply momentum TWICE
            p._array = (p._array.astype(jnp.float32)
                        - lr * send).astype(p._array.dtype)
        self._inner.clear_grad()

    def clear_grad(self):
        self._inner.clear_grad()

    clear_gradients = clear_grad

    def get_lr(self):
        return self._inner.get_lr()

    def __getattr__(self, name):
        return getattr(self._inner, name)
