"""Hybrid-parallel topology.

Parity: ``/root/reference/python/paddle/distributed/fleet/base/topology.py``
(``CommunicateTopology``:36, ``HybridCommunicateGroup``:117 — the rank mesh
``dp x pp x sharding x mp`` and its sub-groups).

TPU-first: the topology directly BUILDS the 4-axis jax Mesh; each "comm
group" is a mesh axis (collectives over it are XLA collectives on ICI), so
there are no ring ids to initialize and no p2p groups to pre-create — the
pipeline engine uses ppermute over the 'pp' axis.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Sequence, Tuple

import numpy as np

from .. import collective
from .. import env as dist_env
from .. import mesh as mesh_mod


class CommunicateTopology:
    """Parity: topology.py:36 — pure rank arithmetic over the hybrid axes."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(*(range(d) for d in dims)))
        self._word_size = int(np.prod(dims))
        self._rank2coord = {self._coord_to_rank(c): c for c in self.coordinate}

    def _coord_to_rank(self, coord) -> int:
        rank = 0
        for c, d in zip(coord, self._dims):
            rank = rank * d + c
        return rank

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._word_size

    def get_rank(self, **kwargs) -> int:
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return self._coord_to_rank(coord)

    def get_coord(self, rank: int):
        coord = self._rank2coord[rank]
        import collections

        C = collections.namedtuple("Coord", self._parallel_names)
        return C(*coord)

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        axis = self._parallel_names.index(axis_name)
        return sorted(
            self._coord_to_rank(c) for c in self.coordinate if c[axis] == index
        )

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        """All groups along axis_name (ranks varying only in that axis)."""
        axis = self._parallel_names.index(axis_name)
        others = [
            (i, d) for i, d in enumerate(self._dims) if i != axis
        ]
        groups = []
        for combo in itertools.product(*(range(d) for _, d in others)):
            group = []
            for v in range(self._dims[axis]):
                coord = [0] * len(self._dims)
                for (i, _), cv in zip(others, combo):
                    coord[i] = cv
                coord[axis] = v
                group.append(self._coord_to_rank(tuple(coord)))
            groups.append(group)
        return groups


# paddle axis name -> mesh axis name
_AXIS_MAP = {"data": "dp", "pipe": "pp", "sharding": "sharding", "model": "mp"}


class HybridCommunicateGroup:
    """Parity: topology.py:117 — builds the jax hybrid Mesh and exposes the
    per-axis (rank, world, group) accessors the meta_parallel engines use."""

    def __init__(self, topology: CommunicateTopology):
        self._topo = topology
        self.global_rank = dist_env.get_rank()
        self.nranks = topology.world_size()

        names = topology.get_hybrid_group_names()
        dims = {n: topology.get_dim(n) for n in names}
        self._dp_degree = dims.get("data", 1)
        self._pp_degree = dims.get("pipe", 1)
        self._sharding_degree = dims.get("sharding", 1)
        self._mp_degree = dims.get("model", 1)

        # install the hybrid mesh over the actual jax devices
        self.mesh = mesh_mod.build_hybrid_mesh(
            dp=self._dp_degree, mp=self._mp_degree, pp=self._pp_degree,
            sharding=self._sharding_degree,
        )

        coord = topology.get_coord(self.global_rank) if self.nranks > 1 else None
        self._dp_rank = getattr(coord, "data", 0) if coord else 0
        self._pp_rank = getattr(coord, "pipe", 0) if coord else 0
        self._sharding_rank = getattr(coord, "sharding", 0) if coord else 0
        self._mp_rank = getattr(coord, "model", 0) if coord else 0

        # groups bound to mesh axes (ring_id -> axis for the kernels)
        self._dp_group = collective.new_group(axis_name="dp")
        self._pp_group = collective.new_group(axis_name="pp")
        self._sharding_group = collective.new_group(axis_name="sharding")
        self._mp_group = collective.new_group(axis_name="mp")
        self._check_group = collective.new_group(axis_name=None)

    # -- parity accessors -------------------------------------------------
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and self._sharding_degree == 1:
            return "data_parallel"
        if self._sharding_degree > 1 and self._mp_degree == 1 and self._pp_degree == 1:
            return "sharding_parallel"
        if self._mp_degree > 1 and self._pp_degree == 1:
            return "tensor_parallel"
        if self._pp_degree > 1:
            return "pipeline_parallel"
        return "data_parallel"

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._dp_rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return 0

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._mp_rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return 0

    # pipeline
    def get_stage_id(self):
        return self._pp_rank

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def is_first_stage(self):
        return self._pp_rank == 0

    def is_last_stage(self):
        return self._pp_rank == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._sharding_rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return 0

    def get_check_parallel_group(self):
        return self._check_group

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank(
            data=self._dp_rank, pipe=stage_id,
            sharding=self._sharding_rank, model=self._mp_rank,
        )
