"""``python -m paddle_tpu.distributed.fleet.launch`` — reference-path alias.

Parity: ``/root/reference/python/paddle/distributed/fleet/launch.py`` (the
module users actually invoke); implementation lives in
``paddle_tpu.distributed.launch``.
"""

from ..launch import launch, main  # noqa: F401

if __name__ == "__main__":
    main()
