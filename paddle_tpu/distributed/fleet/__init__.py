"""``paddle.distributed.fleet``.

Parity: ``/root/reference/python/paddle/distributed/fleet/__init__.py`` +
``base/fleet_base.py`` (``init``:139, ``distributed_model``:836,
``distributed_optimizer``:783, worker/server accessors).  The parameter-server
mode is explicitly out of scope (BASELINE north star) — PS entry points raise
with a pointer to the collective path.
"""

from __future__ import annotations

import os
from typing import Optional

from .. import env as dist_env
from .. import mesh as mesh_mod
from .distributed_strategy import DistributedStrategy
from .topology import CommunicateTopology, HybridCommunicateGroup
from . import meta_parallel  # noqa: F401
from . import elastic  # noqa: F401
from . import meta_optimizers  # noqa: F401
from ..parallel import init_parallel_env

__all__ = [
    "init", "DistributedStrategy", "UserDefinedRoleMaker", "PaddleCloudRoleMaker",
    "worker_index", "worker_num", "is_worker", "worker_endpoints", "server_num",
    "server_index", "server_endpoints", "is_server", "is_first_worker", "barrier_worker",
    "init_worker", "init_server", "run_server", "stop_worker", "distributed_model",
    "distributed_optimizer", "get_hybrid_communicate_group", "meta_parallel",
]

_fleet_state = {
    "initialized": False,
    "strategy": None,
    "hcg": None,
    "is_collective": True,
}


class PaddleCloudRoleMaker:
    """Parity: fleet/base/role_maker.py — env-driven role discovery."""

    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective

    def _worker_index(self):
        return dist_env.get_rank()

    def _worker_num(self):
        return dist_env.get_world_size()


UserDefinedRoleMaker = PaddleCloudRoleMaker


def init(role_maker=None, is_collective=True, strategy=None):
    """Parity: fleet_base.py:139 fleet.init."""
    if strategy is None:
        strategy = DistributedStrategy()
    _fleet_state["strategy"] = strategy
    _fleet_state["is_collective"] = is_collective
    init_parallel_env()

    hc = strategy.hybrid_configs
    dp, mp = hc.get("dp_degree", -1), hc.get("mp_degree", 1)
    pp, sd = hc.get("pp_degree", 1), hc.get("sharding_degree", 1)
    import jax

    ndev = len(jax.devices())
    if dp in (-1, 0, None):
        dp = max(ndev // max(mp * pp * sd, 1), 1)
    topo = CommunicateTopology(dims=(dp, pp, sd, mp))
    _fleet_state["hcg"] = HybridCommunicateGroup(topo)
    _fleet_state["initialized"] = True
    return None


def _hcg() -> HybridCommunicateGroup:
    if _fleet_state["hcg"] is None:
        init()
    return _fleet_state["hcg"]


def get_hybrid_communicate_group():
    return _hcg()


def distributed_model(model):
    """Parity: fleet_base.py:836 — wrap by parallel mode."""
    hcg = _hcg()
    strategy = _fleet_state["strategy"]
    mode = hcg.get_parallel_mode()
    mp_cls = meta_parallel
    if mode == "pipeline_parallel":
        return mp_cls.PipelineParallel(model, hcg, strategy)
    if mode == "tensor_parallel":
        return mp_cls.TensorParallel(model, hcg, strategy)
    if mode == "sharding_parallel":
        return mp_cls.ShardingParallel(model, hcg, strategy)
    return mp_cls.DataParallelSPMD(model, hcg, strategy)


def distributed_optimizer(optimizer, strategy=None):
    """Parity: fleet_base.py:783."""
    if strategy is not None:
        _fleet_state["strategy"] = strategy
    return meta_parallel.HybridParallelOptimizer(
        optimizer, _hcg(), _fleet_state["strategy"] or DistributedStrategy()
    )


# -- worker/server accessors (collective mode) ------------------------------


def worker_index():
    return dist_env.get_rank()


def worker_num():
    return dist_env.get_world_size()


def is_worker():
    return True


def is_first_worker():
    return dist_env.get_rank() == 0


def worker_endpoints(to_string=False):
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "").split(",")
    return ",".join(eps) if to_string else eps


def barrier_worker():
    from .. import collective

    collective.barrier()


# -- parameter-server path: explicitly out of scope -------------------------

_PS_MSG = (
    "the parameter-server path is out of scope for the TPU build (BASELINE "
    "north star: 'the parameter-server path is left untouched'); use the "
    "collective path — sparse tables map to mesh-sharded embeddings "
    "(meta_parallel.VocabParallelEmbedding)"
)


def init_server(*a, **k):
    raise NotImplementedError(_PS_MSG)


def run_server(*a, **k):
    raise NotImplementedError(_PS_MSG)


def init_worker(*a, **k):  # collective mode: nothing to do
    return None


def stop_worker(*a, **k):
    return None


def server_num():
    return 0


def server_index():
    return 0


def server_endpoints(to_string=False):
    return "" if to_string else []


def is_server():
    return False


# -- fleet save APIs (fleet_base.py:697/732) --------------------------------


def save_inference_model(executor, dirname, feeded_var_names, target_vars,
                         main_program=None, export_for_deployment=True,
                         mode=0):
    """Rank-0 inference export (fleet_base.py:697) — under the
    single-controller SPMD model only process 0 writes."""
    from ... import static as static_mod

    if dist_env.get_rank() != 0:
        return
    prog = main_program or static_mod.default_main_program()
    blk = prog.global_block()
    feed_vars = [blk.var(n) if isinstance(n, str) else n
                 for n in feeded_var_names]
    import os as _os

    prefix = _os.path.join(dirname, "model")
    static_mod.save_inference_model(prefix, feed_vars, list(target_vars),
                                    executor, program=prog)


def save_persistables(executor, dirname, main_program=None, mode=0):
    """Rank-0 program-state snapshot (fleet_base.py:732)."""
    from ...static import io as static_io
    from ... import static as static_mod

    if dist_env.get_rank() != 0:
        return
    import os as _os

    prog = main_program or static_mod.default_main_program()
    _os.makedirs(dirname, exist_ok=True)
    static_io.save(prog, _os.path.join(dirname, "persistables"))


class UtilBase:
    """Parity: fleet/base/util_factory.py UtilBase — cross-worker helper
    math over the collective surface + host-side file sharding."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from .. import collective as C
        from ...dygraph.tensor import Tensor

        t = Tensor(np.asarray(input))
        op = {"sum": C.ReduceOp.SUM, "max": C.ReduceOp.MAX,
              "min": C.ReduceOp.MIN}[mode]
        C.all_reduce(t, op=op)
        return np.asarray(t.numpy())

    def all_gather(self, input, comm_world="worker"):
        import numpy as np

        from .. import collective as C
        from ...dygraph.tensor import Tensor

        out = []
        C.all_gather(out, Tensor(np.asarray(input)))
        return [np.asarray(t.numpy()) for t in out]

    def barrier(self, comm_world="worker"):
        from .. import collective as C

        C.barrier()

    def get_file_shard(self, files):
        """Split a file list contiguously across workers (util_factory
        get_file_shard semantics: first ``len % n`` workers get one
        extra)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file paths")
        n = max(dist_env.get_world_size(), 1)
        idx = dist_env.get_rank()
        base, extra = divmod(len(files), n)
        counts = [base + (1 if i < extra else 0) for i in range(n)]
        start = sum(counts[:idx])
        return files[start:start + counts[idx]]

    def print_on_rank(self, message, rank_id):
        if dist_env.get_rank() == rank_id:
            print(message, flush=True)


util = UtilBase()

from . import utils  # noqa: E402,F401
