"""Dygraph activation recompute built on PyLayer.

Parity: ``/root/reference/python/paddle/distributed/fleet/utils/recompute.py:63``
(``RecomputeFunction(PyLayer)``: forward under no_grad saving inputs + RNG
state; backward replays the function with gradients enabled under the saved
RNG state, runs autograd over the replayed subgraph, and returns the input
grads).

TPU-first note: inside jit-compiled train steps ``jax.checkpoint`` is the
native remat mechanism (models/gpt.py); this module serves the EAGER dygraph
API so reference training scripts using ``fleet.utils.recompute`` run
unchanged.
"""

from __future__ import annotations

from ....dygraph.tensor import Tensor
from ....autograd import PyLayer
from ....dygraph import tracer
from ....framework import random as frandom


def check_recompute_necessary(inputs):
    if not any(isinstance(x, Tensor) and not x.stop_gradient for x in inputs):
        import warnings

        warnings.warn(
            "[Recompute]: None of the inputs to current recompute block need "
            "grad; there is NO need to recompute this block in backward")


class RecomputeFunction(PyLayer):
    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        check_recompute_necessary(args)
        ctx.run_function = run_function
        ctx.preserve_rng_state = preserve_rng_state

        ctx.inputs = []
        ctx.tensor_indices = []
        tensor_inputs = []
        for i, arg in enumerate(args):
            if isinstance(arg, Tensor):
                tensor_inputs.append(arg)
                ctx.tensor_indices.append(i)
                ctx.inputs.append(None)
            else:
                ctx.inputs.append(arg)
        ctx.save_for_backward(*tensor_inputs)
        # dropout replay: snapshot the framework RNG key (the reference saves
        # the CUDA RNG state; here a jax PRNGKey)
        if preserve_rng_state:
            ctx.fw_rng_state = frandom.get_rng_state()
        ctx.amp_state = tracer.amp_state()

        outputs = run_function(*args)  # apply() already disabled grads
        return outputs

    @staticmethod
    def backward(ctx, *output_grads):
        from ....autograd import backward as autograd_backward
        from ....amp.auto_cast import auto_cast

        inputs = list(ctx.inputs)
        detached = []
        for i, idx in enumerate(ctx.tensor_indices):
            saved = ctx.saved_tensor()[i]
            d = Tensor(saved._array, stop_gradient=saved.stop_gradient)
            inputs[idx] = d
            detached.append(d)

        old_rng = None
        if ctx.preserve_rng_state:
            old_rng = frandom.get_rng_state()
            frandom.set_rng_state(ctx.fw_rng_state)
        old_grad = tracer.set_grad_enabled(True)
        old_amp = tracer.amp_state()
        tracer.set_amp_state(ctx.amp_state)
        try:
            outputs = ctx.run_function(*inputs)
        finally:
            tracer.set_amp_state(old_amp)
            tracer.set_grad_enabled(old_grad)
            if old_rng is not None:
                frandom.set_rng_state(old_rng)

        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        tensor_outs = [t for t in outs if isinstance(t, Tensor)]
        grads = [g for t, g in zip(tensor_outs, output_grads)]
        autograd_backward(tensor_outs, grads)
        return tuple(
            d.grad if d.grad is not None else None for d in detached
        )


def recompute(function, *args, **kwargs):
    """``fleet.utils.recompute(fn, *args)`` — recompute fn's activations in
    backward instead of storing them (recompute.py:171 parity)."""
    preserve = kwargs.pop("preserve_rng_state", True)
    if kwargs:
        raise ValueError(f"Unexpected kwargs: {list(kwargs)}")
    return RecomputeFunction.apply(function, preserve, *args)
