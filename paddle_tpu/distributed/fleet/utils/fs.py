"""Filesystem abstraction for checkpoints / data shards.

Parity: ``/root/reference/python/paddle/distributed/fleet/utils/fs.py``
(FS base:57, LocalFS:119, HDFSClient:423).  TPU pods mount shared
filesystems (GCS-fuse/NFS), so ``LocalFS`` covers the pod case; the
``HDFSClient`` surface is kept but requires a ``hadoop`` binary — absent
in this zero-egress build it raises with guidance rather than shelling
out blind.
"""

from __future__ import annotations

import os
import shutil
from typing import List

__all__ = [
    "ExecuteError", "FSFileExistsError", "FSFileNotExistsError", "FSTimeOut",
    "FSShellCmdAborted", "FS", "LocalFS", "HDFSClient",
]


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class FSShellCmdAborted(ExecuteError):
    pass


class FS:
    """Abstract surface (fs.py:57)."""

    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None):
        raise NotImplementedError


class LocalFS(FS):
    """Parity: fs.py:119 — local/shared-mount filesystem."""

    def ls_dir(self, fs_path):
        """Returns ([dirs], [files]) like the reference."""
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            if os.path.isdir(os.path.join(fs_path, name)):
                dirs.append(name)
            else:
                files.append(name)
        return dirs, files

    def list_dirs(self, fs_path) -> List[str]:
        return self.ls_dir(fs_path)[0]

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        # reference semantics: these checks are UNCONDITIONAL — callers use
        # FSFileExistsError to detect concurrent writers; silently
        # clobbering dst would lose data
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if not overwrite and self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        os.rename(src_path, dst_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            os.utime(fs_path, None)
            return
        with open(fs_path, "a"):
            pass

    def cat(self, fs_path=None):
        with open(fs_path, "r") as f:
            return f.read()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)

    def need_upload_download(self):
        return False


class HDFSClient(FS):
    """Real shell-out client over ``hadoop fs`` (fs.py:423 parity).

    When a hadoop CLI exists at ``hadoop_home/bin/hadoop`` every operation
    runs ``hadoop fs -<cmd>`` with the given ``configs`` as ``-D`` options
    (the reference shells out the same way); without one, construction
    raises with the supported deployment route instead of failing later
    on the first operation."""

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home or "", "bin", "hadoop")
        self._timeout = max(time_out / 1000.0, 1.0)
        self._configs = []
        for k, v in (configs or {}).items():
            self._configs += ["-D", f"{k}={v}"]
        if not os.path.exists(self._hadoop):
            raise RuntimeError(
                "HDFSClient needs a hadoop CLI (hadoop_home/bin/hadoop); "
                "none found in this build — use LocalFS over a shared "
                "mount (GCS-fuse/NFS), which is the TPU-pod deployment "
                "path")

    def _run(self, *args, ok_codes=(0,), binary=False):
        import subprocess

        cmd = [self._hadoop, "fs"] + self._configs + list(args)
        try:
            proc = subprocess.run(cmd, capture_output=True, text=not binary,
                                  timeout=self._timeout)
        except subprocess.TimeoutExpired as e:
            raise FSTimeOut(f"{' '.join(cmd)} timed out") from e
        if proc.returncode not in ok_codes:
            err = proc.stderr
            if binary:
                err = err.decode("utf-8", "replace")
            raise ExecuteError(
                f"{' '.join(cmd)} failed (rc={proc.returncode}): "
                f"{err.strip()[:500]}")
        return proc.returncode, proc.stdout

    def ls_dir(self, fs_path):
        """(dirs, files) under fs_path — parses ``hadoop fs -ls`` rows."""
        if not self.is_exist(fs_path):
            return [], []
        _, out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8 or parts[0] == "Found":
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def _test(self, flag, fs_path) -> bool:
        rc, _ = self._run("-test", flag, fs_path, ok_codes=(0, 1))
        return rc == 0

    def is_file(self, fs_path):
        return self._test("-f", fs_path)

    def is_dir(self, fs_path):
        return self._test("-d", fs_path)

    def is_exist(self, fs_path):
        return self._test("-e", fs_path)

    def upload(self, local_path, fs_path, multi_processes=1, overwrite=False):
        if not os.path.exists(local_path):
            raise FSFileNotExistsError(local_path)
        if not overwrite:
            if self.is_exist(fs_path):
                raise FSFileExistsError(fs_path)
            # plain -put (no -f): a concurrent writer racing past the
            # is_exist check still fails loudly instead of clobbering
            self._run("-put", local_path, fs_path)
            return
        if self.is_dir(fs_path):
            # '-put -f file dir' would nest the file INSIDE the directory;
            # only a directory target needs the explicit delete
            self.delete(fs_path)
        # -put -f overwrites a file atomically on the NameNode; the previous
        # delete-then-put left a window with NO file if the put failed
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path, multi_processes=1,
                 overwrite=False):
        if not self.is_exist(fs_path):
            raise FSFileNotExistsError(fs_path)
        if os.path.exists(local_path) and overwrite:
            if os.path.isdir(local_path):
                shutil.rmtree(local_path)
            else:
                os.remove(local_path)
        self._run("-get", fs_path, local_path)

    def mkdirs(self, fs_path):
        if not self.is_exist(fs_path):
            self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        if self.is_exist(fs_path):
            self._run("-rm", "-r", "-f", fs_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(fs_src_path):
            raise FSFileNotExistsError(fs_src_path)
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        self._run("-touchz", fs_path)

    def cat(self, fs_path, binary=False):
        """File contents; ``binary=True`` returns raw bytes.  The default
        decodes on demand (replacement chars instead of raising), so
        catting a binary checkpoint can never throw UnicodeDecodeError
        mid-pipeline."""
        if not self.is_exist(fs_path):
            return b"" if binary else ""
        _, out = self._run("-cat", fs_path, binary=True)
        return out if binary else out.decode("utf-8", "replace")

    def need_upload_download(self):
        return True
