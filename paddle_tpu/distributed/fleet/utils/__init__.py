"""fleet.utils — recompute and helper utilities.

Parity: ``/root/reference/python/paddle/distributed/fleet/utils/__init__.py``.
"""

from . import recompute as recompute_mod  # noqa: F401
from .recompute import recompute  # noqa: F401
from . import fs  # noqa: F401
from .fs import HDFSClient, LocalFS  # noqa: F401

__all__ = ["recompute", "fs", "LocalFS", "HDFSClient"]
