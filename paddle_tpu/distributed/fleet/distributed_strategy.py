"""DistributedStrategy.

Parity: ``/root/reference/paddle/fluid/framework/distributed_strategy.proto``
(:159-211 — amp/recompute/gradient_merge/pipeline/sharding/tensor_parallel/
hybrid configs) and its Python wrapper
``fleet/base/distributed_strategy.py`` (hybrid_configs:835-847).  Plain
Python here — there is no proto round-trip because no C++ side consumes it.
"""

from __future__ import annotations

from typing import Dict


class DistributedStrategy:
    def __init__(self):
        # strategy switches (proto:159-211 field parity)
        self.amp = False
        self.amp_configs: Dict = {
            "init_loss_scaling": 32768.0, "use_dynamic_loss_scaling": True,
            "custom_white_list": [], "custom_black_list": [], "use_pure_fp16": False,
        }
        self.recompute = False
        self.recompute_configs: Dict = {"checkpoints": []}
        self.gradient_merge = False
        self.gradient_merge_configs: Dict = {"k_steps": 1, "avg": True}
        self.pipeline = False
        self.pipeline_configs: Dict = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.sharding = False
        self.sharding_configs: Dict = {
            "sharding_degree": 1, "stage": 1, "segment_broadcast_MB": 32.0,
            "offload": False,
        }
        self.tensor_parallel = False
        self.tensor_parallel_configs: Dict = {"tensor_parallel_degree": 1}
        self.lamb = False
        self.lars = False
        self.dgc = False
        self.localsgd = False
        self.a_sync = False
        self.heter_ccl_mode = False
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.gradient_scale_configs: Dict = {"scale_strategy": "avg"}
        # hybrid degrees (distributed_strategy.py:835-847 parity)
        self.hybrid_configs: Dict = {
            "dp_degree": -1, "mp_degree": 1, "pp_degree": 1, "sharding_degree": 1,
        }

    def __setattr__(self, k, v):
        if k == "hybrid_configs" and hasattr(self, "hybrid_configs"):
            merged = dict(self.__dict__["hybrid_configs"])
            merged.update(v)
            self.__dict__[k] = merged
            return
        self.__dict__[k] = v

    def __repr__(self):
        on = [k for k, v in self.__dict__.items() if v is True]
        return f"DistributedStrategy(enabled={on}, hybrid={self.hybrid_configs})"
