"""Elastic training / failure detection.

Parity: ``/root/reference/python/paddle/distributed/fleet/elastic.py:99``
(ElasticManager: etcd-backed member registry, heartbeat watchdog,
scale-in/out decisions).  TPU-first minimal core: the rendezvous store is
a FILESYSTEM directory (shared FS on pods; localhost for tests) instead of
etcd — ranks heartbeat by touching ``{store}/rank_{i}``; the watcher flags
ranks whose heartbeat is stale, and the launcher can restart the job when
membership changes.  The reference's etcd client is an optional transport
behind the same API.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

__all__ = ["ElasticStatus", "ElasticManager"]


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """File-store elastic membership + heartbeat watchdog."""

    def __init__(self, store_dir: Optional[str] = None,
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 timeout: float = 30.0):
        self.store = store_dir or os.environ.get(
            "PADDLE_ELASTIC_STORE", "/tmp/paddle_tpu_elastic")
        self.rank = rank if rank is not None else int(
            os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.world_size = world_size if world_size is not None else int(
            os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.timeout = float(
            os.environ.get("PADDLE_ELASTIC_TIMEOUT", timeout))
        os.makedirs(self.store, exist_ok=True)

    @property
    def enabled(self) -> bool:
        """Parity: elastic is on when the env requests it (np range set)."""
        return bool(os.environ.get("PADDLE_ELASTIC_NP")
                    or os.environ.get("PADDLE_ELASTIC_STORE"))

    def _hb_path(self, rank: int) -> str:
        return os.path.join(self.store, f"rank_{rank}")

    def register(self) -> None:
        """Join the membership (first heartbeat)."""
        self.beat()

    def beat(self) -> None:
        """Heartbeat — cheap atomic mtime bump."""
        p = self._hb_path(self.rank)
        with open(p, "a"):
            os.utime(p, None)

    def exit(self) -> None:
        """Leave cleanly (no failure flagged for this rank)."""
        try:
            os.remove(self._hb_path(self.rank))
        except FileNotFoundError:
            pass

    def alive_ranks(self) -> List[int]:
        now = time.time()
        out = []
        for r in range(self.world_size):
            p = self._hb_path(r)
            try:
                if now - os.path.getmtime(p) <= self.timeout:
                    out.append(r)
            except FileNotFoundError:
                pass
        return out

    def failed_ranks(self) -> List[int]:
        """Ranks that registered but stopped heartbeating (stale mtime)."""
        now = time.time()
        out = []
        for r in range(self.world_size):
            p = self._hb_path(r)
            try:
                if now - os.path.getmtime(p) > self.timeout:
                    out.append(r)
            except FileNotFoundError:
                continue  # never registered or exited cleanly
        return out

    def watch(self) -> str:
        """One watchdog poll (parity: ElasticManager.watch loop body)."""
        failed = self.failed_ranks()
        if failed:
            return ElasticStatus.RESTART
        if not os.listdir(self.store):
            return ElasticStatus.COMPLETED
        return ElasticStatus.HOLD

    def start_beat_thread(self, interval: Optional[float] = None):
        """Heartbeat from a daemon thread (the reference keeps an etcd
        lease alive the same way).  Returns the thread."""
        import threading

        iv = interval if interval is not None else max(self.timeout / 5, 0.2)
        self.register()

        def loop():
            while not self._stop_beat.is_set():
                self.beat()
                self._stop_beat.wait(iv)

        self._stop_beat = threading.Event()
        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self._beat_thread = t
        return t

    def stop_beat_thread(self):
        ev = getattr(self, "_stop_beat", None)
        if ev is not None:
            ev.set()

    def clear(self):
        """Reset the membership store (launcher does this before each
        (re)start so stale heartbeats don't trigger an immediate restart)."""
        for name in os.listdir(self.store):
            try:
                os.remove(os.path.join(self.store, name))
            except OSError:
                pass
