"""SPMD pipeline-parallel engine: 1F1B-style microbatch schedule compiled as
ONE XLA program over the 'pp' mesh axis.

Role parity: ``/root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py`` (``PipelineParallel.train_batch``:114, ``_forward``:156,
``_backward``:199) and its NCCL p2p transport
(``pp_utils/p2p_communication.py:38-130``).

TPU-first design (SURVEY.md §7 "hard parts"):
  * stage transfer = ``lax.ppermute`` over the 'pp' ICI axis inside
    ``shard_map`` — no send_v2/recv_v2 ops, no comm streams;
  * the whole microbatch loop is a ``lax.scan`` in ONE jitted program, so XLA
    overlaps the ppermute with the next microbatch's compute (the 1F1B
    overlap the reference schedules by hand);
  * backward is ``jax.grad`` THROUGH the scan — no hand-written 1B phase;
  * stage weights live as stacked arrays ``(S, ...)`` sharded over 'pp', so
    each device holds exactly its stage's weights (pp memory scaling).

Requires homogeneous stages (same param structure per stage) — the shape
GPT/BERT stacks have.  Prologue (embedding) and epilogue (head/loss) run
replicated outside the pipelined region (cheap relative to the blocks).
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ... import mesh as mesh_mod


def spmd_pipeline(stage_fn: Callable, num_stages: int, axis: str = "pp"):
    """Build a pipelined apply: ``(stacked_params, microbatches) -> outputs``.

    stage_fn(params, x) -> y must be jax-traceable with y.shape == x.shape
    (transformer blocks).  ``stacked_params`` is a pytree whose leaves have a
    leading stage dim (S, ...); ``microbatches`` has shape (M, mb, ...).

    The returned function is meant to be called INSIDE shard_map/jit with the
    mesh installed; it handles its own shard_map over the pp axis.
    """

    mesh = mesh_mod.get_mesh()
    S = num_stages

    def per_device(params_block, xs):
        # params_block leaves: (1, ...) — this device's stage params
        stage = lax.axis_index(axis)
        p = jax.tree_util.tree_map(lambda a: a[0], params_block)
        M = xs.shape[0]
        T = M + S - 1
        state = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t; other stages use the received act
            mb = lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, M - 1), keepdims=False)
            x_in = jnp.where(stage == 0, mb, state)
            y = stage_fn(p, x_in)
            # last stage emits microbatch t-(S-1)
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            valid = (stage == S - 1) & (t >= S - 1)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, cur), out_idx, axis=0
            )
            state = lax.ppermute(y, axis, perm)
            return (state, outputs), None

        (state, outputs), _ = lax.scan(tick, (state, outputs), jnp.arange(T))
        # replicate the last stage's outputs across the pp axis
        outputs = lax.psum(jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)), axis)
        return outputs

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    def apply(stacked_params, microbatches):
        param_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
        try:
            fn = shard_map(
                per_device, mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
                check_vma=False,
            )
        except TypeError:
            fn = shard_map(
                per_device, mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
                check_rep=False,
            )
        return fn(stacked_params, microbatches)

    return apply


class PipelineEngine:
    """Owns the stacked stage params + the compiled train step.

    Exposed through ``PipelineParallel`` (paddle train_batch API parity).
    """

    def __init__(self, pipeline_layer, loss_fn=None, prologue=None, epilogue=None,
                 axis: str = "pp"):
        from .pp_layers import PipelineLayer

        self.layers = pipeline_layer
        self.axis = axis
        self.mesh = mesh_mod.get_mesh()
        self.S = pipeline_layer.get_num_stages()
        self.loss_fn = loss_fn or pipeline_layer._loss_fn
        self._stage_modules = [
            [l for l, _ in pipeline_layer.stage_layers(s)] for s in range(self.S)
        ]
        self._flatten_stage_params()
        self._train_step = None

    # -- parameter management -------------------------------------------
    def _stage_param_objs(self, s):
        out = []
        for m in self._stage_modules[s]:
            if hasattr(m, "parameters"):
                out.extend(m.parameters())
        return out

    def _flatten_stage_params(self):
        per_stage = [self._stage_param_objs(s) for s in range(self.S)]
        structs = [[tuple(p.shape) for p in ps] for ps in per_stage]
        if any(st != structs[0] for st in structs[1:]):
            raise ValueError(
                "SPMD pipeline requires homogeneous stages (same param "
                f"structure per stage); got {structs}"
            )
        self._param_objs = per_stage
        sharding = NamedSharding(self.mesh, P(self.axis))
        self.stacked = [
            jax.device_put(
                jnp.stack([np.asarray(per_stage[s][i]._array) for s in range(self.S)]),
                sharding,
            )
            for i in range(len(per_stage[0]))
        ]

    def sync_to_layers(self):
        """Write the engine's (possibly updated) stacked params back into the
        layer objects (for state_dict/save)."""
        for i, arr in enumerate(self.stacked):
            host = np.asarray(arr)
            for s in range(self.S):
                self._param_objs[s][i]._array = jnp.asarray(host[s])

    # -- functional stage apply ------------------------------------------
    def _stage_fn(self, params_list, x):
        """Run one stage's modules functionally (swap arrays, no taping)."""
        from ....dygraph import tracer
        from ....dygraph.tensor import Tensor

        mods = self._stage_modules[0]  # homogeneous: stage 0 structure
        objs = self._param_objs[0]
        old = [p._array for p in objs]
        for p, a in zip(objs, params_list):
            p._array = a
        old_grad = tracer.set_grad_enabled(False)
        try:
            t = Tensor(x, stop_gradient=True)
            for m in mods:
                t = m(t) if not isinstance(t, tuple) else m(*t)
            return t._array
        finally:
            tracer.set_grad_enabled(old_grad)
            for p, a in zip(objs, old):
                p._array = a

    # -- compiled step ----------------------------------------------------
    def build_forward(self):
        apply = spmd_pipeline(
            lambda p, x: self._stage_fn(p, x), self.S, self.axis
        )
        return apply

    def forward_backward(self, microbatches, labels_mb, loss_fn):
        """Returns (loss, grads_stacked).  loss_fn(y, label) -> scalar."""
        apply = self.build_forward()

        def total_loss(stacked, xs, ys):
            out = apply(stacked, xs)
            M = xs.shape[0]
            losses = jax.vmap(loss_fn)(out, ys)
            return jnp.mean(losses)

        if self._train_step is None:
            self._train_step = jax.jit(jax.value_and_grad(total_loss))
        return self._train_step(self.stacked, microbatches, labels_mb)

    def apply_grads_sgd(self, grads, lr: float):
        self.stacked = [p - lr * g for p, g in zip(self.stacked, grads)]
