"""SPMD pipeline-parallel engine: 1F1B-style microbatch schedule compiled as
ONE XLA program over the 'pp' mesh axis.

Role parity: ``/root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py`` (``PipelineParallel.train_batch``:114, ``_forward``:156,
``_backward``:199), its NCCL p2p transport
(``pp_utils/p2p_communication.py:38-130``), and the optimizer hookup the
reference does through ``HybridParallelOptimizer``.

TPU-first design (SURVEY.md §7 "hard parts"):
  * stage transfer = ``lax.ppermute`` over the 'pp' ICI axis inside
    ``shard_map`` — no send_v2/recv_v2 ops, no comm streams;
  * the whole microbatch loop is a ``lax.scan`` in ONE jitted program, so XLA
    overlaps the ppermute with the next microbatch's compute (the 1F1B
    overlap the reference schedules by hand);
  * backward is ``jax.grad`` THROUGH the scan — no hand-written 1B phase;
  * stage weights live as stacked arrays ``(S, bps, ...)`` sharded over 'pp',
    so each device holds exactly its stage's weights (pp memory scaling);
  * the optimizer (SGD/Momentum/Adam/AdamW, global-norm clip, scheduled LR)
    runs INSIDE the same jitted step — kernels match ``ops/optimizer_ops.py``
    bit-for-bit so pipelined training equals single-device training.

Stage layout: the engine partitions the ``PipelineLayer``'s layer list into
``prologue | homogeneous middle | epilogue``.  The middle (the maximal run of
layers with identical parameter structure, e.g. transformer blocks) is
pipelined over 'pp' with ``blocks_per_stage = len(middle) // S`` layers per
stage.  Prologue (embedding) and epilogue (final LN + tied head + loss)
COMPUTE runs on every pp rank, but their parameters and ALL their optimizer
state are stored sharded 1/S over the 'pp' axis (each param flattened,
padded to a multiple of S, and laid out ``P('pp')``): XLA all-gathers the
bf16/fp32 param at its use site and reduce-scatters the grad back, while
the fp32 master weights and Adam moments never materialize unsharded.  This
is the ZeRO-3-over-pp answer to the reference's stage-resident extra layers
(``pp_layers.py:76`` puts the embedding on stage 0, the head on the last
stage, and needs ``SharedLayerDesc`` + a grad allreduce for the tied
weight): per-rank bytes for the largest tensors in the model scale as 1/S
— better balanced than the reference, which concentrates them on the first
and last ranks — and a tied embedding/head is naturally one shard-stored
parameter whose two use-site grads autodiff sums, no shared-group comm.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ... import mesh as mesh_mod


def spmd_pipeline(stage_fn: Callable, num_stages: int, axis: str = "pp"):
    """Build a pipelined apply: ``(stacked_params, microbatches) -> outputs``.

    stage_fn(params, x) -> y must be jax-traceable with y.shape == x.shape
    (transformer blocks).  ``stacked_params`` is a pytree whose leaves have a
    leading stage dim (S, ...); ``microbatches`` has shape (M, mb, ...).

    The returned function is meant to be called INSIDE shard_map/jit with the
    mesh installed; it handles its own shard_map over the pp axis.
    """

    mesh = mesh_mod.get_mesh()
    S = num_stages

    def per_device(params_block, xs):
        # params_block leaves: (1, ...) — this device's stage params
        stage = lax.axis_index(axis)
        p = jax.tree_util.tree_map(lambda a: a[0], params_block)
        M = xs.shape[0]
        T = M + S - 1
        state = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t; other stages use the received act
            mb = lax.dynamic_index_in_dim(xs, jnp.clip(t, 0, M - 1), keepdims=False)
            x_in = jnp.where(stage == 0, mb, state)
            y = stage_fn(p, x_in)
            # last stage emits microbatch t-(S-1)
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            valid = (stage == S - 1) & (t >= S - 1)
            cur = lax.dynamic_index_in_dim(outputs, out_idx, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(valid, y, cur), out_idx, axis=0
            )
            state = lax.ppermute(y, axis, perm)
            return (state, outputs), None

        (state, outputs), _ = lax.scan(tick, (state, outputs), jnp.arange(T))
        # replicate the last stage's outputs across the pp axis
        outputs = lax.psum(jnp.where(stage == S - 1, outputs, jnp.zeros_like(outputs)), axis)
        return outputs

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    def apply(stacked_params, microbatches):
        param_specs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
        try:
            fn = shard_map(
                per_device, mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
                check_vma=False,
            )
        except TypeError:
            fn = shard_map(
                per_device, mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
                check_rep=False,
            )
        return fn(stacked_params, microbatches)

    return apply


# ---------------------------------------------------------------------------
# In-jit optimizer updates — driven through the REGISTERED kernels in
# ops/optimizer_ops.py (jax-traceable), so pipelined training equals
# single-device training by construction, not by a hand-kept copy.
# ---------------------------------------------------------------------------


def _clip_by_global_norm(flat_grads, clip_norm):
    """Functional twin of nn.clip.ClipGradByGlobalNorm (fluid/clip.py):
    scale = clip_norm / max(global_norm, clip_norm)."""
    gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in flat_grads))
    scale = clip_norm / jnp.maximum(gn, clip_norm)
    return [(g.astype(jnp.float32) * scale).astype(g.dtype) for g in flat_grads]


def _init_opt_state(mode: str, flat_params, hyper):
    def zeros(p):
        z = jnp.zeros(p.shape, jnp.float32)
        sh = getattr(p, "sharding", None)
        if isinstance(sh, NamedSharding):
            z = jax.device_put(z, sh)
        return z

    if mode == "sgd":
        state = {}
    elif mode == "momentum":
        state = {"velocity": [zeros(p) for p in flat_params]}
    elif mode in ("adam", "adamw"):
        # one global beta-pow pair: all params update in lockstep (shape [1]
        # like the reference's beta1_pow_acc accumulator)
        state = {
            "m": [zeros(p) for p in flat_params],
            "v": [zeros(p) for p in flat_params],
            "b1p": jnp.full((1,), hyper["beta1"], jnp.float32),
            "b2p": jnp.full((1,), hyper["beta2"], jnp.float32),
        }
    else:
        raise ValueError(f"unknown optimizer mode {mode!r}")
    if any(p.dtype != jnp.float32 for p in flat_params):
        # fp32 master weights for low-precision params — bf16-only updates
        # round sub-ulp deltas to zero and stall training (multi_precision
        # parity, same rationale as gpt.build_functional_train_step)
        state["master"] = [p.astype(jnp.float32) for p in flat_params]
    return state


def _apply_update(mode: str, hyper, flat_params, flat_grads, opt_state, lr):
    """Returns (new_flat_params, new_opt_state) by invoking the registered
    op kernels (sgd/momentum/adam/adamw from ops/optimizer_ops.py)."""
    from ....ops import optimizer_ops as K

    # NOTE: L2 regularization is folded into the grads BEFORE this function
    # (and before clipping) by the caller — eager Optimizer.step order is
    # _apply_regularization THEN _apply_clip (optimizer/__init__.py:217).
    # adamw per-param decay mask (apply_decay_param_fun): True = decay
    decay_mask = hyper.get("decay_mask") or (True,) * len(flat_params)
    masters = opt_state.get("master")
    work_p = masters if masters is not None else flat_params
    new_p, new_master, new_state = [], [], {}
    if mode == "sgd":
        for p, w, g in zip(flat_params, work_p, flat_grads):
            w_new = K.sgd_kernel(
                {"Param": w, "Grad": g, "LearningRate": lr}, {})["ParamOut"]
            new_master.append(w_new)
            new_p.append(w_new.astype(p.dtype))
    elif mode == "momentum":
        attrs = {"mu": hyper["momentum"],
                 "use_nesterov": hyper.get("use_nesterov", False)}
        vels = []
        for p, w, g, v in zip(flat_params, work_p, flat_grads,
                              opt_state["velocity"]):
            out = K.momentum_kernel(
                {"Param": w.astype(jnp.float32), "Grad": g.astype(jnp.float32),
                 "Velocity": v, "LearningRate": lr}, attrs)
            new_master.append(out["ParamOut"])
            new_p.append(out["ParamOut"].astype(p.dtype))
            vels.append(out["VelocityOut"])
        new_state["velocity"] = vels
    else:  # adam / adamw
        base_attrs = {"beta1": hyper["beta1"], "beta2": hyper["beta2"],
                      "epsilon": hyper["epsilon"]}
        b1p, b2p = opt_state["b1p"], opt_state["b2p"]
        ms, vs = [], []
        out = None
        for i, (p, w, g, m, v) in enumerate(zip(flat_params, work_p, flat_grads,
                                                opt_state["m"], opt_state["v"])):
            gf = g.astype(jnp.float32)
            if mode == "adamw":
                kernel = K.adamw_kernel
                attrs = dict(base_attrs, coeff=hyper.get("coeff", 0.01),
                             with_decay=bool(decay_mask[i]))
            else:
                kernel, attrs = K.adam_kernel, base_attrs
            out = kernel(
                {"Param": w.astype(jnp.float32), "Grad": gf, "Moment1": m,
                 "Moment2": v, "LearningRate": lr,
                 "Beta1Pow": b1p, "Beta2Pow": b2p}, attrs)
            new_master.append(out["ParamOut"])
            new_p.append(out["ParamOut"].astype(p.dtype))
            ms.append(out["Moment1Out"])
            vs.append(out["Moment2Out"])
        new_state = {"m": ms, "v": vs,
                     "b1p": out["Beta1PowOut"] if out is not None else b1p,
                     "b2p": out["Beta2PowOut"] if out is not None else b2p}
    if masters is not None:
        new_state["master"] = new_master
    return new_p, new_state


def extract_opt_config(optimizer) -> Tuple[str, dict, Optional[float]]:
    """Map a paddle_tpu optimizer object to (mode, hyper, clip_norm).

    Raises on configurations the in-jit update cannot honor — a silently
    degraded update (e.g. Lamb treated as SGD) would train a wrong
    trajectory with no warning."""
    from ....nn.clip import ClipGradByGlobalNorm
    from ....regularizer import L2Decay
    from .... import optimizer as opt_mod

    clip = getattr(optimizer, "_grad_clip", None)
    if clip is not None and not isinstance(clip, ClipGradByGlobalNorm):
        raise NotImplementedError(
            f"pipeline engine supports grad_clip=ClipGradByGlobalNorm only, "
            f"got {type(clip).__name__}")
    clip_norm = clip.clip_norm if clip is not None else None

    reg = getattr(optimizer, "regularization", None)
    l2 = 0.0
    if isinstance(reg, L2Decay):
        l2 = reg.coeff
    elif reg is not None:
        raise NotImplementedError(
            f"pipeline engine supports L2Decay regularization only, got {reg}")

    if isinstance(optimizer, opt_mod.AdamW):
        return ("adamw", {"beta1": optimizer._beta1, "beta2": optimizer._beta2,
                          "epsilon": optimizer._epsilon,
                          "coeff": optimizer._coeff, "l2": l2}, clip_norm)
    if isinstance(optimizer, opt_mod.Adam):
        return ("adam", {"beta1": optimizer._beta1, "beta2": optimizer._beta2,
                         "epsilon": optimizer._epsilon, "l2": l2}, clip_norm)
    if isinstance(optimizer, opt_mod.Momentum):
        return ("momentum", {"momentum": optimizer._momentum,
                             "use_nesterov": optimizer._use_nesterov,
                             "l2": l2}, clip_norm)
    if type(optimizer) is opt_mod.SGD:
        return ("sgd", {"l2": l2}, clip_norm)
    raise NotImplementedError(
        f"pipeline engine in-jit update does not support "
        f"{type(optimizer).__name__}; use SGD, Momentum, Adam, or AdamW")


class PipelineEngine:
    """Owns the partitioned params + the compiled pipelined train step.

    Exposed through ``PipelineParallel`` (paddle train_batch API parity).
    """

    def __init__(self, pipeline_layer, loss_fn=None, axis: str = "pp"):
        self.layers = pipeline_layer
        self.axis = axis
        self.mesh = mesh_mod.get_mesh()
        self.S = pipeline_layer.get_num_stages()
        self.seq_major = bool(getattr(pipeline_layer, "seq_major", False))
        self.loss_fn = loss_fn or pipeline_layer._loss_fn
        self._funcs = list(pipeline_layer._funcs)
        self._partition()
        self._materialize()
        self._step_cache = {}
        self.opt_state = None
        self._opt_key = None
        self._dirty = False
        self._eval_fn = None

    # -- stage partition ---------------------------------------------------
    @staticmethod
    def _sig(entry):
        """Homogeneity signature: layer CLASS tree + scalar config attrs +
        param structure.  Params alone are not enough — two blocks with
        identical weights shapes but different classes (or e.g. different
        window sizes) must not be treated as the same stage_fn."""
        layer, fwd = entry
        from ....nn.layer_base import Layer

        if not isinstance(layer, Layer):
            return None
        ps = list(layer.parameters())
        if not ps:
            return None

        def scalars(l, prefix=""):
            out = [(prefix + "::class", type(l).__name__)]
            for k, v in vars(l).items():
                if k.startswith("_") or k == "training":
                    continue
                if isinstance(v, (int, float, bool, str)):
                    out.append((prefix + k, v))
            for name, sub in getattr(l, "_sub_layers", {}).items():
                out.extend(scalars(sub, prefix + name + "."))
            return out

        # a SharedLayerDesc forward_func changes behavior with the same
        # layer/params — it must split the homogeneous run
        fwd_id = getattr(fwd, "__qualname__", repr(fwd)) if fwd else None
        return (fwd_id, tuple(scalars(layer)),
                tuple((tuple(p.shape), str(p._array.dtype)) for p in ps))

    def _partition(self):
        """Split layers into prologue | homogeneous middle | epilogue."""
        sigs = [self._sig(e) for e in self._funcs]
        best = (0, 0)  # (length, lo)
        i = 0
        while i < len(sigs):
            if sigs[i] is None:
                i += 1
                continue
            j = i
            while j < len(sigs) and sigs[j] == sigs[i]:
                j += 1
            if j - i > best[0]:
                best = (j - i, i)
            i = j
        run_len, lo = best
        usable = (run_len // self.S) * self.S
        if usable < self.S or usable == 0:
            raise ValueError(
                "SPMD pipeline requires a contiguous run of >= num_stages "
                "layers with identical parameter structure (e.g. transformer "
                f"blocks); longest run is {run_len} for {self.S} stages"
            )
        if usable < run_len:
            import warnings

            warnings.warn(
                f"pipeline stage partition: {run_len - usable} of {run_len} "
                f"homogeneous layers do not divide into {self.S} stages and "
                f"will run REPLICATED in the epilogue (duplicated compute, "
                f"no pp memory scaling for them); prefer num_layers a "
                f"multiple of num_stages")
        hi = lo + usable
        self._pro = self._funcs[:lo]
        self._mid = self._funcs[lo:hi]
        self._epi = self._funcs[hi:]
        self.blocks_per_stage = usable // self.S

    def _run_entries(self, entries, t):
        for layer, fwd in entries:
            if fwd is not None:
                t = fwd(layer, t)
            elif isinstance(t, tuple):
                t = layer(*t)
            else:
                t = layer(t)
        return t

    # -- parameter management -------------------------------------------
    def _materialize(self):
        mid_objs = [list(l.parameters()) for l, _ in self._mid]
        mid_ids = {id(p) for ps in mid_objs for p in ps}
        self._mid_objs = mid_objs
        self._tmpl = self._mid[0][0]
        self._tmpl_fwd = self._mid[0][1]  # shared forward_func (or None)
        self._tmpl_objs = mid_objs[0]

        other, seen = [], set()
        from ....nn.layer_base import Layer

        for layer, _ in self._pro + self._epi:
            if not isinstance(layer, Layer):
                continue
            for p in layer.parameters():
                if id(p) in seen or id(p) in mid_ids:
                    continue
                seen.add(id(p))
                other.append(p)
        self._other_objs = other

        mesh = self.mesh
        # prologue/epilogue params: store flattened + padded to a multiple
        # of S and sharded P('pp') — 1/S persistent bytes per rank for the
        # param AND everything _init_opt_state derives from it (master
        # weights, moments inherit this sharding via zeros_like/astype)
        shard = (NamedSharding(mesh, P(self.axis))
                 if mesh is not None else None)
        self._other_meta = []
        self.other = []
        for p in other:
            host = np.asarray(p._array)
            n = host.size
            pad = (-n) % self.S
            self._other_meta.append((tuple(host.shape), host.dtype.name, n))
            flat = np.concatenate([host.reshape(-1),
                                   np.zeros((pad,), host.dtype)])
            self.other.append(
                jax.device_put(flat, shard) if shard is not None
                else jnp.asarray(flat))
        # stack middle params: leaf j -> (S, bps, ...) sharded over pp on dim 0
        bps = self.blocks_per_stage
        self.stacked = []
        for j in range(len(self._tmpl_objs)):
            host = np.stack([np.asarray(ps[j]._array) for ps in mid_objs])
            host = host.reshape((self.S, bps) + host.shape[1:])
            if mesh is not None:
                arr = jax.device_put(host, NamedSharding(mesh, P(self.axis)))
            else:
                arr = jnp.asarray(host)
            self.stacked.append(arr)

    def sync_from_layers(self):
        """Re-materialize the engine's device copies FROM the layer objects —
        required after set_state_dict / checkpoint load, which rewrite the
        Tensors the engine snapshotted at construction.  fp32 master weights
        re-seed from the loaded params (otherwise the next step would resume
        the pre-load trajectory and overwrite the checkpoint); moments are
        kept, matching eager set_state_dict semantics."""
        self._materialize()
        self._dirty = False
        if self.opt_state is not None and "master" in self.opt_state:
            flat_p = jax.tree_util.tree_leaves((self.other, self.stacked))
            self.opt_state["master"] = [p.astype(jnp.float32) for p in flat_p]

    def sync_to_layers(self):
        """Write the engine's (possibly updated) params back into the layer
        objects (for state_dict/save).  No-op when nothing trained since the
        last sync — the host round-trip of every param is not free."""
        if not self._dirty:
            return
        self._dirty = False
        for j, arr in enumerate(self.stacked):
            host = np.asarray(arr)
            flat = host.reshape((self.S * self.blocks_per_stage,) + host.shape[2:])
            for i, ps in enumerate(self._mid_objs):
                ps[j]._array = jnp.asarray(flat[i])
        for p, arr, (shape, _dt, n) in zip(self._other_objs, self.other,
                                           self._other_meta):
            host = np.asarray(arr)
            p._array = jnp.asarray(host[:n].reshape(shape))

    # -- functional applies ----------------------------------------------
    def _apply_block(self, leaves, h):
        """Run the template middle block functionally on array ``h``."""
        from ....dygraph.tensor import Tensor

        saved = [p._array for p in self._tmpl_objs]
        for p, a in zip(self._tmpl_objs, leaves):
            p._array = a
        try:
            tin = Tensor(h, stop_gradient=True)
            t = (self._tmpl_fwd(self._tmpl, tin) if self._tmpl_fwd is not None
                 else self._tmpl(tin))
            return t._array if isinstance(t, Tensor) else t
        finally:
            for p, a in zip(self._tmpl_objs, saved):
                p._array = a

    def _stage_fn(self, leaves_bps, x):
        """One pipeline stage = blocks_per_stage sequential blocks; leaves
        have a leading (bps,) dim."""
        def body(h, leaves):
            return self._apply_block(leaves, h), None

        h, _ = lax.scan(body, x, tuple(leaves_bps))
        return h

    def _swap_other(self, arrays):
        saved = [p._array for p in self._other_objs]
        for p, a in zip(self._other_objs, arrays):
            p._array = a
        return saved

    def _unpack_other(self, packed):
        """Padded-1D shard-stored params -> full-shape arrays for compute.
        Under jit/GSPMD the slice+reshape is where XLA inserts the
        all-gather; the grad of this op is the matching scatter, so grads
        land back on the P('pp') layout elementwise with the opt state."""
        return [a[:n].reshape(shape)
                for a, (shape, _dt, n) in zip(packed, self._other_meta)]

    def _forward_arrays(self, other_arrays, stacked, xs_mb, apply):
        """prologue -> pipelined middle -> epilogue on traced arrays.
        xs_mb: (M, mb, ...); returns the epilogue output Tensor for the
        flattened batch.  ``other_arrays`` are the packed 1/S-sharded
        prologue/epilogue params."""
        from ....dygraph import tracer
        from ....dygraph.tensor import Tensor

        M = xs_mb.shape[0]
        saved = self._swap_other(self._unpack_other(other_arrays))
        og = tracer.set_grad_enabled(False)
        try:
            flat = xs_mb.reshape((-1,) + xs_mb.shape[2:])
            t = self._run_entries(self._pro, Tensor(flat, stop_gradient=True))
            h = t._array if isinstance(t, Tensor) else t
            if self.seq_major:
                # prologue emits [S, M*mb, H]: the scan indexes microbatches
                # on the LEADING dim, so lift the (M, mb) split out of dim 1
                # — the only layout change on the seq-major pipeline path
                s_len = h.shape[0]
                h_mb = jnp.moveaxis(
                    h.reshape((s_len, M, -1) + h.shape[2:]), 1, 0)
                y = apply(stacked, h_mb)
                out = jnp.moveaxis(y, 0, 1).reshape(
                    (s_len, -1) + y.shape[3:])
            else:
                y = apply(stacked, h.reshape((M, -1) + h.shape[1:]))
                out = y.reshape((-1,) + y.shape[2:])
            return self._run_entries(self._epi, Tensor(out, stop_gradient=True))
        finally:
            tracer.set_grad_enabled(og)
            self._swap_other(saved)

    def _loss_arrays(self, other_arrays, stacked, xs_mb, ys_mb, apply):
        """Full forward + loss on traced arrays.  xs_mb: (M, mb, ...)."""
        from ....dygraph import tracer
        from ....dygraph.tensor import Tensor

        t = self._forward_arrays(other_arrays, stacked, xs_mb, apply)
        og = tracer.set_grad_enabled(False)
        try:
            ys_flat = ys_mb.reshape((-1,) + ys_mb.shape[2:])
            res = self.loss_fn(t, Tensor(ys_flat, stop_gradient=True))
            loss = res._array if isinstance(res, Tensor) else jnp.asarray(res)
            return jnp.mean(loss)
        finally:
            tracer.set_grad_enabled(og)

    # -- compiled train step ----------------------------------------------
    def _get_step(self, mode: str, hyper: dict, clip_norm):
        key = (mode, tuple(sorted(hyper.items())), clip_norm)
        if key in self._step_cache:
            return self._step_cache[key]

        apply = spmd_pipeline(self._stage_fn, self.S, self.axis)

        def step(other, stacked, opt_state, lr, rng_key, xs, ys):
            from ....framework import random as fr

            def total(trainable):
                o, s = trainable
                # fresh per-step randomness for dropout etc.: rng_key is a
                # jit ARGUMENT, so each executed step draws new masks
                with fr.trace_rng_scope(rng_key):
                    return self._loss_arrays(o, s, xs, ys, apply)

            loss, grads = jax.value_and_grad(total)((other, stacked))
            flat_p, treedef = jax.tree_util.tree_flatten((other, stacked))
            flat_g = jax.tree_util.tree_leaves(grads)
            l2 = hyper.get("l2", 0.0)
            if l2:
                # regularization BEFORE clip — eager Optimizer.step order
                flat_g = [g + l2 * p.astype(g.dtype)
                          for p, g in zip(flat_p, flat_g)]
            if clip_norm is not None:
                flat_g = _clip_by_global_norm(flat_g, clip_norm)
            new_p, new_state = _apply_update(
                mode, hyper, flat_p, flat_g, opt_state, lr)
            new_other, new_stacked = jax.tree_util.tree_unflatten(treedef, new_p)
            return new_other, new_stacked, new_state, loss

        jitted = jax.jit(step, donate_argnums=(0, 1, 2))
        self._step_cache[key] = jitted
        return jitted

    def train_step(self, xs_mb, ys_mb, optimizer=None, lr: Optional[float] = None):
        """One pipelined fwd+bwd+update; returns the scalar loss array.

        ``optimizer`` is a paddle_tpu optimizer object (its mode/hyperparams
        are extracted; LR is read per-call so schedulers work) or None (SGD
        with ``lr``).
        """
        if optimizer is not None:
            mode, hyper, clip_norm = extract_opt_config(optimizer)
            lr_val = optimizer.get_lr()
            decay_fn = getattr(optimizer, "_apply_decay_param_fun", None)
            if mode == "adamw" and decay_fn is not None:
                # per-param decay decisions by name; a stacked block leaf is
                # decided by its template param (all blocks share the role)
                names = ([p.name for p in self._other_objs]
                         + [p.name for p in self._tmpl_objs])
                hyper = dict(hyper,
                             decay_mask=tuple(bool(decay_fn(n)) for n in names))
        else:
            mode, hyper, clip_norm = "sgd", {}, None
            lr_val = 1e-3 if lr is None else lr
        okey = (mode, tuple(sorted(hyper.items())))
        if self.opt_state is None or self._opt_key != okey:
            flat_p = jax.tree_util.tree_leaves((self.other, self.stacked))
            self.opt_state = _init_opt_state(mode, flat_p, hyper)
            self._opt_key = okey
        step = self._get_step(mode, hyper, clip_norm)
        from ....framework.random import next_rng_key

        self.other, self.stacked, self.opt_state, loss = step(
            self.other, self.stacked, self.opt_state,
            jnp.asarray(lr_val, jnp.float32), next_rng_key(),
            jnp.asarray(xs_mb), jnp.asarray(ys_mb))
        self._dirty = True
        return loss

    def eval_output(self, xs_mb):
        """Pipelined forward only (no loss): returns the epilogue output for
        the flattened batch.  The jitted forward is cached on the engine and
        TRACED IN EVAL MODE (dropout etc. off) regardless of the layers'
        current training flag — this is the inference path, and the flag is
        only read at trace time."""
        from ....dygraph.tensor import Tensor
        from ....nn.layer_base import Layer

        xs = jnp.asarray(xs_mb)
        if self._eval_fn is None:
            apply = spmd_pipeline(self._stage_fn, self.S, self.axis)
            mods = [l for l, _ in self._funcs if isinstance(l, Layer)]

            @jax.jit
            def fwd(other, stacked, xs):
                # body runs only at trace time: force eval mode for the trace
                was = [m.training for m in mods]
                for m in mods:
                    m.eval()
                try:
                    t = self._forward_arrays(other, stacked, xs, apply)
                    return t._array if isinstance(t, Tensor) else t
                finally:
                    for m, tr in zip(mods, was):
                        (m.train() if tr else m.eval())

            self._eval_fn = fwd
        return self._eval_fn(self.other, self.stacked, xs)
