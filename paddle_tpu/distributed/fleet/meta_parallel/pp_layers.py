"""Pipeline-parallel layer container.

Parity: ``/root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/pp_layers.py`` — ``LayerDesc``:44, ``SharedLayerDesc``:62,
``SegmentLayers``:23, ``PipelineLayer``:76.

TPU-first: PipelineLayer materializes ALL stages' layers in the single SPMD
program (params are jax global arrays); the stage partition is metadata the
pipeline ENGINE (pipeline_engine.py) uses to build the shard_map 1F1B
schedule over the 'pp' mesh axis with ppermute stage transfer — replacing
the reference's send_v2/recv_v2 NCCL p2p (pp_utils/p2p_communication.py).
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence, Union

from ....nn.layer_base import Layer, LayerList
from ... import mesh as mesh_mod


class LayerDesc:
    """Deferred layer construction (pp_layers.py:44)."""

    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("The input of LayerDesc should be Layer")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-shared layer across stages (pp_layers.py:62 — e.g. tied
    embedding/softmax).  In SPMD the weight is one global array, so sharing
    is simple aliasing."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Partition N layers into num_parts stages (pp_layers.py:23)."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self._layers_desc = layers_desc
        self.method = method
        self.num_parts = num_parts
        self.num_items = len(layers_desc)
        assert self.num_items >= self.num_parts, (
            "layer number should be greater than number of segments"
        )

    def do_segment(self) -> List[int]:
        if self.method == "uniform":
            return self.uniform(self.num_items, self.num_parts)
        if self.method.startswith("layer:"):
            # segment by layer-class name occurrences (pp_layers parity)
            cls_name = self.method.split(":", 1)[1]
            hits = [
                i for i, d in enumerate(self._layers_desc)
                if (d.layer_func.__name__ if isinstance(d, LayerDesc)
                    else d.__class__.__name__) == cls_name
            ]
            assert len(hits) >= self.num_parts
            per = len(hits) // self.num_parts
            result = [0] * (self.num_parts + 1)
            for p in range(1, self.num_parts):
                result[p] = hits[p * per]
            result[self.num_parts] = self.num_items
            return result
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts) -> List[int]:
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extra else 0)
        return result


class PipelineLayer(Layer):
    """Parity: pp_layers.py:76.  Holds the FULL layer stack (SPMD) plus the
    stage partition; run_function(stage) gives the stage's callable for the
    pipeline engine; plain __call__ runs the whole stack (single-program
    semantics, used for eval/export and as the autodiff reference)."""

    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, seq_major=False,
                 **kwargs):
        super().__init__()
        self._loss_fn = loss_fn
        self._topo = topology
        # activations flow [S, B, H] (GPTConfig.seq_major): the engine packs
        # microbatches on the BATCH dim (dim 1) instead of dim 0
        self.seq_major = seq_major
        if num_stages is None and topology is not None:
            num_stages = topology.get_dim("pipe")
        self._num_stages = num_stages or max(mesh_mod.axis_size("pp"), 1)
        self._layers_desc = list(layers)
        self._recompute_interval = recompute_interval

        seg = SegmentLayers(self._layers_desc, self._num_stages, seg_method)
        self.segment_parts = seg.do_segment()

        # build ALL layers (SPMD global program) — shared descs built once
        self._shared = {}
        built = []
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                if d.layer_name not in self._shared:
                    self._shared[d.layer_name] = d.build_layer()
                built.append((self._shared[d.layer_name], d.forward_func))
            elif isinstance(d, LayerDesc):
                built.append((d.build_layer(), None))
            elif isinstance(d, Layer):
                built.append((d, None))
            elif callable(d):
                built.append((d, None))
            else:
                raise TypeError(f"bad layer desc {d!r}")
        self._funcs = built
        self.run_functions = LayerList(
            [l for l, _ in built if isinstance(l, Layer)]
        )

    def get_num_stages(self):
        return self._num_stages

    def get_stage_from_index(self, layer_idx) -> int:
        for s in range(self._num_stages):
            if self.segment_parts[s] <= layer_idx < self.segment_parts[s + 1]:
                return s
        return self._num_stages - 1

    def stage_layers(self, stage: int):
        lo, hi = self.segment_parts[stage], self.segment_parts[stage + 1]
        return self._funcs[lo:hi]

    def run_function(self, stage: int) -> Callable:
        funcs = self.stage_layers(stage)

        def run(x):
            for layer, fwd in funcs:
                if fwd is not None:
                    x = fwd(layer, x)
                elif isinstance(x, tuple):
                    x = layer(*x)
                else:
                    x = layer(x)
            return x

        return run

    def forward(self, x):
        for layer, fwd in self._funcs:
            if fwd is not None:
                x = fwd(layer, x)
            elif isinstance(x, tuple):
                x = layer(*x)
            else:
                x = layer(x)
        return x
