from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RNGStatesTracker,
    RowParallelLinear, VocabParallelEmbedding, get_rng_state_tracker,
    model_parallel_random_seed,
)
from .pp_layers import LayerDesc, PipelineLayer, SegmentLayers, SharedLayerDesc  # noqa: F401
from .pipeline_engine import PipelineEngine, spmd_pipeline  # noqa: F401
from .parallel_wrappers import (  # noqa: F401
    DataParallelSPMD, PipelineParallel, ShardingParallel, TensorParallel,
)
from .sharding_optimizer import DygraphShardingOptimizer, HybridParallelOptimizer  # noqa: F401
