"""Megatron tensor-parallel layers.

Parity: ``/root/reference/python/paddle/distributed/fleet/meta_parallel/
parallel_layers/mp_layers.py`` — ``VocabParallelEmbedding``:30,
``ColumnParallelLinear``:97, ``RowParallelLinear``:170,
``ParallelCrossEntropy``:249 — and ``random.py:24`` RNGStatesTracker.

TPU-first: parameters carry a NamedSharding over the 'mp' mesh axis (GSPMD).
Eagerly and under jit, XLA propagates the shardings and inserts the identity/
allreduce pair the reference builds explicitly with c_identity /
c_allreduce_sum ops; under shard_map the same layers lower through the
``c_*`` kernels with named-axis collectives.  Either way the collectives ride
ICI — no NCCL rings (SURVEY.md §2.4).
"""

from __future__ import annotations

import contextlib

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ....framework import program as fw
from ....nn import functional as F
from ....nn.layer_base import Layer
from ....nn.initializer import Constant, Normal, XavierUniform
from .... import tensor_api as T
from ... import mesh as mesh_mod


def _place(param, *spec):
    """Attach a mesh sharding to an eager parameter (no-op in static mode or
    without a multi-device mesh)."""
    mesh = mesh_mod.get_mesh()
    if mesh is None or not fw.in_dygraph_mode() or param is None:
        return param
    names = [s for s in spec if s is not None]
    if any(mesh.shape.get(n, 1) > 1 for n in names) or not names:
        param._array = jax.device_put(param._array, NamedSharding(mesh, P(*spec)))
    return param


def _mp_degree() -> int:
    return mesh_mod.axis_size("mp")


class VocabParallelEmbedding(Layer):
    """Rows (vocab dim) sharded over 'mp' (mp_layers.py:30 parity)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 0.02),
        )
        _place(self.weight, "mp", None)
        self.weight.is_distributed = _mp_degree() > 1

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """Weight columns (output dim) sharded over 'mp' (mp_layers.py:97)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 gather_output=True, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform(),
        )
        _place(self.weight, None, "mp")
        self.weight.is_distributed = _mp_degree() > 1
        self.bias = (
            self.create_parameter(shape=[out_features], attr=None, is_bias=True)
            if has_bias else None
        )
        _place(self.bias, "mp")

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output and _mp_degree() > 1 and fw.in_dygraph_mode():
            mesh = mesh_mod.get_mesh()
            out._array = jax.device_put(out._array, NamedSharding(mesh, P()))
        return out


class RowParallelLinear(Layer):
    """Weight rows (input dim) sharded over 'mp'; the contraction over the
    sharded dim makes XLA emit the allreduce (mp_layers.py:170)."""

    def __init__(self, in_features, out_features, weight_attr=None, has_bias=True,
                 input_is_parallel=False, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=XavierUniform(),
        )
        _place(self.weight, "mp", None)
        self.weight.is_distributed = _mp_degree() > 1
        self.bias = (
            self.create_parameter(shape=[out_features], attr=None, is_bias=True)
            if has_bias else None
        )
        _place(self.bias)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)


class ParallelCrossEntropy(Layer):
    """Vocab-parallel softmax+CE (mp_layers.py:249; kernel parity:
    c_softmax_with_cross_entropy_op.cu)."""

    def __init__(self, mp_group=None, name=None):
        super().__init__()
        self._group = mp_group

    def forward(self, input, label):
        from ....ops.dispatch import dispatch

        ring = self._group.id if self._group is not None else 0
        outs = dispatch(
            "c_softmax_with_cross_entropy",
            {"Logits": [input], "Label": [label]},
            {"ring_id": ring},
        )
        return outs["Loss"][0]


# -- RNG state tracker (random.py:24 parity) --------------------------------


class RNGStatesTracker:
    """Named RNG states so dropout inside/outside TP regions decorrelates per
    mp rank (parity: fleet/meta_parallel/parallel_layers/random.py)."""

    def __init__(self):
        self.states_ = {}

    def reset(self):
        self.states_.clear()

    def add(self, name, seed):
        import jax

        self.states_[name] = jax.random.PRNGKey(int(seed))

    @contextlib.contextmanager
    def rng_state(self, name="model_parallel_rng"):
        from ....framework import random as fr

        if name not in self.states_:
            self.add(name, np.random.randint(0, 2**31))
        old = fr.get_rng_state()
        fr.set_rng_state(self.states_[name])
        try:
            yield
        finally:
            self.states_[name] = fr.get_rng_state()
            fr.set_rng_state(old)


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import numpy as _np

    seed = seed or _np.random.randint(0, 2**31)
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add("model_parallel_rng", seed)
