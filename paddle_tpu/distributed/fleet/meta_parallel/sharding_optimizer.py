"""ZeRO-style sharding optimizers.

Parity: ``/root/reference/python/paddle/distributed/fleet/meta_optimizers/
dygraph_optimizer/dygraph_sharding_optimizer.py`` (``DygraphShardingOptimizer``
:27, greedy ``_partition_parameters``:90) and
``hybrid_parallel_optimizer.py`` (HybridParallelOptimizer).

TPU-first: optimizer state (moments, etc.) is SHARDED over the 'sharding'
mesh axis via NamedSharding on dim 0 — XLA keeps the state resident 1/N per
device and inserts the reduce-scatter / all-gather pair around the update,
which is exactly ZeRO stage 1 communication (SURVEY.md §2.3 Sharding row).
"""

from __future__ import annotations

from typing import List

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ....dygraph.tensor import Tensor
from ... import mesh as mesh_mod


class DygraphShardingOptimizer:
    """Wraps an inner optimizer; shards its accumulators over 'sharding'."""

    def __init__(self, optimizer=None, hcg=None, user_defined_strategy=None,
                 params=None, inner_optimizer_class=None, **inner_kw):
        if optimizer is None and inner_optimizer_class is not None:
            optimizer = inner_optimizer_class(parameters=params, **inner_kw)
        self._inner = optimizer
        self._hcg = hcg
        self._axis = "sharding"
        self._size = mesh_mod.axis_size(self._axis)
        self._wrap_accumulators()

    # parity: greedy by-size partition (rank -> params) for bookkeeping
    def _partition_parameters(self) -> dict:
        mapping = {i: [] for i in range(max(self._size, 1))}
        sizes = [0] * max(self._size, 1)
        params = self._inner._parameter_list or []
        for p in sorted(params, key=lambda q: -int(np.prod(q.shape))):
            r = int(np.argmin(sizes))
            mapping[r].append(p)
            sizes[r] += int(np.prod(p.shape))
        return mapping

    def _wrap_accumulators(self):
        if self._size <= 1:
            return
        inner = self._inner
        orig = inner._add_accumulator
        mesh = mesh_mod.get_mesh()

        def sharded_add(name, param, fill_value=0.0, shape=None, dtype=None):
            acc = orig(name, param, fill_value=fill_value, shape=shape, dtype=dtype)
            if isinstance(acc, Tensor) and acc._array.ndim >= 1 and (
                acc._array.shape[0] % self._size == 0
            ):
                acc._array = jax.device_put(
                    acc._array, NamedSharding(mesh, P(self._axis))
                )
            return acc

        inner._add_accumulator = sharded_add

    # -- delegation --------------------------------------------------------
    def step(self):
        return self._inner.step()

    def minimize(self, *a, **k):
        return self._inner.minimize(*a, **k)

    def clear_grad(self):
        return self._inner.clear_grad()

    clear_gradients = clear_grad

    def get_lr(self):
        return self._inner.get_lr()

    def set_lr(self, v):
        return self._inner.set_lr(v)

    def state_dict(self):
        return self._inner.state_dict()

    def set_state_dict(self, s):
        return self._inner.set_state_dict(s)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class HybridParallelOptimizer:
    """Parity: hybrid_parallel_optimizer.py — wraps the user optimizer for
    hybrid runs; grad clipping stays correct because gradients are GLOBAL
    arrays (mp-sharded tensors still produce the true global norm)."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if strategy is not None and strategy.sharding and mesh_mod.axis_size("sharding") > 1:
            self._inner_wrapped = DygraphShardingOptimizer(optimizer, hcg)
        else:
            self._inner_wrapped = optimizer

    def step(self):
        return self._inner_wrapped.step()

    def minimize(self, *a, **k):
        return self._inner_wrapped.minimize(*a, **k)

    def clear_grad(self):
        return self._inner_wrapped.clear_grad()

    clear_gradients = clear_grad

    def __getattr__(self, name):
        return getattr(self._inner, name)
