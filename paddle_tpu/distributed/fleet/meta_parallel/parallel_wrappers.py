"""Model wrappers selected by ``fleet.distributed_model``.

Parity: ``/root/reference/python/paddle/distributed/fleet/meta_parallel/
{tensor_parallel.py, pipeline_parallel.py, sharding_parallel.py}`` and
``fleet_base.py:836`` wrapper selection.

TPU-first semantics: data/tensor/sharding parallelism are expressed as
ARRAY SHARDINGS on the hybrid mesh — forward code is unchanged and XLA
inserts the collectives (no Reducer, no bucketed allreduce: gradients of
replicated params over sharded batches psum automatically).  Pipeline
parallelism routes train_batch through the shard_map 1F1B engine.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ....nn.layer_base import Layer
from ....dygraph.tensor import Tensor
from ... import mesh as mesh_mod


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, *a, **k):
        return self._layers.parameters(*a, **k)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self


class DataParallelSPMD(MetaParallelBase):
    """DP by batch sharding: replicate params, shard inputs on 'dp'.

    Role parity: dygraph DataParallel + C++ Reducer
    (``imperative/reducer.cc`` bucketed overlapped allreduce) — unnecessary
    under XLA: the grad of a replicated param w.r.t. a dp-sharded batch IS a
    psum, inserted and overlapped by the compiler (SURVEY.md §7 layer 6).
    """

    def _prepare_for_model(self):
        mesh = mesh_mod.get_mesh()
        if mesh is None:
            return
        repl = NamedSharding(mesh, P())
        for p in self._layers.parameters():
            if isinstance(p, Tensor) and not getattr(p, "is_distributed", False):
                p._array = jax.device_put(p._array, repl)

    def forward(self, *inputs, **kwargs):
        ins = [
            Tensor(mesh_mod.shard_batch(i._array if isinstance(i, Tensor) else np.asarray(i)),
                   stop_gradient=getattr(i, "stop_gradient", True))
            if isinstance(i, (Tensor, np.ndarray)) else i
            for i in inputs
        ]
        return self._layers(*ins, **kwargs)

    def scale_loss(self, loss):
        return loss  # grads are exact global means already

    def apply_collective_grads(self):
        pass  # XLA inserted the reductions in backward


class TensorParallel(DataParallelSPMD):
    """TP: mp_layers carry 'mp' shardings; batch still shards over 'dp'."""


class ShardingParallel(DataParallelSPMD):
    """ZeRO-style sharding: optimizer-state sharding is applied by
    DygraphShardingOptimizer; param placement stays replicated here."""


class PipelineParallel(MetaParallelBase):
    """paddle PipelineParallel API over the shard_map 1F1B engine."""

    def __init__(self, layers, hcg, strategy=None, loss_fn=None):
        super().__init__(layers, hcg, strategy)
        self._engine = None
        self._loss_fn = loss_fn or getattr(layers, "_loss_fn", None)
        acc = 1
        if strategy is not None:
            acc = strategy.pipeline_configs.get("accumulate_steps", 1)
        self.accumulate_steps = acc

    def _get_engine(self):
        if self._engine is None:
            from .pipeline_engine import PipelineEngine

            self._engine = PipelineEngine(self._layers, loss_fn=self._loss_fn)
        return self._engine

    def train_batch(self, data, optimizer=None, lr_scheduler=None, scaler=None):
        """Parity: pipeline_parallel.py:114 train_batch — splits data into
        ``accumulate_steps`` microbatches, runs the pipelined fwd+bwd and the
        optimizer update in ONE jitted step (the optimizer's mode, betas,
        weight decay, and global-norm clip are honored; its LR — scheduled or
        constant — is read every call)."""
        x, y = data
        xa = x._array if isinstance(x, Tensor) else np.asarray(x)
        ya = y._array if isinstance(y, Tensor) else np.asarray(y)
        M = max(self.accumulate_steps, 1)
        assert xa.shape[0] % M == 0, (
            f"batch {xa.shape[0]} must divide into accumulate_steps={M}"
        )
        import jax.numpy as jnp

        xs = jnp.reshape(xa, (M, xa.shape[0] // M) + xa.shape[1:])
        ys = jnp.reshape(ya, (M, ya.shape[0] // M) + ya.shape[1:])
        engine = self._get_engine()
        loss = engine.train_step(xs, ys, optimizer=optimizer)
        # only an EXPLICIT scheduler is stepped (reference _optimizer_step
        # semantics) — callers stepping optimizer._learning_rate themselves
        # must not get a double advance
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss, stop_gradient=True)

    def state_dict(self, *a, **k):
        if self._engine is not None:
            self._engine.sync_to_layers()
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        out = self._layers.set_state_dict(*a, **k)
        if self._engine is not None:
            self._engine.sync_from_layers()
        return out

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        if self._engine is not None:
            # pipelined jitted forward on the engine's device copies — no
            # host round-trip of the weights
            xa = x._array if isinstance(x, Tensor) else np.asarray(x)
            out = Tensor(self._engine.eval_output(xa[None]),
                         stop_gradient=True)
        else:
            out = self._layers(x if isinstance(x, Tensor) else Tensor(np.asarray(x)))
        if compute_loss and self._loss_fn is not None:
            return self._loss_fn(out, y)
        return out
