"""Launcher plumbing: cluster description + per-rank env protocol.

Parity: ``/root/reference/python/paddle/distributed/fleet/launch_utils.py``
(``get_cluster``:271, ``start_local_trainers``:457 building the
``PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS / FLAGS_selected_gpus`` env) — TPU-first: the env
additionally carries ``PADDLE_MASTER``/``MASTER_PORT`` so
``init_parallel_env`` can call ``jax.distributed.initialize`` (the
rendezvous the reference does with its own TCP store + NCCL id exchange).
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


def find_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class TrainerProc:
    rank: int
    proc: subprocess.Popen
    log_path: Optional[str] = None
    log_file: Optional[object] = None


@dataclass
class Cluster:
    """One node's worth of trainers (multi-node: this process launches only
    the local ranks; `ips` orders the global ranks)."""

    ips: List[str]
    nproc_per_node: int
    master: str
    master_port: int
    node_rank: int = 0

    @property
    def world_size(self) -> int:
        return len(self.ips) * self.nproc_per_node

    def endpoints(self) -> List[str]:
        eps = []
        base_port = self.master_port + 1
        for ip in self.ips:
            for i in range(self.nproc_per_node):
                eps.append(f"{ip}:{base_port + i}")
        return eps

    def local_ranks(self) -> List[int]:
        start = self.node_rank * self.nproc_per_node
        return list(range(start, start + self.nproc_per_node))


def rank_env(cluster: Cluster, rank: int, devices: Optional[str] = None
             ) -> Dict[str, str]:
    """The PADDLE_* env protocol for one trainer (launch_utils.py:457)."""
    eps = cluster.endpoints()
    local = rank % cluster.nproc_per_node
    env = {
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_CURRENT_ENDPOINT": eps[rank],
        "PADDLE_TRAINERS_NUM": str(cluster.world_size),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(eps),
        "PADDLE_RANK_IN_NODE": str(local),
        "PADDLE_LOCAL_DEVICE_IDS": devices if devices is not None else str(local),
        "PADDLE_MASTER": cluster.master,
        "MASTER_ADDR": cluster.master,
        "MASTER_PORT": str(cluster.master_port),
        "POD_IP": cluster.ips[cluster.node_rank],
        "FLAGS_selected_tpus": devices if devices is not None else str(local),
    }
    return env


def start_local_trainers(cluster: Cluster, cmd: List[str],
                         base_env: Optional[Dict[str, str]] = None,
                         log_dir: Optional[str] = None,
                         devices: Optional[List[str]] = None
                         ) -> List[TrainerProc]:
    if devices and len(devices) < cluster.nproc_per_node:
        raise ValueError(
            f"--devices lists {len(devices)} device id(s) but "
            f"nproc_per_node={cluster.nproc_per_node}; provide one id per "
            f"local trainer")
    procs = []
    for rank in cluster.local_ranks():
        env = dict(base_env if base_env is not None else os.environ)
        dev = devices[rank % cluster.nproc_per_node] if devices else None
        env.update(rank_env(cluster, rank, dev))
        log_file = log_path = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, f"workerlog.{rank}")
            log_file = open(log_path, "w")
        p = subprocess.Popen(cmd, env=env, stdout=log_file, stderr=log_file)
        procs.append(TrainerProc(rank=rank, proc=p, log_path=log_path,
                                 log_file=log_file))
    return procs


def watch_local_trainers(procs: List[TrainerProc], timeout: Optional[float]
                         = None) -> int:
    """Wait for all trainers; on the first failure, terminate the rest
    (launch_utils.py watch_local_trainers / terminate semantics).  Returns
    the overall exit code."""
    deadline = time.time() + timeout if timeout else None
    alive = {t.rank: t for t in procs}
    rc = 0
    try:
        while alive:
            for rank, t in list(alive.items()):
                code = t.proc.poll()
                if code is None:
                    continue
                del alive[rank]
                if code != 0:
                    sys.stderr.write(
                        f"trainer {rank} exited with code {code}"
                        + (f" (log: {t.log_path})" if t.log_path else "")
                        + "\n")
                    rc = rc or code
            if alive and rc:
                break  # one failed: stop waiting, kill the rest
            if deadline and time.time() > deadline:
                sys.stderr.write("launch: timeout waiting for trainers\n")
                rc = rc or 124
                break
            time.sleep(0.2)
    finally:
        for t in alive.values():
            try:
                t.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        for t in alive.values():
            try:
                t.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                t.proc.kill()
        for t in procs:
            if t.log_file:
                t.log_file.close()
    return rc


def watch_local_trainers_elastic(procs: List[TrainerProc], manager,
                                 timeout: Optional[float] = None) -> int:
    """watch_local_trainers + the ElasticManager watchdog (the reference's
    ``elastic.py:171-204`` watch loop fused with ``launch_utils.py:73``
    ``_check_procs``): besides process exits, a rank whose heartbeat goes
    stale (hung, not crashed) also fails the round.  Returns the exit
    code; callers decide whether to restart the world."""
    from .fleet.elastic import ElasticStatus

    deadline = time.time() + timeout if timeout else None
    alive = {t.rank: t for t in procs}
    rc = 0
    try:
        while alive:
            for rank, t in list(alive.items()):
                code = t.proc.poll()
                if code is None:
                    continue
                del alive[rank]
                if code != 0:
                    sys.stderr.write(
                        f"elastic: trainer {rank} exited with code {code}"
                        + (f" (log: {t.log_path})" if t.log_path else "")
                        + "\n")
                    rc = rc or code
            if alive and rc:
                break  # crash: stop the round, kill the rest
            status = manager.watch()
            if status == ElasticStatus.RESTART and alive:
                stale = manager.failed_ranks()
                sys.stderr.write(
                    f"elastic: stale heartbeat from rank(s) {stale} — "
                    f"restarting the world\n")
                rc = rc or 99  # heartbeat-timeout code
                break
            if deadline and time.time() > deadline:
                sys.stderr.write("elastic: round timeout\n")
                rc = rc or 124
                break
            time.sleep(0.2)
    finally:
        for t in alive.values():
            try:
                t.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        for t in alive.values():
            try:
                # short grace: restart-the-world wants the round torn down
                # promptly (jax's preemption notifier swallows SIGTERM in
                # trainers that don't install their own handler)
                t.proc.wait(timeout=3)
            except subprocess.TimeoutExpired:
                t.proc.kill()
        for t in procs:
            if t.log_file:
                t.log_file.close()
    return rc


def run_elastic(cluster: Cluster, cmd: List[str],
                base_env: Optional[Dict[str, str]] = None,
                log_dir: Optional[str] = None,
                devices: Optional[List[str]] = None,
                max_restarts: int = 3,
                timeout: Optional[float] = None) -> int:
    """Restart-the-world elastic loop (reference ElasticManager semantics:
    any rank failing ends the round; the whole job relaunches and resumes
    from the auto_checkpoint state under the same PADDLE_JOB_ID)."""
    from .fleet.elastic import ElasticManager

    env = dict(base_env if base_env is not None else os.environ)
    store = env.setdefault(
        "PADDLE_ELASTIC_STORE",
        os.path.join(log_dir or "/tmp", "paddle_tpu_elastic_store"))
    manager = ElasticManager(store_dir=store, rank=-1,
                             world_size=cluster.world_size)
    restarts = 0
    while True:
        manager.clear()
        attempt_log = (os.path.join(log_dir, f"attempt_{restarts}")
                       if log_dir else None)
        procs = start_local_trainers(cluster, cmd, base_env=env,
                                     log_dir=attempt_log, devices=devices)
        rc = watch_local_trainers_elastic(procs, manager, timeout=timeout)
        if rc == 0:
            return 0
        restarts += 1
        if restarts > max_restarts:
            sys.stderr.write(
                f"elastic: giving up after {max_restarts} restart(s), "
                f"rc={rc}\n")
            return rc
        sys.stderr.write(
            f"elastic: restarting the world (attempt {restarts}/"
            f"{max_restarts})\n")
        time.sleep(1.0)
