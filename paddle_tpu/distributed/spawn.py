"""``paddle.distributed.spawn`` — in-Python multi-process launch.

Parity: ``/root/reference/python/paddle/distributed/spawn.py`` (``spawn``:
func + args + nprocs + join, per-process env prepared by
``_prepare_trainer_env``).  Each child gets the same ``PADDLE_*`` protocol
the CLI launcher produces, then runs ``func(*args)``; rank is available via
``paddle.distributed.get_rank()`` / ``ParallelEnv`` as in the reference.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import traceback
from typing import Optional, Sequence

from .launch_utils import Cluster, find_free_port, rank_env


class MultiprocessContext:
    """Parity: spawn.py MultiprocessContext — join/terminate over the pool."""

    def __init__(self, processes, error_queues):
        self.processes = processes
        self.error_queues = error_queues

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for ALL ranks concurrently; terminate the pool on the first
        failure (a serial per-rank join would deadlock when a crashed later
        rank leaves an earlier rank blocked in a collective)."""
        import time

        deadline = time.time() + timeout if timeout is not None else None
        failed = []
        while True:
            alive = [p for p in self.processes if p.exitcode is None]
            failed = [(i, p.exitcode) for i, p in enumerate(self.processes)
                      if p.exitcode not in (0, None)]
            if failed or not alive:
                break
            if deadline and time.time() > deadline:
                return False
            time.sleep(0.1)
        if failed:
            for p in self.processes:
                if p.is_alive():
                    p.terminate()
            for p in self.processes:
                p.join(10)
            msgs = []
            for i, code in failed:
                err = ""
                try:
                    if not self.error_queues[i].empty():
                        err = self.error_queues[i].get()
                except OSError:
                    pass
                msgs.append(f"rank {i} exited with code {code}\n{err}")
            raise RuntimeError("spawn: trainer failure:\n" + "\n".join(msgs))
        return True


def _worker(func, args, env, error_queue):
    try:
        os.environ.update(env)
        func(*args)
    except KeyboardInterrupt:
        pass
    except Exception:
        error_queue.put(traceback.format_exc())
        raise


def spawn(func, args: Sequence = (), nprocs: int = -1, join: bool = True,
          daemon: bool = False, **options):
    """Spawn ``nprocs`` processes running ``func(*args)`` with the PADDLE_*
    env protocol installed (reference spawn.py semantics)."""
    if nprocs == -1:
        try:
            import jax

            nprocs = max(jax.local_device_count(), 1)
        except Exception:
            nprocs = 1
    cluster = Cluster(ips=["127.0.0.1"], nproc_per_node=nprocs,
                      master="127.0.0.1",
                      master_port=int(options.get("master_port")
                                      or find_free_port()))
    ctx = mp.get_context(options.get("start_method", "spawn"))
    processes, error_queues = [], []
    for rank in range(nprocs):
        env = rank_env(cluster, rank, devices=str(rank))
        env.update(options.get("env", {}))
        q = ctx.SimpleQueue()
        p = ctx.Process(target=_worker, args=(func, tuple(args), env, q),
                        daemon=daemon)
        p.start()
        processes.append(p)
        error_queues.append(q)
    context = MultiprocessContext(processes, error_queues)
    if not join:
        return context
    context.join()
    return context
