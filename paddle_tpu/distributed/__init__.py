"""``paddle.distributed`` — collective API + fleet over jax device meshes.

Parity: ``/root/reference/python/paddle/distributed/`` (collective.py,
parallel.py, fleet/).  SURVEY.md §2.4: the rendezvous + ring-id + comm-stream
machinery of the reference maps to ``jax.distributed`` + mesh axes; the
``c_*`` collective ops run inside pjit/shard_map over ICI.
"""

from .env import get_rank, get_world_size  # noqa: F401

from .parallel import init_parallel_env, ParallelEnv  # noqa: F401
from .collective import (  # noqa: F401
    all_gather, all_reduce, alltoall, barrier, broadcast, new_group,
    recv, reduce, scatter, send, split, wait, ReduceOp,
)
from . import fleet  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import launch  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401

# -- surface-completeness batch (reference distributed/__init__.py) ---------
from .collective import get_group  # noqa: F401
from . import utils  # noqa: F401
from . import cloud_utils  # noqa: F401


class _PSScopedDataset:
    """PS-training datasets (fleet/dataset/: InMemoryDataset:?,
    QueueDataset, BoxPSDataset) feed the C++ DistMultiTrainer loop — the
    parameter-server path the BASELINE north star leaves untouched.  The
    names exist so reference imports resolve; instantiation points at the
    collective-path alternative (paddle.io.DataLoader)."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            f"{type(self).__name__} feeds the parameter-server trainer "
            "loop, which the BASELINE north star scopes out; use "
            "paddle.io.DataLoader on the collective path instead")


class InMemoryDataset(_PSScopedDataset):
    pass


class QueueDataset(_PSScopedDataset):
    pass


class BoxPSDataset(_PSScopedDataset):
    pass


class CountFilterEntry:
    """PS sparse-table admission config (distributed/entry_attr) — held
    for strategy-config parity; the PS tables themselves are scoped out."""

    def __init__(self, count_filter: int):
        self.count_filter = int(count_filter)

    def to_attr(self):
        return f"count_filter_entry:{self.count_filter}"


class ProbabilityEntry:
    def __init__(self, probability: float):
        self.probability = float(probability)

    def to_attr(self):
        return f"probability_entry:{self.probability}"
