"""``paddle.distributed`` — collective API + fleet over jax device meshes.

Parity: ``/root/reference/python/paddle/distributed/`` (collective.py,
parallel.py, fleet/).  SURVEY.md §2.4: the rendezvous + ring-id + comm-stream
machinery of the reference maps to ``jax.distributed`` + mesh axes; the
``c_*`` collective ops run inside pjit/shard_map over ICI.
"""

from .env import get_rank, get_world_size  # noqa: F401

from .parallel import init_parallel_env, ParallelEnv  # noqa: F401
from .collective import (  # noqa: F401
    all_gather, all_reduce, alltoall, barrier, broadcast, new_group,
    recv, reduce, scatter, send, split, wait, ReduceOp,
)
from . import fleet  # noqa: F401
from .spawn import spawn  # noqa: F401
from . import launch  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import load_state_dict, save_state_dict  # noqa: F401
