"""Flight recorder: a bounded black box of per-step engine decisions.

Counters say HOW OFTEN the engine preempted; they cannot say WHICH
request was evicted at step 412, by whom, or why.  The flight recorder
keeps the last N structured decision records — admissions, preemptions
with victim + reason, handoffs in/out with byte counts, alloc failures,
window recycles, injected faults, terminals — in a ring buffer stamped
on the ENGINE clock (the FaultPlan virtual clock under chaos), so two
replays of the same seeded chaos plan produce byte-identical dumps.

``engine.dump_debug()`` returns the buffer as part of a debug snapshot;
a real exception escaping ``engine.step()`` (the r10 re-park path)
dumps it to ``metrics_dir/flight_crash.json`` before re-raising, so
every postmortem starts with the black box, not a stack trace alone.

Dependency-free (stdlib ``collections`` + scoped ``json``), default-off
(``ServingEngine(flight=True)``), O(1) per record.
"""

from __future__ import annotations

import collections
import json
import time
from typing import Callable, Optional

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Bounded ring buffer of structured decision records.

    ``capacity`` bounds memory (oldest records drop first; ``dropped``
    counts them).  ``clock`` is the seconds source records are stamped
    with — the engine passes its own, so chaos replays under the
    virtual clock are bit-deterministic.
    """

    def __init__(self, capacity: int = 1024,
                 clock: Optional[Callable[[], float]] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        self.capacity = int(capacity)
        # sanctioned fallback binding: attach_flight always injects the
        # engine clock; a standalone recorder defaults to real time
        self._clock = clock or time.monotonic  # graftlint: allow=determinism
        self._t0 = self._clock()
        self._records = collections.deque(maxlen=self.capacity)
        self.recorded = 0

    @property
    def dropped(self) -> int:
        return self.recorded - len(self._records)

    def record(self, kind: str, step: int, **fields) -> None:
        """Append one decision record; O(1), oldest-first eviction."""
        rec = {"kind": kind, "step": int(step),
               "t": round(self._clock() - self._t0, 9)}
        rec.update(fields)
        self._records.append(rec)
        self.recorded += 1

    def __len__(self) -> int:
        return len(self._records)

    def to_json(self) -> dict:
        return {"capacity": self.capacity,
                "recorded": self.recorded,
                "dropped": self.dropped,
                "records": list(self._records)}

    def dumps(self) -> str:
        """Canonical JSON text: sorted keys, compact separators — two
        replays of one chaos seed compare byte-for-byte."""
        return json.dumps(self.to_json(), sort_keys=True,
                          separators=(",", ":"))

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.dumps())
        return path
