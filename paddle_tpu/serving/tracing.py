"""Request-lifecycle tracing: Chrome trace-event JSON from the engine.

Role parity: the reference fork's profiler pairs host ``RecordEvent``
span tables with a CUPTI ``DeviceTracer`` whose output opens in
``chrome://tracing`` (PAPER.md, ``platform/profiler.h``).  Our serving
engine had neither: a request's life — queued, admitted, chunk-prefilled,
decoding, preempted, recomputed, terminal — happened invisibly inside
the host loop.  This module records it as Chrome trace-event JSON
(the ``{"traceEvents": [...]}`` format), openable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``:

  * **one track per request** (pid ``PID_REQUESTS``, tid = rid):
    ``queued`` and ``resident`` B/E spans, ``prefill_chunk`` spans per
    chunk, instants for ``first_token`` / ``preempt`` / ``cow_clone`` /
    the terminal reason — a preempted request visibly bounces back to a
    ``queued`` span and re-prefills;
  * **one engine track** (pid ``PID_ENGINE``): per-step ``admit`` /
    ``prefill`` / ``decode`` phase X (complete) events, so slow steps
    and fault-aborted phases line up against request state;
  * **host spans** (pid ``PID_HOST``): :func:`attach_profiler` bridges
    ``paddle_tpu.profiler.RecordEvent`` — every host span recorded
    anywhere in-process lands on the SAME timeline as the engine
    phases, the unification the reference gets from one profiler state.

B/E discipline: :meth:`TraceRecorder.end` pops the recorder's own
per-track stack and names the E event from it, so emitted B/E pairs are
balanced BY CONSTRUCTION inside a track (asserted over chaos runs in
tests/test_metrics.py).  Engine phases deliberately use X events — an
injected mid-phase fault can abort a phase, and an X event written after
the fact cannot dangle.

Timestamps are microseconds on one monotonic base (``time.perf_counter``
by default; injectable for tests).  Dump with :meth:`save` and load the
file straight into Perfetto.

Cluster tracing (r16): every recorder can carry a **replica identity**
(:meth:`TraceRecorder.set_replica`) that namespaces its pid lanes
(``replica * PID_STRIDE + base``) and prefixes lane names, so N
replicas merge into one timeline without colliding.  The Router gets
its own ``PID_ROUTER`` lane.  Cross-replica handoffs are stitched with
Chrome **flow events** (``ph: "s"/"t"/"f"`` sharing an ``id`` + ``cat``)
— Perfetto draws one arrow from the prefill replica's export through
the router pump into the decode replica's ingest.  :func:`merge_traces`
rebases N recorders sharing one clock onto the earliest ``_t0`` and
returns a single Perfetto-loadable dict; :func:`validate_trace` asserts
well-formedness (balanced B/E per track, every flow start terminated).
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional

__all__ = ["TraceRecorder", "PID_ENGINE", "PID_REQUESTS", "PID_HOST",
           "PID_ROUTER", "PID_STRIDE", "FLOW_CAT_HANDOFF", "flow_id",
           "merge_traces", "validate_trace", "save_trace",
           "attach_profiler", "detach_profiler"]

#: Process lanes of the unified timeline.
PID_ENGINE = 1      # engine step phases (admit/prefill/decode X events)
PID_REQUESTS = 2    # one thread per request (tid = rid)
PID_HOST = 3        # profiler.RecordEvent host spans
PID_ROUTER = 4      # router decisions + handoff pump (cluster runs)

#: Replica pid namespace: replica ``i``'s lanes live at
#: ``i * PID_STRIDE + base`` so merged cluster traces never collide.
PID_STRIDE = 10

#: Category tag shared by handoff flow events (s/t/f bind on (cat, id)).
FLOW_CAT_HANDOFF = "handoff"


def flow_id(rid: int, seq: int) -> int:
    """Globally unique flow id for one handoff: rids are fleet-unique
    (one shared allocator) and ``seq`` is the exporting engine's
    monotonic span sequence, so re-exports of one rid (degraded handoff
    then re-handoff) get distinct arrows."""
    return (int(rid) << 20) | (int(seq) & 0xFFFFF)


class TraceRecorder:
    """Append-only Chrome trace-event recorder.

    ``clock`` is a zero-arg seconds source (default
    ``time.perf_counter``); every event stamps ``ts`` in microseconds
    relative to the recorder's construction, so traces start at t=0.
    """

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._clock = clock or time.perf_counter
        self._t0 = self._clock()
        self.events: List[dict] = []
        # open-span stack per (pid, tid): end() pops, so B/E pairs are
        # balanced by construction within a track
        self._open: Dict[tuple, List[str]] = {}
        self._named_pids = set()
        self.replica: Optional[int] = None
        self.replica_name: Optional[str] = None

    # -- replica identity --------------------------------------------------

    def set_replica(self, index: int, name: Optional[str] = None) -> None:
        """Namespace this recorder's lanes under replica ``index``.

        After this, :meth:`pid` maps base lanes into the replica's pid
        block and lane labels gain an ``r{index}`` (or ``name``) prefix.
        Must be called before any lane is named."""
        if self._named_pids:
            raise ValueError("set_replica must precede process_name")
        self.replica = int(index)
        self.replica_name = name or f"r{index}"

    def pid(self, base: int) -> int:
        """Map a base lane (PID_ENGINE, ...) into this recorder's
        replica namespace; identity when no replica is set."""
        if self.replica is None:
            return base
        return self.replica * PID_STRIDE + base

    def lane_label(self, label: str) -> str:
        if self.replica is None:
            return label
        return f"{self.replica_name}: {label}"

    # -- time -------------------------------------------------------------

    def now_us(self) -> float:
        return (self._clock() - self._t0) * 1e6

    def _ev(self, name: str, ph: str, ts: float, pid: int, tid: int,
            args: Optional[dict] = None, **extra) -> dict:
        ev = {"name": name, "ph": ph, "ts": round(ts, 3),
              "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        ev.update(extra)
        self.events.append(ev)
        return ev

    def process_name(self, pid: int, name: str) -> None:
        """Label a pid lane (idempotent) — Perfetto shows the name."""
        if pid not in self._named_pids:
            self._named_pids.add(pid)
            self._ev("process_name", "M", 0.0, pid, 0,
                     args={"name": name})

    # -- spans ------------------------------------------------------------

    def begin(self, name: str, pid: int, tid: int,
              args: Optional[dict] = None) -> None:
        self._open.setdefault((pid, tid), []).append(name)
        self._ev(name, "B", self.now_us(), pid, tid, args)

    def end(self, pid: int, tid: int, args: Optional[dict] = None) -> str:
        """Close the innermost open span on (pid, tid); returns its name.
        A track with nothing open raises — the engine's lifecycle logic
        is the state machine, and an unmatched end means it broke."""
        stack = self._open.get((pid, tid))
        if not stack:
            raise ValueError(f"no open span on track ({pid}, {tid})")
        name = stack.pop()
        self._ev(name, "E", self.now_us(), pid, tid, args)
        return name

    def open_span(self, pid: int, tid: int) -> Optional[str]:
        """Name of the innermost open span on the track, or None."""
        stack = self._open.get((pid, tid))
        return stack[-1] if stack else None

    def instant(self, name: str, pid: int, tid: int,
                args: Optional[dict] = None) -> None:
        self._ev(name, "i", self.now_us(), pid, tid, args, s="t")

    def complete(self, name: str, start_s: float, dur_s: float, pid: int,
                 tid: int, args: Optional[dict] = None) -> None:
        """An X event from absolute clock seconds (same base as
        ``clock``) — used for engine phases and bridged host spans."""
        self._ev(name, "X", (start_s - self._t0) * 1e6, pid, tid, args,
                 dur=round(dur_s * 1e6, 3))

    # -- flow events -------------------------------------------------------
    #
    # s/t/f events sharing (cat, id) draw one arrow across lanes in
    # Perfetto.  "s"/"t" bind to the NEXT slice on their track by
    # timestamp; "f" with bp="e" binds to the enclosing slice.  The
    # engine emits "s" inside the exporting request's resident span,
    # the router "t" inside its pump span, the ingesting engine "f"
    # inside the request's new queued span.

    def flow_start(self, name: str, pid: int, tid: int, flow_id: int,
                   cat: str = FLOW_CAT_HANDOFF) -> None:
        self._ev(name, "s", self.now_us(), pid, tid, cat=cat,
                 id=int(flow_id))

    def flow_step(self, name: str, pid: int, tid: int, flow_id: int,
                  cat: str = FLOW_CAT_HANDOFF) -> None:
        self._ev(name, "t", self.now_us(), pid, tid, cat=cat,
                 id=int(flow_id))

    def flow_finish(self, name: str, pid: int, tid: int, flow_id: int,
                    cat: str = FLOW_CAT_HANDOFF) -> None:
        self._ev(name, "f", self.now_us(), pid, tid, cat=cat,
                 id=int(flow_id), bp="e")

    # -- output -----------------------------------------------------------

    def to_json(self) -> dict:
        return {"traceEvents": list(self.events),
                "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_json(), f)
        return path


# -- cluster merge + validation ----------------------------------------------

def merge_traces(recorders) -> dict:
    """Merge N recorders into one Perfetto-loadable trace dict.

    All recorders must share one clock (the Router constructs them that
    way); each recorder's events are rebased onto the EARLIEST ``_t0``
    — the same delta idiom snapshot restore uses for the engine clock —
    so spans keep their true relative offsets.  Metadata ("M") events
    stay at ts 0 and are deduplicated per (pid, name)."""
    recorders = [r for r in recorders if r is not None]
    if not recorders:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(r._t0 for r in recorders)
    events: List[dict] = []
    seen_meta = set()
    for r in recorders:
        shift_us = (r._t0 - base) * 1e6
        for ev in r.events:
            if ev["ph"] == "M":
                key = (ev["pid"], ev["name"],
                       ev.get("args", {}).get("name"))
                if key in seen_meta:
                    continue
                seen_meta.add(key)
                events.append(dict(ev))
            else:
                out = dict(ev)
                out["ts"] = round(out["ts"] + shift_us, 3)
                events.append(out)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def save_trace(trace: dict, path: str) -> str:
    """Write a trace dict (e.g. from :func:`merge_traces`) to ``path``
    — kept here so callers outside the scoped-import set (router.py)
    never touch ``json`` directly."""
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


def validate_trace(trace) -> dict:
    """Assert Chrome-trace well-formedness; returns summary counts.

    Checks: every "B" has a matching "E" per (pid, tid) in stack order,
    every flow "s" has exactly ONE "f" per (cat, id) (with optional "t"
    steps in between), "X" events carry a non-negative ``dur``, and all
    timestamps are non-negative.  Raises ``ValueError`` on violation.
    Accepts a trace dict (``{"traceEvents": ...}``), a recorder, or a
    raw event list."""
    if hasattr(trace, "events"):
        events = trace.events
    elif isinstance(trace, dict):
        events = trace["traceEvents"]
    else:
        events = trace
    depth: Dict[tuple, int] = {}
    flows: Dict[tuple, List[str]] = {}
    counts = {"B": 0, "E": 0, "X": 0, "i": 0, "M": 0,
              "s": 0, "t": 0, "f": 0}
    for ev in events:
        ph = ev["ph"]
        counts[ph] = counts.get(ph, 0) + 1
        if ph != "M" and ev["ts"] < 0:
            raise ValueError(f"negative ts on {ev}")
        track = (ev["pid"], ev["tid"])
        if ph == "B":
            depth[track] = depth.get(track, 0) + 1
        elif ph == "E":
            d = depth.get(track, 0) - 1
            if d < 0:
                raise ValueError(f"unmatched E on track {track}: {ev}")
            depth[track] = d
        elif ph == "X":
            if ev.get("dur", 0) < 0:
                raise ValueError(f"negative dur on {ev}")
        elif ph in ("s", "t", "f"):
            flows.setdefault((ev.get("cat"), ev["id"]), []).append(ph)
    for track, d in depth.items():
        if d != 0:
            raise ValueError(f"{d} unclosed span(s) on track {track}")
    for key, phs in flows.items():
        # merged lists concatenate per-recorder, so don't rely on list
        # order — require exactly one start and one finish per flow id
        if phs.count("s") != 1 or phs.count("f") != 1:
            raise ValueError(
                f"flow {key} must have exactly one s and one f, "
                f"got {phs}")
    counts["flows"] = len(flows)
    return counts


# -- profiler bridge ---------------------------------------------------------

def attach_profiler(tracer: TraceRecorder, pid: int = PID_HOST,
                    tid: int = 0):
    """Mirror every ``profiler.RecordEvent`` span into ``tracer`` as an X
    event on the host lane — engine phases, request lifecycle and host
    spans land on ONE Perfetto timeline.  Returns the sink handle for
    :func:`detach_profiler`; callers who outlive the tracer should
    detach, or the module-global sink list keeps feeding (and growing)
    a dead trace.  Idempotent per tracer: re-attaching an
    already-bridged tracer returns the existing sink instead of
    doubling every span.  RecordEvent measures on
    ``time.perf_counter``; the recorder maps those stamps through its
    own t0, so alignment with engine phases is exact when the tracer
    runs on the default clock (and merely monotonic under a virtual
    test clock)."""
    from .. import profiler as _prof

    existing = getattr(tracer, "_profiler_sink", None)
    if existing is not None:
        return existing
    tracer.process_name(pid, "host (profiler.RecordEvent)")

    def sink(name: str, t0: float, t1: float) -> None:
        tracer.complete(name, t0, t1 - t0, pid, tid)

    sink.tracer = tracer
    tracer._profiler_sink = sink
    _prof.add_span_sink(sink)
    return sink


def detach_profiler(sink) -> None:
    from .. import profiler as _prof

    _prof.remove_span_sink(sink)
    tracer = getattr(sink, "tracer", None)
    if tracer is not None and \
            getattr(tracer, "_profiler_sink", None) is sink:
        tracer._profiler_sink = None
