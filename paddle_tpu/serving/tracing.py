"""Request-lifecycle tracing: Chrome trace-event JSON from the engine.

Role parity: the reference fork's profiler pairs host ``RecordEvent``
span tables with a CUPTI ``DeviceTracer`` whose output opens in
``chrome://tracing`` (PAPER.md, ``platform/profiler.h``).  Our serving
engine had neither: a request's life — queued, admitted, chunk-prefilled,
decoding, preempted, recomputed, terminal — happened invisibly inside
the host loop.  This module records it as Chrome trace-event JSON
(the ``{"traceEvents": [...]}`` format), openable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``:

  * **one track per request** (pid ``PID_REQUESTS``, tid = rid):
    ``queued`` and ``resident`` B/E spans, ``prefill_chunk`` spans per
    chunk, instants for ``first_token`` / ``preempt`` / ``cow_clone`` /
    the terminal reason — a preempted request visibly bounces back to a
    ``queued`` span and re-prefills;
  * **one engine track** (pid ``PID_ENGINE``): per-step ``admit`` /
    ``prefill`` / ``decode`` phase X (complete) events, so slow steps
    and fault-aborted phases line up against request state;
  * **host spans** (pid ``PID_HOST``): :func:`attach_profiler` bridges
    ``paddle_tpu.profiler.RecordEvent`` — every host span recorded
    anywhere in-process lands on the SAME timeline as the engine
    phases, the unification the reference gets from one profiler state.

B/E discipline: :meth:`TraceRecorder.end` pops the recorder's own
per-track stack and names the E event from it, so emitted B/E pairs are
balanced BY CONSTRUCTION inside a track (asserted over chaos runs in
tests/test_metrics.py).  Engine phases deliberately use X events — an
injected mid-phase fault can abort a phase, and an X event written after
the fact cannot dangle.

Timestamps are microseconds on one monotonic base (``time.perf_counter``
by default; injectable for tests).  Dump with :meth:`save` and load the
file straight into Perfetto.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Dict, List, Optional

__all__ = ["TraceRecorder", "PID_ENGINE", "PID_REQUESTS", "PID_HOST",
           "attach_profiler", "detach_profiler"]

#: Process lanes of the unified timeline.
PID_ENGINE = 1      # engine step phases (admit/prefill/decode X events)
PID_REQUESTS = 2    # one thread per request (tid = rid)
PID_HOST = 3        # profiler.RecordEvent host spans


class TraceRecorder:
    """Append-only Chrome trace-event recorder.

    ``clock`` is a zero-arg seconds source (default
    ``time.perf_counter``); every event stamps ``ts`` in microseconds
    relative to the recorder's construction, so traces start at t=0.
    """

    def __init__(self, clock: Optional[Callable[[], float]] = None):
        self._clock = clock or time.perf_counter
        self._t0 = self._clock()
        self.events: List[dict] = []
        # open-span stack per (pid, tid): end() pops, so B/E pairs are
        # balanced by construction within a track
        self._open: Dict[tuple, List[str]] = {}
        self._named_pids = set()

    # -- time -------------------------------------------------------------

    def now_us(self) -> float:
        return (self._clock() - self._t0) * 1e6

    def _ev(self, name: str, ph: str, ts: float, pid: int, tid: int,
            args: Optional[dict] = None, **extra) -> dict:
        ev = {"name": name, "ph": ph, "ts": round(ts, 3),
              "pid": pid, "tid": tid}
        if args:
            ev["args"] = args
        ev.update(extra)
        self.events.append(ev)
        return ev

    def process_name(self, pid: int, name: str) -> None:
        """Label a pid lane (idempotent) — Perfetto shows the name."""
        if pid not in self._named_pids:
            self._named_pids.add(pid)
            self._ev("process_name", "M", 0.0, pid, 0,
                     args={"name": name})

    # -- spans ------------------------------------------------------------

    def begin(self, name: str, pid: int, tid: int,
              args: Optional[dict] = None) -> None:
        self._open.setdefault((pid, tid), []).append(name)
        self._ev(name, "B", self.now_us(), pid, tid, args)

    def end(self, pid: int, tid: int, args: Optional[dict] = None) -> str:
        """Close the innermost open span on (pid, tid); returns its name.
        A track with nothing open raises — the engine's lifecycle logic
        is the state machine, and an unmatched end means it broke."""
        stack = self._open.get((pid, tid))
        if not stack:
            raise ValueError(f"no open span on track ({pid}, {tid})")
        name = stack.pop()
        self._ev(name, "E", self.now_us(), pid, tid, args)
        return name

    def open_span(self, pid: int, tid: int) -> Optional[str]:
        """Name of the innermost open span on the track, or None."""
        stack = self._open.get((pid, tid))
        return stack[-1] if stack else None

    def instant(self, name: str, pid: int, tid: int,
                args: Optional[dict] = None) -> None:
        self._ev(name, "i", self.now_us(), pid, tid, args, s="t")

    def complete(self, name: str, start_s: float, dur_s: float, pid: int,
                 tid: int, args: Optional[dict] = None) -> None:
        """An X event from absolute clock seconds (same base as
        ``clock``) — used for engine phases and bridged host spans."""
        self._ev(name, "X", (start_s - self._t0) * 1e6, pid, tid, args,
                 dur=round(dur_s * 1e6, 3))

    # -- output -----------------------------------------------------------

    def to_json(self) -> dict:
        return {"traceEvents": list(self.events),
                "displayTimeUnit": "ms"}

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.to_json(), f)
        return path


# -- profiler bridge ---------------------------------------------------------

def attach_profiler(tracer: TraceRecorder, pid: int = PID_HOST,
                    tid: int = 0):
    """Mirror every ``profiler.RecordEvent`` span into ``tracer`` as an X
    event on the host lane — engine phases, request lifecycle and host
    spans land on ONE Perfetto timeline.  Returns the sink handle for
    :func:`detach_profiler`; callers who outlive the tracer should
    detach, or the module-global sink list keeps feeding (and growing)
    a dead trace.  Idempotent per tracer: re-attaching an
    already-bridged tracer returns the existing sink instead of
    doubling every span.  RecordEvent measures on
    ``time.perf_counter``; the recorder maps those stamps through its
    own t0, so alignment with engine phases is exact when the tracer
    runs on the default clock (and merely monotonic under a virtual
    test clock)."""
    from .. import profiler as _prof

    existing = getattr(tracer, "_profiler_sink", None)
    if existing is not None:
        return existing
    tracer.process_name(pid, "host (profiler.RecordEvent)")

    def sink(name: str, t0: float, t1: float) -> None:
        tracer.complete(name, t0, t1 - t0, pid, tid)

    sink.tracer = tracer
    tracer._profiler_sink = sink
    _prof.add_span_sink(sink)
    return sink


def detach_profiler(sink) -> None:
    from .. import profiler as _prof

    _prof.remove_span_sink(sink)
    tracer = getattr(sink, "tracer", None)
    if tracer is not None and \
            getattr(tracer, "_profiler_sink", None) is sink:
        tracer._profiler_sink = None
