"""Multi-replica serving router: disaggregated prefill/decode (r15).

One :class:`~paddle_tpu.serving.engine.ServingEngine` is one replica —
its own KV pool, prefix index, scheduler and jitted programs.  This
module is the tier ABOVE them: a :class:`Router` that owns admission for
a fleet of replicas and the three decisions a fleet adds over a single
engine:

  * **cache-affinity routing** — each replica exposes its prefix-index
    keys through the read-only ``prefix_match_len`` probe; a request
    routes to the prefill replica holding its LONGEST cached prefix
    (DistServe/Mooncake-style KV-aware dispatch), tie-broken by
    ``load_score`` (resident slots + queue depth + pool pressure), then
    by index for determinism.  Affinity concentrates shared prefixes on
    the replica that already has their pages, so the hit rate of the
    FLEET approaches the hit rate of one big pool without sharing
    memory;
  * **prefill/decode separation** — ``role="prefill"`` replicas run
    chunked prefill to completion and export ``(request, page payloads,
    scales)`` records (snapshot v5 wire format); the router pumps each
    record to the least-loaded ``role="decode"`` replica, whose pool
    adopts the pages bit-exactly (layout-guarded) with zero recompute.
    Decode steps never contend with prompt chunks for the token budget,
    which is the whole point of disaggregation (DistServe, OSDI '24);
  * **router-global fairness** — with a
    :class:`~paddle_tpu.serving.tenancy.ClusterWFQState`, every member
    policy shares ONE virtual-token-counter table, so ``vt ==
    served/weight`` holds across the cluster, not per replica, and a
    tenant cannot dodge its weight by landing on an idle replica.

The router is deliberately in-process and synchronous — ``step()``
steps every replica then pumps handoffs, exactly like the single-engine
host loop.  Network serving stays in
:class:`~paddle_tpu.serving.frontend.ServingFrontend`, which accepts a
Router anywhere it accepts an engine (asyncio/socket imports stay scoped
to the front tier; this module is plain host code over numpy records).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from .engine import FinishedRequest, ServingEngine
from .scheduler import Request
from .tenancy import ClusterWFQState, WFQPolicy
from .tracing import (PID_ROUTER, TraceRecorder, flow_id, merge_traces)

__all__ = ["Router", "make_cluster"]


class Router:
    """Admission + routing tier over a fleet of serving replicas.

    ``replicas`` is the fleet in index order; roles partition it into
    PREFILL targets (``role`` in ``both``/``prefill`` — they can admit
    fresh prompts) and DECODE targets (``both``/``decode`` — they can
    ingest handoffs).  A monolithic fleet (all ``both``) routes and
    balances but never hands off; a disaggregated fleet moves every
    request across the wire exactly once, after its prompt is paid for.

    ``max_queue`` bounds the CLUSTER's total waiting count — overflow
    requests get a ``rejected`` terminal from the router itself (no
    replica ever sees them).  Per-tenant quotas stay inside the engines
    (cluster-wide when the fleet shares a ClusterWFQState).
    """

    def __init__(self, replicas: Sequence[ServingEngine], *,
                 max_queue: Optional[int] = None):
        self.replicas: List[ServingEngine] = list(replicas)
        if not self.replicas:
            raise ValueError("a Router needs at least one replica")
        self.prefill_targets = [e for e in self.replicas
                                if e.role in ("both", "prefill")]
        self.decode_targets = [e for e in self.replicas
                               if e.role in ("both", "decode")]
        if not self.prefill_targets:
            raise ValueError("no replica can admit prompts "
                             "(need role 'both' or 'prefill')")
        if not self.decode_targets:
            raise ValueError("no replica can decode "
                             "(need role 'both' or 'decode')")
        self.max_queue = max_queue
        # router-owned terminals (cluster-queue rejects) awaiting delivery
        self._pending: List[FinishedRequest] = []
        self._on_token: Optional[Callable[[int, int], None]] = None
        self.stats: Dict[str, object] = {
            "routed": [0] * len(self.prefill_targets),
            "prefix_routed": 0,        # requests routed BY a prefix match
            "prefix_match_tokens": 0,  # tokens already cached at routing
            "rejected": 0,             # cluster-queue overflow terminals
            "handoffs": 0,             # records pumped prefill -> decode
            "handoff_bytes": 0,        # payload bytes moved
            "degraded_handoffs": 0,    # records pumped WITHOUT payload
        }
        self._parts: Optional[Dict[str, object]] = None
        # cluster tracing (attach_tracers): the router's own recorder
        # plus one per replica, all on ONE shared clock so merge_traces
        # can rebase them onto a single timeline
        self.tracer: Optional[TraceRecorder] = None
        self._tracers: List[TraceRecorder] = []

    # -- streaming --------------------------------------------------------

    @property
    def on_token(self) -> Optional[Callable[[int, int], None]]:
        """Fleet-wide token observer: assigning it installs the same
        callback on every replica (rids are globally unique, so one
        ``(rid, token)`` stream is unambiguous across the fleet)."""
        return self._on_token

    @on_token.setter
    def on_token(self, cb: Optional[Callable[[int, int], None]]) -> None:
        self._on_token = cb
        for eng in self.replicas:
            eng.on_token = cb

    @property
    def max_seq_len(self) -> int:
        """Longest prompt+continuation the FLEET can take: the smallest
        replica bound (a handoff must fit its decode replica too)."""
        return min(e.max_seq_len for e in self.replicas)

    # -- admission + routing ----------------------------------------------

    def add_request(self, prompt, max_new_tokens: int,
                    arrival: float = 0.0,
                    deadline_s: Optional[float] = None,
                    tenant: Optional[str] = None) -> int:
        """Route one request into the fleet; returns its rid (globally
        unique across replicas).  Same signature as the engine's."""
        return self.submit(Request(
            prompt=np.asarray(prompt, np.int32).reshape(-1),
            max_new_tokens=max_new_tokens, arrival=arrival,
            deadline_s=deadline_s, tenant=tenant))

    def submit(self, req: Request) -> int:
        """Admission for an already-built Request: cluster queue bound
        first (overflow is a router-owned ``rejected`` terminal — no
        replica billed, no engine metrics), then cache-affinity routing
        into the best prefill target's own admission gate (which still
        applies per-engine backpressure and tenant quotas)."""
        if req.total_len > self.max_seq_len:
            # fleet-level bound: the request must also fit whatever
            # decode replica its handoff lands on, not just the replica
            # that prefills it
            raise ValueError(
                f"request needs {req.total_len} positions; the fleet's "
                f"max_seq_len is {self.max_seq_len}")
        if self.max_queue is not None and self.queue_depth >= self.max_queue:
            self.stats["rejected"] += 1
            self._pending.append(FinishedRequest(
                rid=req.rid, prompt=req.prompt,
                tokens=np.asarray(req.generated, np.int32),
                finish_reason="rejected", n_steps=0))
            return req.rid
        t_pick = self.tracer._clock() if self.tracer is not None else 0.0
        i, matched = self._pick_replica(req)
        self.stats["routed"][i] += 1
        if matched:
            self.stats["prefix_routed"] += 1
            self.stats["prefix_match_tokens"] += matched
        if self.tracer is not None:
            # the routing decision as an X span on the router lane:
            # WHY this replica won (cache affinity vs. load) is visible
            # right next to the request's lifecycle in the merged trace
            self.tracer.complete(
                "route", t_pick, self.tracer._clock() - t_pick,
                PID_ROUTER, 0,
                args={"rid": int(req.rid), "replica": int(i),
                      "prefix_match_len": int(matched),
                      "load_score": float(
                          self.prefill_targets[i].load_score())})
        return self.prefill_targets[i]._enqueue(req)

    def _pick_replica(self, req: Request):
        """(index into prefill_targets, matched tokens): longest cached
        prefix wins; ties (usually 0-vs-0 on cold caches) fall to the
        lowest load score, then the lowest index — fully deterministic
        for a given fleet state."""
        best_i, best_key = 0, None
        for i, eng in enumerate(self.prefill_targets):
            key = (-eng.prefix_match_len(req.prompt), eng.load_score(), i)
            if best_key is None or key < best_key:
                best_i, best_key = i, key
        return best_i, -best_key[0]

    def cancel(self, rid: int) -> bool:
        """Cancel wherever the request currently lives (waiting,
        resident, or parked in a handoff inbox on any replica)."""
        return any(eng.cancel(rid) for eng in self.replicas)

    # -- the cluster step -------------------------------------------------

    @property
    def queue_depth(self) -> int:
        """Total waiting requests across the fleet (handoff inboxes
        excluded — those requests were already admitted once)."""
        return sum(e.scheduler.n_waiting for e in self.replicas)

    @property
    def has_work(self) -> bool:
        return (bool(self._pending)
                or any(e.has_work for e in self.replicas)
                or any(e._handoff_out for e in self.replicas))

    def step(self) -> List[FinishedRequest]:
        """One cluster iteration: step every replica that has work, then
        pump handoff outboxes to decode targets.  Pumping AFTER the
        sweep means a record produced by replica i this step reaches its
        decode replica's inbox before that replica's NEXT admit phase —
        one router hop of latency, same as a real transfer fabric."""
        finished: List[FinishedRequest] = list(self._pending)
        self._pending.clear()
        for eng in self.replicas:
            if eng.has_work:
                finished.extend(eng.step())
        self._pump_handoffs()
        return finished

    def _pump_handoffs(self) -> None:
        """Deliver every outbox record to the least-loaded decode
        target.  Degraded records (payload dropped by an injected
        transfer fault) still deliver — the decode replica re-prefills
        them — so a fabric fault costs recompute, never a request."""
        for eng in self.replicas:
            if not eng._handoff_out:
                continue
            for h in eng.drain_handoffs():
                j = min(range(len(self.decode_targets)),
                        key=lambda j: (self.decode_targets[j].load_score(),
                                       j))
                self.stats["handoffs"] += 1
                if h["payload"] is None:
                    self.stats["degraded_handoffs"] += 1
                else:
                    self.stats["handoff_bytes"] += h["nbytes"]
                tr = h.get("trace")
                if self.tracer is not None:
                    self.tracer.begin(
                        "pump_handoff", PID_ROUTER, 0,
                        args={"rid": int(h["request"]["rid"]),
                              "to_replica": int(j),
                              "nbytes": int(h["nbytes"]),
                              "degraded": h["payload"] is None})
                    if tr is not None:
                        # the "t" hop of the handoff arrow: binds to
                        # this pump span, between the prefill export
                        # ("s") and the decode ingest ("f")
                        self.tracer.flow_step(
                            "handoff", PID_ROUTER, 0,
                            flow_id(tr["rid"], tr["seq"]))
                self.decode_targets[j].ingest_handoff(h)
                if self.tracer is not None:
                    self.tracer.end(PID_ROUTER, 0)

    def run(self, requests: Optional[Sequence] = None,
            metrics_dir: Optional[str] = None
            ) -> Dict[int, FinishedRequest]:
        """Drive the cluster to drain; returns {rid: FinishedRequest}
        with degraded terminals included — the fleet-level mirror of
        ``ServingEngine.run``.  Asserts every replica drained leak-free.

        ``metrics_dir`` turns the drain into an observed run
        (auto-attaching metrics, shared-clock tracers and flight
        recorders if none are set); at drain the dir holds
        ``metrics_r{i}.prom`` per replica, ``cluster.prom`` (one scrape
        page for the fleet), ``trace.json`` (the MERGED cluster trace —
        open in Perfetto to see handoff arrows cross replicas) and
        ``flight_r{i}.json`` black-box dumps.  A crash escaping any
        replica's step loop dumps ``flight_crash_r{i}.json`` before
        re-raising."""
        for r in requests or ():
            if isinstance(r, Request):
                self.submit(r)
            else:
                prompt, max_new = r
                self.add_request(prompt, max_new)
        if metrics_dir is not None:
            if self._parts is None:
                self.attach_metrics()
            if self.tracer is None:
                self.attach_tracers()
            self.attach_flight()
            os.makedirs(metrics_dir, exist_ok=True)
            for i, eng in enumerate(self.replicas):
                eng._crash_dump_dir = metrics_dir
                eng._crash_dump_name = f"flight_crash_r{i}.json"
        done: Dict[int, FinishedRequest] = {}
        try:
            while self.has_work:
                for fin in self.step():
                    done[fin.rid] = fin
        finally:
            if metrics_dir is not None:
                self._dump_artifacts(metrics_dir)
        for i, eng in enumerate(self.replicas):
            if eng.scheduler.n_active or eng.pool.pages_in_use:
                raise AssertionError(
                    f"replica {i} did not drain: "
                    f"{eng.scheduler.n_active} active slots, "
                    f"{eng.pool.pages_in_use} pages in use")
        return done

    def _dump_artifacts(self, metrics_dir: str) -> None:
        from .metrics import cluster_prometheus
        from .tracing import save_trace

        for i, eng in enumerate(self.replicas):
            if eng.metrics is not None:
                with open(os.path.join(metrics_dir,
                                       f"metrics_r{i}.prom"), "w") as f:
                    f.write(eng.metrics.to_prometheus())
            if eng.flight is not None:
                eng.flight.dump(os.path.join(metrics_dir,
                                             f"flight_r{i}.json"))
        if self._parts is not None:
            with open(os.path.join(metrics_dir, "cluster.prom"),
                      "w") as f:
                f.write(cluster_prometheus(self._parts))
        if self.tracer is not None:
            save_trace(self.merged_trace(),
                       os.path.join(metrics_dir, "trace.json"))

    # -- audits + observability -------------------------------------------

    def check_invariants(self) -> None:
        """Every replica's page-leak/refcount/scheduler audit."""
        for eng in self.replicas:
            eng.check_invariants()

    def attach_tracers(self, clock: Optional[Callable[[], float]] = None
                       ) -> TraceRecorder:
        """Cluster tracing: one recorder per replica (lanes namespaced
        ``replica * PID_STRIDE + base``, labels prefixed ``r{i}:``) plus
        the router's own PID_ROUTER lane, ALL on one shared clock —
        the precondition for :func:`~paddle_tpu.serving.tracing.
        merge_traces` rebasing them onto a single timeline.  Returns
        the router's recorder."""
        clk = clock or time.perf_counter
        self.tracer = TraceRecorder(clock=clk)
        self.tracer.process_name(
            PID_ROUTER, "router (routing + handoff pump)")
        self._tracers = []
        for i, eng in enumerate(self.replicas):
            rec = TraceRecorder(clock=clk)
            eng.attach_tracer(rec, replica=i)
            self._tracers.append(rec)
        return self.tracer

    def attach_flight(self, capacity: int = 1024) -> None:
        """A flight recorder on every replica that lacks one (each on
        its OWN engine clock — the black box must replay
        bit-identically under that replica's fault plan)."""
        for eng in self.replicas:
            if eng.flight is None:
                eng.attach_flight(capacity=capacity)

    def merged_trace(self) -> dict:
        """The fleet's recorders merged into ONE Perfetto-loadable
        trace: router lane + every replica's lanes, handoff flow
        arrows intact.  Requires :meth:`attach_tracers`."""
        if self.tracer is None:
            raise RuntimeError("call attach_tracers() first")
        return merge_traces([self.tracer] + self._tracers)

    def dump_debug(self) -> Dict[str, object]:
        """Fleet-wide debug snapshot (the /debug/state payload): the
        router's ledger plus every replica's
        :meth:`~paddle_tpu.serving.engine.ServingEngine.dump_debug`
        (invariant verdicts, stats, flight rings)."""
        return {"router": self.stats_snapshot(),
                "queue_depth": self.queue_depth,
                "replicas": [eng.dump_debug() for eng in self.replicas]}

    def attach_metrics(self) -> Dict[str, object]:
        """One FRESH registry per replica (the engine's one-registry
        rule), keyed ``"replica0"``... — aggregate with
        :func:`~paddle_tpu.serving.metrics.aggregate_scalars` or render
        one scrape page with
        :func:`~paddle_tpu.serving.metrics.cluster_prometheus`."""
        self._parts = {f"replica{i}": eng.attach_metrics()
                       for i, eng in enumerate(self.replicas)}
        return self._parts

    def scalars(self) -> Dict[str, float]:
        """Cluster-rollup scalars: counters sum, gauges min/max-combine,
        and histogram BUCKETS merge before re-quantizing, so the
        ``*_p50``/``*_p99`` here are true cluster quantiles (r16) —
        identical to what one union registry would have reported."""
        from .metrics import aggregate_scalars

        if self._parts is None:
            raise RuntimeError("call attach_metrics() first")
        return aggregate_scalars(self._parts)

    def to_prometheus(self) -> str:
        """One scrape page for the fleet: every series labeled
        ``replica="replicaN"``, one HELP/TYPE per family."""
        from .metrics import cluster_prometheus

        if self._parts is None:
            raise RuntimeError("call attach_metrics() first")
        return cluster_prometheus(self._parts)

    def stats_snapshot(self) -> Dict[str, object]:
        out = dict(self.stats, routed=list(self.stats["routed"]))
        return out


def make_cluster(model, n_replicas: int = 2, *, disaggregate: bool = False,
                 tenants=None, router_max_queue: Optional[int] = None,
                 **engine_kw) -> Router:
    """Build a routed fleet over one model.

    ``disaggregate=False``: ``n_replicas`` monolithic (``role="both"``)
    engines — pure routing/balancing.  ``disaggregate=True`` (needs >= 2
    replicas): the first ``n_replicas // 2`` (at least one) become
    prefill workers, the rest decode workers.  ``tenants`` installs
    router-global WFQ: one shared
    :class:`~paddle_tpu.serving.tenancy.ClusterWFQState` with every
    member policy aliasing its virtual-token table.  Remaining keyword
    arguments go to every :class:`ServingEngine` verbatim.
    """
    if n_replicas < 1:
        raise ValueError("n_replicas must be >= 1")
    if disaggregate and n_replicas < 2:
        raise ValueError("disaggregation needs >= 2 replicas "
                         "(one prefill + one decode)")
    if disaggregate:
        n_pre = max(1, n_replicas // 2)
        roles = ["prefill"] * n_pre + ["decode"] * (n_replicas - n_pre)
    else:
        roles = ["both"] * n_replicas
    state = ClusterWFQState(tenants) if tenants is not None else None
    replicas = []
    for role in roles:
        kw = dict(engine_kw)
        if state is not None:
            kw["policy"] = WFQPolicy(state=state)
        replicas.append(ServingEngine(model, role=role, **kw))
    return Router(replicas, max_queue=router_max_queue)
