"""Deterministic fault injection for the serving engine (chaos harness).

Production serving dies in ways unit tests never exercise: the allocator
comes up empty under a burst, a device step throws mid-flight, one step
stalls long enough for deadlines to blow.  This module scripts those
faults DETERMINISTICALLY — a :class:`FaultPlan` is a step-indexed
schedule derived from one RNG seed, so a chaos run that trips an
invariant replays bit-for-bit from its seed.

Wiring (chosen so no fault can land at an inconsistent point):

  * **alloc failures** — ``KVPool.alloc`` consults ``pool.faults`` FIRST
    and returns None for every call in a scripted step, exactly the
    signal real exhaustion produces.  Admission backs off (the request
    stays queued); decode page growth stalls the slot for the step when
    the pool could actually satisfy the lease (a transient fault must
    not cascade preemptions), and walks the preemption path only under
    real pressure.
  * **step exceptions** — ``ServingEngine.step`` calls
    ``plan.check_raise(phase)`` at its three phase boundaries
    (``admit`` / ``prefill`` / ``decode``), where host mirrors, slots and
    pool bookkeeping are consistent; :class:`InjectedFault` aborts the
    rest of the iteration and the engine resumes next step (counted in
    ``stats["step_faults"]``).
  * **step latency** — the plan owns a VIRTUAL clock advanced by
    ``step_tick_s`` plus any scripted per-step latency at
    ``begin_step``; an engine built with a plan reads deadlines off that
    clock, so expiry under slowdown is reproducible and test-fast (no
    real sleeping).  The same clock drives the engine's request-time
    METRICS (queue-wait / TTFT / TBT / e2e histograms,
    serving/metrics.py), so under a plan those readouts are
    bit-deterministic — asserted by the chaos suite.

The chaos acceptance contract (tests/test_serving_faults.py): under ANY
seeded plan every request reaches exactly one terminal state
(finished / rejected / expired / cancelled), ``check_invariants`` holds
after every step, and drain leaves zero pages in use.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Set

import numpy as np

#: The engine's intra-step injection points, in execution order.
#: "verify" (r13) fires INSIDE a speculative decode step — after drafts
#: are proposed and pages grown, before the verify dispatch — so chaos
#: runs exercise the draft-buffers-populated-but-unverified state; a
#: non-speculative engine never reaches it (the fault stays silent).
#: "handoff" (r15) fires in a PREFILL-role engine's handoff phase and
#: models the transfer fabric dropping that step's page payloads: the
#: handoff DEGRADES (the request ships without KV and re-prefills on the
#: decode replica) instead of aborting the step, so disaggregated chaos
#: runs exercise the recompute fallback.  Engines that never hand off
#: (role "both"/"decode") never reach it — the fault stays silent, like
#: "verify" on a non-speculative engine.
PHASES = ("admit", "prefill", "handoff", "verify", "decode")


class InjectedFault(RuntimeError):
    """A scripted mid-step failure (stands in for a device fault).  The
    engine catches it at the phase boundary that raised it, abandons the
    rest of the iteration, and carries on next step."""


class FaultPlan:
    """A step-indexed, seed-reproducible fault schedule.

    ``alloc_fail_steps`` — steps in which every ``KVPool.alloc`` fails;
    ``raise_steps``      — ``{step: phase}`` injected step exceptions;
    ``latency_s``        — ``{step: seconds}`` extra virtual step time;
    ``step_tick_s``      — base virtual time every step advances.

    ``injected`` counts what actually fired, for test assertions.
    """

    def __init__(self, seed: int = 0,
                 alloc_fail_steps: Iterable[int] = (),
                 raise_steps: Optional[Dict[int, str]] = None,
                 latency_s: Optional[Dict[int, float]] = None,
                 step_tick_s: float = 1e-3):
        self.seed = seed
        self.alloc_fail_steps: Set[int] = set(alloc_fail_steps)
        self.raise_steps: Dict[int, str] = dict(raise_steps or {})
        for phase in self.raise_steps.values():
            if phase not in PHASES:
                raise ValueError(f"unknown fault phase {phase!r}")
        self.latency_s: Dict[int, float] = dict(latency_s or {})
        self.step_tick_s = float(step_tick_s)
        self.step = 0
        self.clock = 0.0
        self.injected = {"alloc_fail": 0, "raise": 0, "latency_s": 0.0}

    @classmethod
    def random(cls, seed: int, n_steps: int = 64, p_alloc: float = 0.12,
               p_raise: float = 0.06, p_latency: float = 0.10,
               max_latency_s: float = 0.05,
               step_tick_s: float = 1e-3) -> "FaultPlan":
        """Draw a schedule over steps ``1..n_steps`` from one seed.  The
        horizon is FINITE by design: past it the plan is silent, so a
        chaos run always converges once the scripted trouble ends."""
        rng = np.random.RandomState(seed)
        alloc: Set[int] = set()
        raises: Dict[int, str] = {}
        lat: Dict[int, float] = {}
        for i in range(1, n_steps + 1):
            if rng.rand() < p_alloc:
                alloc.add(i)
            if rng.rand() < p_raise:
                raises[i] = PHASES[rng.randint(len(PHASES))]
            if rng.rand() < p_latency:
                lat[i] = float(rng.rand() * max_latency_s)
        return cls(seed=seed, alloc_fail_steps=alloc, raise_steps=raises,
                   latency_s=lat, step_tick_s=step_tick_s)

    # -- engine hooks -----------------------------------------------------

    def begin_step(self, step_idx: int) -> None:
        """Advance the virtual clock into ``step_idx`` (base tick + any
        scripted latency) and arm this step's faults."""
        self.step = step_idx
        extra = self.latency_s.get(step_idx, 0.0)
        self.clock += self.step_tick_s + extra
        self.injected["latency_s"] += extra

    def now(self) -> float:
        """The virtual clock — engines built with a plan read deadlines
        off this instead of ``time.monotonic``."""
        return self.clock

    def fail_alloc(self) -> bool:
        """True when the current step scripts allocator exhaustion
        (consulted by ``KVPool.alloc`` before touching the free list)."""
        if self.step in self.alloc_fail_steps:
            self.injected["alloc_fail"] += 1
            return True
        return False

    def check_raise(self, phase: str) -> None:
        """Raise :class:`InjectedFault` if the current step scripts an
        exception at ``phase``."""
        if self.raise_steps.get(self.step) == phase:
            self.injected["raise"] += 1
            raise InjectedFault(
                f"injected fault at step {self.step} ({phase})")
