"""FCFS continuous-batching scheduler (Orca, OSDI '22 + Sarathi, OSDI '24).

The scheduler owns the WAITING queue, the slot occupancy map and the
per-step token budget; the engine owns the device programs.  Every engine
step asks :meth:`FCFSScheduler.schedule_step` which requests to admit
into freed slots, then runs at most ``chunk budget`` tokens of prefill
plus ONE decode step over all started slots — iteration-level scheduling
instead of run-to-completion batches.

Budget semantics (Sarathi-Serve's chunked prefill): admission costs
nothing up front — an admitted request's prompt is prefilled in CHUNKS
across subsequent steps, co-scheduled with decode.  Each step the engine
spends :meth:`prefill_budget` prompt tokens, i.e. ``token_budget`` minus
one token per active decode, so a burst of long prompts can no longer
stall every in-flight decode behind a monolithic prefill (the pre-r09
failure mode that needed whole prompts force-admitted over budget).
Admission is gated only by free slots and pages.

Page accounting is conservative: a request is admitted only when the pool
can hold its WHOLE worst-case sequence (prompt + max_new_tokens), so an
admitted request can never die of page exhaustion mid-flight (no
preemption/swap tier — requests are small relative to the pool; add
eviction here if that stops holding).  Prefix-cached pages
(kv_pool.KVPool ``prefix_cache=True``) are matched AT ADMISSION: shared
full pages are retained instead of allocated, a partial-tail match is
handed to the engine as a copy-on-write candidate, and only the uncached
remainder allocates fresh pages.
"""

from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple

import numpy as np

from .kv_pool import KVPool

_rid_counter = itertools.count()


@dataclass
class Request:
    """One generation request: token ids in, up to ``max_new_tokens`` out."""

    prompt: np.ndarray
    max_new_tokens: int
    rid: int = field(default_factory=lambda: next(_rid_counter))
    arrival: float = 0.0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    @property
    def total_len(self) -> int:
        return self.prompt_len + self.max_new_tokens


@dataclass
class Admission:
    """One scheduling decision: request -> slot, with its pages.

    ``pages`` are freshly leased (refcount 1, this request's alone);
    ``cached`` are prefix-index pages shared read-only (already retained);
    ``cow`` is an optional ``(source_page, n_tokens)`` partial-tail match
    the engine must copy-on-write into ``pages[0]`` (the source is
    retained until the engine releases it after the copy); ``matched`` is
    the total prompt tokens whose K/V need no recompute."""

    slot: int
    request: Request
    pages: List[int]
    cached: List[int] = field(default_factory=list)
    cow: Optional[Tuple[int, int]] = None
    matched: int = 0


class FCFSScheduler:
    """First-come-first-served admission over a fixed slot array."""

    def __init__(self, n_slots: int, pool: KVPool,
                 token_budget: Optional[int] = None):
        self.n_slots = n_slots
        self.pool = pool
        # default budget: every slot decoding plus one flagship-sized
        # prefill chunk per step keeps step latency bounded without
        # starving admission
        self.token_budget = token_budget or (n_slots + 512)
        self.waiting: Deque[Request] = deque()
        self._free_slots: List[int] = list(range(n_slots - 1, -1, -1))

    # -- queue ------------------------------------------------------------

    def add(self, request: Request) -> int:
        max_tokens = (self.pool.num_pages - 1) * self.pool.page_size
        if request.total_len > max_tokens:
            raise ValueError(
                f"request {request.rid} needs {request.total_len} tokens; "
                f"the pool holds {max_tokens} — raise num_pages/max_seq_len")
        self.waiting.append(request)
        return request.rid

    @property
    def n_waiting(self) -> int:
        return len(self.waiting)

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free_slots)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or self.n_active > 0

    # -- per-step decisions ----------------------------------------------

    def prefill_budget(self, n_decoding: int, chunk_tokens: int) -> int:
        """Sarathi chunk budget for one step: the token budget left after
        paying one token per active decode, capped at the engine's chunk
        program width and floored at 1 so prefill always progresses even
        when decodes alone exceed the budget."""
        return max(1, min(chunk_tokens, self.token_budget - n_decoding))

    def schedule_step(self) -> List[Admission]:
        """Admit FCFS from the waiting queue into free slots until slots
        or pages run out.  Head-of-line blocking is intentional (FCFS
        fairness): if the HEAD's pages don't fit we stop, we don't scan
        deeper for a smaller request.  Prefix-cache matching happens
        here, while this step's page arithmetic is decided: matched full
        pages are retained (shared) instead of allocated, and a
        partial-tail match rides along as the COW candidate."""
        admissions: List[Admission] = []
        while self.waiting and self._free_slots:
            req = self.waiting[0]
            cached: List[int] = []
            cow: Optional[Tuple[int, int]] = None
            held: List[int] = []
            if self.pool.prefix is not None:
                # never match the whole prompt: the last token must be
                # prefilled so its logits exist to sample the first output
                cached, cow = self.pool.prefix.match(req.prompt[:-1])
                held = list(cached) + ([cow[0]] if cow else [])
                # pin matches BEFORE alloc — alloc may LRU-evict
                # reclaimable cached pages to satisfy the fresh lease
                self.pool.retain(held)
            need = self.pool.pages_for(req.total_len) - len(cached)
            pages = self.pool.alloc(need)
            if pages is None and cow is not None:
                # the pinned COW source inflates peak demand by one page
                # beyond the admission arithmetic (pages_for(total_len));
                # for a request sized to the remaining pool that ONE page
                # can make alloc fail forever — drop the partial match
                # (full-page matches only ever reduce demand) and retry
                self.pool.release([cow[0]])
                held, cow = list(cached), None
                pages = self.pool.alloc(need)
            if pages is None:
                if held:
                    self.pool.release(held)
                break
            matched = len(cached) * self.pool.page_size + \
                (cow[1] if cow else 0)
            self.waiting.popleft()
            slot = self._free_slots.pop()
            admissions.append(Admission(slot=slot, request=req, pages=pages,
                                        cached=cached, cow=cow,
                                        matched=matched))
        return admissions

    def release(self, slot: int, pages: List[int]) -> None:
        """A request finished: its slot frees and every page reference it
        held drops (shared prefix pages simply lose one reference; pages
        reaching refcount 0 return to the free list unless the prefix
        index keeps them reclaimable)."""
        if slot in self._free_slots:
            raise ValueError(f"double release of slot {slot}")
        self.pool.release(pages)
        self._free_slots.append(slot)
