"""FCFS continuous-batching scheduler (Orca, OSDI '22 + Sarathi, OSDI '24).

The scheduler owns the WAITING queue, the slot occupancy map and the
per-step token budget; the engine owns the device programs.  Every engine
step asks :meth:`FCFSScheduler.schedule_step` which requests to admit
into freed slots, then runs at most ``chunk budget`` tokens of prefill
plus ONE decode step over all started slots — iteration-level scheduling
instead of run-to-completion batches.

Budget semantics (Sarathi-Serve's chunked prefill): admission costs
nothing up front — an admitted request's prompt is prefilled in CHUNKS
across subsequent steps, co-scheduled with decode.  Each step the engine
spends :meth:`prefill_budget` prompt tokens, i.e. ``token_budget`` minus
one token per active decode, so a burst of long prompts can no longer
stall every in-flight decode behind a monolithic prefill (the pre-r09
failure mode that needed whole prompts force-admitted over budget).
Admission is gated only by free slots and pages.

Page accounting is ON-DEMAND (r10, vLLM's preempt-by-recompute tier):
admission reserves only the pages the PROMPT needs — decode grows the
block table one page at a time as the sequence crosses page boundaries,
and when growth fails the engine preempts the youngest occupied slot
(its pages free, its generated tokens survive on the request, and
:meth:`requeue` puts it back at the HEAD of the waiting queue for
recompute-restart through the chunked-prefill path).  The pre-r10
whole-lifetime reservation (``pages_for(total_len)`` at admission) paid
``max_new_tokens`` worth of pages for every resident request whether
generated or not; on-demand growth lifts occupancy at the cost of the
preemption tier.  No-livelock: the OLDEST admitted request (smallest
admission seq, preserved across preemptions) is never chosen as a
victim, so it always progresses and the system always shrinks.
Prefix-cached pages (kv_pool.KVPool ``prefix_cache=True``) are matched
AT ADMISSION: shared full pages are retained instead of allocated, a
partial-tail match is handed to the engine as a copy-on-write candidate,
and only the uncached remainder allocates fresh pages — which is also
what makes a preempted request's recompute cheap: its already-computed
full prompt pages park reclaimable in the prefix index and are simply
re-adopted at re-admission.

Lifecycle (r10): a request may carry a ``deadline_s`` (seconds from
enqueue, measured on the engine's clock) — :meth:`pop_expired` removes
overdue requests at queue-pop time, the engine expires overdue slots
per-step.  :meth:`remove_waiting` serves ``engine.cancel`` for queued
requests.  The BOUND (backpressure) lives in the engine, which converts
an over-limit enqueue into an explicit ``rejected`` terminal instead of
unbounded growth.

Queue ORDER is pluggable (r12, serving/tenancy.py): the scheduler
delegates push/peek/pop/requeue-at-head to a
:class:`~paddle_tpu.serving.tenancy.SchedulerPolicy` — FCFS by default
(the pre-r12 deque, semantics unchanged), or weighted fair queueing over
per-tenant virtual token counters for multi-tenant isolation.  The
scheduler keeps owning slots, pages and the token budget; the policy
only decides WHOSE request admits next.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

import numpy as np

from .kv_pool import KVPool
from .tenancy import SchedulerPolicy, make_policy


class _RidCounter:
    """Monotonic request-id source.  A plain mutable counter (not
    itertools.count) so snapshot/restore can capture and re-seed it —
    restored engines must keep minting rids unique w.r.t. the snapshot."""

    __slots__ = ("n",)

    def __init__(self) -> None:
        self.n = 0

    def __call__(self) -> int:
        rid, self.n = self.n, self.n + 1
        return rid


_next_rid = _RidCounter()


@dataclass(eq=False)
class Request:
    """One generation request: token ids in, up to ``max_new_tokens`` out.
    Identity equality (``eq=False``): requests are stateful queue members
    — field-wise comparison over numpy prompts is meaningless (and
    ``deque.remove`` relies on ``==``).

    ``deadline_s`` (optional) expires the request ``deadline_s`` engine-
    clock seconds after enqueue, in ANY state.  ``generated`` holds every
    token produced so far and SURVIVES preemption — a preempted request
    re-enters the queue carrying its continuation, and the engine
    re-prefills ``work_prompt`` (prompt + generated) before decoding the
    remaining ``remaining_new`` tokens, so the final output is identical
    to an unpreempted run under greedy sampling.
    """

    prompt: np.ndarray
    max_new_tokens: int
    rid: int = field(default_factory=_next_rid)
    arrival: float = 0.0
    deadline_s: Optional[float] = None
    tenant: Optional[str] = None

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # lifecycle state (not ctor args): tokens generated so far (kept
        # across preemption), preemption count, enqueue timestamp on the
        # engine's clock, and the admission seq — assigned at FIRST
        # admission and preserved so the globally oldest request is never
        # a preemption victim (the no-livelock guarantee).
        self.generated: List[int] = []
        self.n_preempted = 0
        self.t_enqueue = 0.0
        self.seq: Optional[int] = None
        # observability timestamps (engine clock, r11): first admission,
        # first token ever sampled, last token delivered — the engine
        # derives queue-wait / TTFT / time-between-token histograms from
        # these; all survive preemption (a recomputed request keeps its
        # original TTFT) and snapshot/restore.
        self.t_admitted: Optional[float] = None
        self.t_first_token: Optional[float] = None
        self.t_last_token: Optional[float] = None
        # fair-queueing service accounting (r12): ``vt_charged`` is the
        # total first-time-served tokens already charged to the tenant's
        # virtual counter; ``max_prompt_prefilled`` is the high-water
        # mark of ORIGINAL-prompt positions ever prefilled.  Both are
        # monotone across preemption, which is exactly what makes a
        # recompute free: re-prefilling positions below the high-water
        # mark raises neither, so ``uncharged_tokens`` stays 0 for them.
        self.vt_charged = 0
        self.max_prompt_prefilled = 0
        # speculative decoding observability (r13): draft tokens this
        # request's verify dispatches scored / accepted.  Survive
        # preemption and snapshot (they are cumulative request history);
        # the engine observes accepted/drafted into the acceptance-rate
        # histogram at the terminal.  NOT service accounting: WFQ charges
        # through ``uncharged_tokens`` — only ACCEPTED tokens ever enter
        # ``generated``, so rejected drafts bill zero by construction.
        self.spec_drafted = 0
        self.spec_accepted = 0

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    @property
    def total_len(self) -> int:
        """Worst-case positions ever needed — invariant under preemption
        (``work_len + remaining_new`` is constant)."""
        return self.prompt_len + self.max_new_tokens

    @property
    def work_len(self) -> int:
        """Positions needing K/V before the next decode: the original
        prompt plus every token generated so far."""
        return self.prompt_len + len(self.generated)

    @property
    def remaining_new(self) -> int:
        return self.max_new_tokens - len(self.generated)

    def work_prompt(self) -> np.ndarray:
        """The token sequence to (re)prefill: prompt + generated."""
        if not self.generated:
            return self.prompt
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    def expired(self, now: float) -> bool:
        return (self.deadline_s is not None
                and now - self.t_enqueue > self.deadline_s)

    # -- fair-queueing service accounting (r12) ---------------------------

    def note_prefill_progress(self, prefilled: int) -> None:
        """``prefilled`` counts WORK-prompt positions with K/V written.
        Only original-prompt positions past the high-water mark are
        first-time service — generated tokens re-prefilled after a
        preemption were already charged when they were decoded."""
        self.max_prompt_prefilled = max(
            self.max_prompt_prefilled, min(prefilled, self.prompt_len))

    def uncharged_tokens(self) -> int:
        """Tokens served for the first time since the last call: the
        delta of the monotone ``max_prompt_prefilled + len(generated)``.
        Recomputed (post-preemption) work never raises it, so the
        tenant's virtual counter is charged exactly once per token."""
        served = self.max_prompt_prefilled + len(self.generated)
        delta = served - self.vt_charged
        self.vt_charged = served
        return delta


@dataclass
class Admission:
    """One scheduling decision: request -> slot, with its pages.

    ``pages`` are freshly leased (refcount 1, this request's alone);
    ``cached`` are prefix-index pages shared read-only (already retained);
    ``cow`` is an optional ``(source_page, n_tokens)`` partial-tail match
    the engine must copy-on-write into ``pages[0]`` (the source is
    retained until the engine releases it after the copy); ``matched`` is
    the total prompt tokens whose K/V need no recompute."""

    slot: int
    request: Request
    pages: List[int]
    cached: List[int] = field(default_factory=list)
    cow: Optional[Tuple[int, int]] = None
    matched: int = 0


class FCFSScheduler:
    """Iteration-level admission over a fixed slot array.  Queue ORDER
    comes from ``policy`` (default: true FCFS); slots, pages and the
    token budget are policy-independent.  The name survives from r08 —
    every call site and test builds this class."""

    def __init__(self, n_slots: int, pool: KVPool,
                 token_budget: Optional[int] = None,
                 policy: Union[None, str, SchedulerPolicy] = None,
                 tenants=None):
        self.n_slots = n_slots
        self.pool = pool
        # default budget: every slot decoding plus one flagship-sized
        # prefill chunk per step keeps step latency bounded without
        # starving admission
        self.token_budget = token_budget or (n_slots + 512)
        self.policy: SchedulerPolicy = make_policy(policy, tenants)
        self._free_slots: List[int] = list(range(n_slots - 1, -1, -1))

    # -- queue ------------------------------------------------------------

    @property
    def waiting(self) -> List[Request]:
        """Every waiting request, in the policy's deterministic
        iteration order (FCFS: arrival order).  A fresh list each call —
        mutate through the scheduler's methods, not this view."""
        return list(self.policy)

    def add(self, request: Request) -> int:
        max_tokens = (self.pool.num_pages - 1) * self.pool.page_size
        if request.total_len > max_tokens:
            raise ValueError(
                f"request {request.rid} needs {request.total_len} tokens; "
                f"the pool holds {max_tokens} — raise num_pages/max_seq_len")
        self.policy.push(request)
        return request.rid

    def requeue(self, request: Request) -> None:
        """Put a PREEMPTED request back at the head of the queue: it was
        admitted before anything still waiting, so FCFS order puts it in
        front (multiple preemptions in one step requeue youngest-first,
        each head-insert landing the older one ahead; under WFQ, the head
        of its tenant's queue).  Bypasses the engine's backpressure bound
        — the request was already accepted."""
        self.policy.requeue_head(request)

    def remove_waiting(self, rid: int) -> Optional[Request]:
        """Remove and return the waiting request with ``rid`` (cancel),
        or None if it is not queued."""
        return self.policy.remove(rid)

    def pop_expired(self, now: float) -> List[Request]:
        """Drop every waiting request whose deadline has passed (checked
        at queue-pop time, before this step's admissions)."""
        return self.policy.pop_expired(now)

    def quota_reject(self, tenant: Optional[str]) -> bool:
        """Per-tenant backpressure (engine consults at enqueue)."""
        return self.policy.quota_reject(tenant)

    def charge(self, request: Request, n_tokens: int) -> None:
        """Account ``n_tokens`` of first-time service to the request's
        tenant (WFQ virtual counters; FCFS ignores)."""
        self.policy.charge(request, n_tokens)

    def load_waiting(self, requests: List[Request]) -> None:
        """Snapshot-restore path: refill the queue without arrival side
        effects (policy counters load separately)."""
        self.policy.load_waiting(requests)

    def note_restored_slot(self, request: Request) -> None:
        """Snapshot-restore path: a slot came back occupied — give the
        policy its residency accounting without re-admitting."""
        self.policy.on_admit(request)

    @property
    def n_waiting(self) -> int:
        return len(self.policy)

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free_slots)

    @property
    def has_work(self) -> bool:
        return len(self.policy) > 0 or self.n_active > 0

    # -- per-step decisions ----------------------------------------------

    def prefill_budget(self, n_decoding: int, chunk_tokens: int,
                       decode_cost: int = 1) -> int:
        """Sarathi chunk budget for one step: the token budget left after
        paying ``decode_cost`` tokens per active decode, capped at the
        engine's chunk program width and floored at 1 so prefill always
        progresses even when decodes alone exceed the budget.
        ``decode_cost`` is 1 for plain decode; a speculative engine
        reserves ``spec_k + 1`` per decoding slot — the verify dispatch
        scores that many positions whether or not they are accepted, so
        the step's compute reservation must not be distorted by
        speculation (WFQ SERVICE charging, by contrast, bills accepted
        tokens only, through ``Request.uncharged_tokens``)."""
        return max(1, min(chunk_tokens,
                          self.token_budget - n_decoding * decode_cost))

    def schedule_step(self) -> List[Admission]:
        """Admit from the policy's queue into free slots until slots or
        pages run out.  Head-of-line blocking is intentional (fairness):
        if the chosen head's pages don't fit we stop, we don't scan
        deeper for a smaller request — under WFQ "the head" is the
        lowest-virtual-counter eligible tenant's oldest request, FCFS
        within the tenant.  Page demand covers the WORK PROMPT only
        (prompt + any preemption-survived tokens) — decode pages are
        allocated on demand by the engine, which preempts under pressure.
        Prefix-cache matching happens here, while this step's page
        arithmetic is decided: matched full pages are retained (shared)
        instead of allocated, and a partial-tail match rides along as the
        COW candidate."""
        admissions: List[Admission] = []
        while self._free_slots:
            req = self.policy.peek()
            if req is None:
                break
            work = req.work_prompt()
            cached: List[int] = []
            cow: Optional[Tuple[int, int]] = None
            held: List[int] = []
            if self.pool.prefix is not None:
                # never match the whole prompt: the last token must be
                # prefilled so its logits exist to sample the first output
                cached, cow = self.pool.prefix.match(work[:-1])
                held = list(cached) + ([cow[0]] if cow else [])
                # pin matches BEFORE alloc — alloc may LRU-evict
                # reclaimable cached pages to satisfy the fresh lease
                self.pool.retain(held)
            need = self.pool.pages_for(req.work_len) - len(cached)
            pages = self.pool.alloc(need)
            if pages is None and cow is not None:
                # the pinned COW source inflates peak demand by one page
                # beyond the admission arithmetic (pages_for(work_len));
                # for a request sized to the remaining pool that ONE page
                # can make alloc fail forever — drop the partial match
                # (full-page matches only ever reduce demand) and retry
                self.pool.release([cow[0]])
                held, cow = list(cached), None
                pages = self.pool.alloc(need)
            if pages is None:
                if held:
                    self.pool.release(held)
                break
            matched = len(cached) * self.pool.page_size + \
                (cow[1] if cow else 0)
            popped = self.policy.pop()
            if popped is not req:           # peek/pop must agree
                raise AssertionError(
                    "scheduler policy popped a different request than it "
                    "peeked — admission page arithmetic is now wrong")
            self.policy.on_admit(req)
            slot = self._free_slots.pop()
            admissions.append(Admission(slot=slot, request=req, pages=pages,
                                        cached=cached, cow=cow,
                                        matched=matched))
        return admissions

    def release(self, slot: int, pages: List[int],
                request: Optional[Request] = None) -> None:
        """A request finished (or was preempted): its slot frees and
        every page reference it held drops (shared prefix pages simply
        lose one reference; pages reaching refcount 0 return to the free
        list unless the prefix index keeps them reclaimable).  ``request``
        lets the policy drop its residency accounting."""
        if slot in self._free_slots:
            raise ValueError(f"double release of slot {slot}")
        self.pool.release(pages)
        self._free_slots.append(slot)
        if request is not None:
            self.policy.on_release(request)
