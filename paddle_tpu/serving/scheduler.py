"""FCFS continuous-batching scheduler (Orca, OSDI '22).

The scheduler owns the WAITING queue, the slot occupancy map and the
per-step token budget; the engine owns the device programs.  Every engine
step asks :meth:`FCFSScheduler.schedule_step` which requests to admit
into freed slots, then runs ONE decode step over all occupied slots —
iteration-level scheduling instead of run-to-completion batches.

Budget semantics (Orca's "token budget"): one engine step costs
``n_active`` decode tokens (one per occupied slot) plus the FULL prompt
length of every request admitted this step (its prefill runs before the
step's decode).  Admission stops when the budget is spent, so a burst of
long prompts cannot starve in-flight decodes of step latency; a lone
request is force-admitted even over budget (no deadlock when the budget
is smaller than a prompt).

Page accounting is conservative: a request is admitted only when the pool
can hold its WHOLE worst-case sequence (prompt + max_new_tokens), so an
admitted request can never die of page exhaustion mid-flight (no
preemption/swap tier — requests are small relative to the pool; add
eviction here if that stops holding).
"""

from __future__ import annotations

import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, List, Optional, Tuple

import numpy as np

from .kv_pool import KVPool

_rid_counter = itertools.count()


@dataclass
class Request:
    """One generation request: token ids in, up to ``max_new_tokens`` out."""

    prompt: np.ndarray
    max_new_tokens: int
    rid: int = field(default_factory=lambda: next(_rid_counter))
    arrival: float = 0.0

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.size)

    @property
    def total_len(self) -> int:
        return self.prompt_len + self.max_new_tokens


@dataclass
class Admission:
    """One scheduling decision: request -> slot, with its pages."""

    slot: int
    request: Request
    pages: List[int]


class FCFSScheduler:
    """First-come-first-served admission over a fixed slot array."""

    def __init__(self, n_slots: int, pool: KVPool,
                 token_budget: Optional[int] = None):
        self.n_slots = n_slots
        self.pool = pool
        # default budget: every slot decoding plus one flagship-sized
        # prefill per step keeps step latency bounded without starving
        # admission
        self.token_budget = token_budget or (n_slots + 512)
        self.waiting: Deque[Request] = deque()
        self._free_slots: List[int] = list(range(n_slots - 1, -1, -1))

    # -- queue ------------------------------------------------------------

    def add(self, request: Request) -> int:
        max_tokens = (self.pool.num_pages - 1) * self.pool.page_size
        if request.total_len > max_tokens:
            raise ValueError(
                f"request {request.rid} needs {request.total_len} tokens; "
                f"the pool holds {max_tokens} — raise num_pages/max_seq_len")
        self.waiting.append(request)
        return request.rid

    @property
    def n_waiting(self) -> int:
        return len(self.waiting)

    @property
    def n_active(self) -> int:
        return self.n_slots - len(self._free_slots)

    @property
    def has_work(self) -> bool:
        return bool(self.waiting) or self.n_active > 0

    # -- per-step decisions ----------------------------------------------

    def schedule_step(self) -> List[Admission]:
        """Admit FCFS from the waiting queue into free slots until slots,
        pages or the step's token budget run out.  Head-of-line blocking
        is intentional (FCFS fairness): if the HEAD doesn't fit we stop,
        we don't scan deeper for a smaller request."""
        admissions: List[Admission] = []
        budget = self.token_budget - self.n_active
        while self.waiting and self._free_slots:
            req = self.waiting[0]
            if req.prompt_len > budget:
                # force-admit a lone request so an over-budget prompt can't
                # deadlock an idle engine
                if self.n_active > 0 or admissions:
                    break
            pages = self.pool.alloc(self.pool.pages_for(req.total_len))
            if pages is None:
                break
            self.waiting.popleft()
            slot = self._free_slots.pop()
            admissions.append(Admission(slot=slot, request=req, pages=pages))
            budget -= req.prompt_len
        return admissions

    def release(self, slot: int, pages: List[int]) -> None:
        """A request finished: its slot and pages return to the free pools
        (next step's schedule_step can hand them straight out again)."""
        if slot in self._free_slots:
            raise ValueError(f"double release of slot {slot}")
        self.pool.free(pages)
        self._free_slots.append(slot)
