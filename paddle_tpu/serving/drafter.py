"""Host-side n-gram self-drafting for speculative decoding (r13).

Prompt-lookup decoding (the PLD / vLLM ``ngram`` speculator, the
weight-free end of the Medusa/EAGLE draft-model line): the draft "model"
is the request's OWN token history.  Repetitive and extractive workloads
— code, quotes, structured extraction, templated answers — keep emitting
spans that already occurred earlier in prompt + generated; matching the
history's trailing n-gram against its earlier occurrences and proposing
the continuation that followed the most recent match recovers those
spans without any extra weights or device work.

The drafter is deliberately HOST-ONLY and model-free:

  * pure numpy over the request's ``work_prompt()`` (prompt + generated)
    — no device dispatch, no state of its own, so draft buffers are
    always reconstructible from request history (snapshot/restore needs
    nothing from it, and a step fault between drafting and verify simply
    re-drafts next step);
  * deterministic: same history -> same proposal, which is what lets the
    engine's speculative greedy decode stay token-for-token identical to
    non-speculative decode (the verify pass, not the drafter, decides
    what is emitted — a bad draft only costs speed, never correctness);
  * duck-typed: the engine accepts any object with
    ``draft(history, max_tokens) -> np.ndarray`` (tests inject oracle /
    adversarial drafters to pin the full-accept and full-reject paths).

Stays jax/numpy/stdlib-only — enforced by the serving AST import guard
(tests/test_metrics.py).
"""

from __future__ import annotations

import numpy as np


class NGramDrafter:
    """Propose up to ``spec_k`` tokens by prompt lookup.

    Matches the history's trailing n-gram for ``n`` from ``max_ngram``
    down to ``min_ngram`` (longer matches are more predictive, so they
    win); within one ``n`` the MOST RECENT earlier occurrence wins (local
    context beats distant context).  The proposal is the ``spec_k``
    tokens that followed the match — possibly overlapping the suffix
    itself, which is exactly right for periodic continuations.  No match
    at any ``n`` proposes nothing: the engine's verify step then runs as
    a plain one-token decode.
    """

    def __init__(self, spec_k: int, max_ngram: int = 3, min_ngram: int = 1):
        if spec_k < 1:
            raise ValueError("spec_k must be >= 1")
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError("need 1 <= min_ngram <= max_ngram")
        self.spec_k = int(spec_k)
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def draft(self, history, max_tokens: int | None = None) -> np.ndarray:
        """Up to ``min(spec_k, max_tokens)`` proposed continuation tokens
        of ``history`` (1-D int tokens), possibly empty.  O(len * ngram)
        numpy per call — noise next to one device dispatch."""
        h = np.asarray(history, np.int32).reshape(-1)
        k = self.spec_k if max_tokens is None else min(self.spec_k,
                                                       int(max_tokens))
        if k < 1 or h.size < self.min_ngram + 1:
            return np.zeros((0,), np.int32)
        for n in range(min(self.max_ngram, h.size - 1),
                       self.min_ngram - 1, -1):
            suffix = h[h.size - n:]
            # windows starting before the trailing suffix itself
            wins = np.lib.stride_tricks.sliding_window_view(
                h, n)[: h.size - n]
            hits = np.flatnonzero((wins == suffix).all(axis=1))
            if hits.size:
                j = int(hits[-1]) + n
                return h[j:j + k].copy()
        return np.zeros((0,), np.int32)
