"""Continuous-batching serving engine over the paged KV pool.

The static-batch decoder (``models/generation.build_generate_fn``) jits
prefill + ``max_new_tokens`` decode steps as ONE program over a fixed
batch: finished sequences keep burning decode steps until the longest
request ends, and a new request cannot join until the whole batch
drains.  This engine instead runs serving as TWO reusable jitted
programs called from a host loop:

  * ``chunk prefill``: up to ``chunk_tokens`` of ONE request's prompt
    per call — embeddings, ``_block_qkv``, the chunk's K/V scattered
    into the slot's pool pages, then paged attention of the chunk
    against everything already written (cached prefix pages, earlier
    chunks, itself) via the block table — the Sarathi-Serve chunked
    prefill (kernels/paged_prefill.py).  Chunk widths pad to power-of-two
    buckets so the program retraces per bucket, not per length.  A long
    prompt no longer stalls every in-flight decode for a monolithic
    prefill: each step spends at most the scheduler's chunk budget on
    prefill, co-scheduled with decode.
  * ``decode``: ONE token for EVERY started slot — per-slot paged KV
    write at each slot's own position, paged attention through the block
    table (kernels/paged_attention.py), sampling.  Slot count is static;
    inactive/partially-prefilled lanes compute into the pool's null page
    and are ignored.

Prefix caching (RadixAttention, SGLang) rides on the page pool: at
admission the scheduler matches the prompt against the pool's
token-chunk radix index, the request's block table starts with the
matched pages SHARED (refcounted, read-only), a partial-tail match is
COPY-ON-WRITE cloned into a fresh page, and only the uncached suffix is
chunk-prefilled.  When a prompt finishes prefilling, its full pages are
inserted into the index; a finished request's pages drop their reference
and cached pages park reclaimable (LRU-evicted under pressure) instead
of being eagerly freed — a shared system prompt is computed once and
reused by every later request.

Fault tolerance (r10) — the engine degrades instead of failing:

  * **On-demand page growth + preempt-and-recompute.**  Admission
    reserves pages for the PROMPT only; decode allocates one page the
    step a slot crosses a page boundary.  When growth (or admission)
    meets an empty pool, the engine preempts the YOUNGEST occupied slot
    — pages freed, generated tokens kept on the request, requeued at
    the head of the waiting queue for recompute-restart through the
    chunked-prefill path (vLLM's preempt-by-recompute; the prefix cache
    makes the recompute cheap because the victim's full prompt pages
    park reclaimable and are re-adopted at re-admission).  The OLDEST
    request (admission seq preserved across preemptions) is never a
    victim, so it always progresses — no livelock.  Greedy outputs are
    token-for-token identical to an unpressured run.
  * **Request lifecycle.**  ``deadline_s`` expires a request at
    queue-pop and per-step; ``cancel(rid)`` works in any state (waiting,
    mid-prefill, decoding — pages released the same call); ``max_queue``
    bounds the waiting queue and converts overflow into an explicit
    ``rejected`` terminal (backpressure) instead of unbounded growth.
    Every request ends in EXACTLY one of
    {``eos``, ``length``, ``rejected``, ``expired``, ``cancelled``},
    delivered as a :class:`FinishedRequest` from ``step()``.
  * **Snapshot / restore.**  ``snapshot()`` captures queue + slot
    metadata + pool/prefix state + host mirrors;
    ``ServingEngine.restore`` resumes a killed host loop with
    token-for-token identical output (serving/snapshot.py).
  * **Deterministic fault injection.**  A ``faults=FaultPlan`` scripts
    alloc failures, phase-boundary step exceptions and virtual step
    latency by step index (serving/faults.py); the engine absorbs them
    (``stats["step_faults"]``) and the chaos suite asserts
    terminal-state totality + leak-free drain under any seed.

Every host-loop iteration the FCFS scheduler admits waiting requests
into freed slots, the chunk budget advances partial prefills, exactly
one decode call covers the started slots, and finished requests return —
iteration-level scheduling (Orca) with block-table paging (vLLM),
composed with the int8 W8A8 + int8-KV serving path from the dense
decoder: the per-(layer, batch, head, position) scale layout carries
over to per-page scales unchanged.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..models.generation import (
    _block_finish,
    _block_qkv,
    _decoder_setup,
    _lm_head,
    _make_sampler,
    _resolve_kv_bits,
    spec_accept_greedy,
)
from ..kernels import paged_attention as pa
from ..kernels import paged_prefill as pp
from .drafter import NGramDrafter
from .faults import FaultPlan, InjectedFault
from .flight_recorder import FlightRecorder
from .kv_pool import KVPool
from .metrics import MetricsRegistry, SLOTracker
from .scheduler import FCFSScheduler, Request
from .tenancy import normalize_tenants
from .tracing import PID_ENGINE, PID_REQUESTS, TraceRecorder, flow_id

#: Reasons a request leaves the engine.  "eos"/"length" are successful
#: completions; the r10 lifecycle adds the degraded terminals.
TERMINAL_REASONS = ("eos", "length", "rejected", "expired", "cancelled")


@dataclasses.dataclass
class FinishedRequest:
    """One terminal request: the continuation produced (prompt excluded).

    ``finish_reason`` is one of :data:`TERMINAL_REASONS`; ``reason`` is
    the same value under the r10 lifecycle name.  For degraded terminals
    (``rejected``/``expired``/``cancelled``) ``tokens`` holds whatever
    was generated before the request left (possibly empty)."""

    rid: int
    prompt: np.ndarray
    tokens: np.ndarray            # generated continuation, EOS included
    finish_reason: str
    n_steps: int                  # engine steps it was resident

    @property
    def reason(self) -> str:
        return self.finish_reason

    @property
    def ok(self) -> bool:
        """True when the request ran to completion (eos/length)."""
        return self.finish_reason in ("eos", "length")


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class _Slot:
    """Host-side state of one occupied engine slot."""

    def __init__(self, request: Request, pages: List[int], prefilled: int,
                 seq: int, base_len: int):
        self.request = request
        self.pages = pages            # table order: shared prefix + owned
        # generated tokens live ON THE REQUEST so they survive preemption;
        # the slot aliases the same list
        self.tokens: List[int] = request.generated
        self.born_step = 0
        self.seq = seq                # admission order (FCFS, preserved
        #                               across preemption — oldest is
        #                               never a preemption victim)
        self.base_len = base_len      # work-prompt length at admission
        self.prefilled = prefilled    # work positions with K/V in pages
        # high-water LOGICAL page count — how many block-table entries
        # have ever been populated.  Without a window it always equals
        # len(pages); windowed recycling frees dead leading pages (their
        # table entries become the null page) so len(pages) shrinks while
        # hw_pages keeps marking where the next growth appends
        self.hw_pages = len(pages)
        self.started = False          # first token sampled; decoding
        # speculative draft buffer (r13): host-only, overwritten by every
        # spec step's fresh proposal — reconstructible from the request
        # history, so snapshots never capture it and a step fault between
        # drafting and verify costs nothing but the proposal
        self.draft: List[int] = []


class ServingEngine:
    """Continuous-batching generation over a paged KV cache.

    ``max_slots`` bounds the decode batch (the step's static shape);
    ``page_size`` the pool granularity; ``num_pages`` the pool size
    (default: enough for every slot at ``max_seq_len``, +1 null page);
    ``token_budget`` the scheduler's per-step token budget (decode tokens
    + prefill chunk); ``chunk_tokens`` the chunk-prefill program width —
    prompts longer than a step's chunk budget prefill across steps,
    co-scheduled with decode; ``prefix_cache`` reuses KV pages across
    requests sharing a page-aligned token prefix.  Sampling knobs mirror
    ``build_generate_fn``; ``int8`` serves W8A8 projections + int8 KV
    pages.  ``use_paged_kernel`` forces the Pallas kernels (or the jnp
    references) instead of auto-dispatch — tests use it to pin the
    interpret-mode kernel path on CPU.

    r10 lifecycle knobs: ``max_queue`` bounds the waiting queue (overflow
    becomes a ``rejected`` terminal); ``faults`` installs a
    :class:`~paddle_tpu.serving.faults.FaultPlan`; ``clock`` overrides
    the deadline clock (a zero-arg callable returning seconds — defaults
    to the fault plan's virtual clock when one is set, else
    ``time.monotonic``).

    r11 observability knobs: ``metrics`` feeds a
    :class:`~paddle_tpu.serving.metrics.MetricsRegistry` every step
    (pass a registry, or ``True`` to create one; ``None`` = off — the
    hot loop then pays zero metric cost); ``trace`` records the
    per-request lifecycle + engine step phases as Chrome trace-event
    JSON (pass a :class:`~paddle_tpu.serving.tracing.TraceRecorder`, or
    ``True`` to create one).  ``run(metrics_dir=...)`` exports both:
    TensorBoard scalars per step, a ``metrics.prom`` Prometheus text
    dump and ``trace.json`` (open in Perfetto) at drain.  Request-time
    observations (queue wait, TTFT, time-between-tokens, e2e latency)
    are measured on the ENGINE clock, so a FaultPlan's virtual clock
    makes their histograms bit-deterministic.

    r12 multi-tenancy/streaming knobs: ``policy`` picks the waiting-
    queue order (``"fcfs"`` default, ``"wfq"`` for weighted fair
    queueing over per-tenant virtual token counters, or a
    :class:`~paddle_tpu.serving.tenancy.SchedulerPolicy` instance);
    ``tenants`` maps tenant name -> weight /
    :class:`~paddle_tpu.serving.tenancy.TenantConfig` (naming tenants
    implies WFQ); ``on_token(rid, token)`` observes every sampled token
    in delivery order — the streaming HTTP front end
    (:class:`~paddle_tpu.serving.frontend.ServingFrontend`) builds SSE
    on it.  Requests carry ``tenant=`` through :meth:`add_request`;
    per-tenant token/terminal counters land in the metrics registry as
    labeled series (``serving_tenant_*{tenant="..."}``).

    r13 speculative-decoding knobs: ``spec_k`` > 0 proposes up to that
    many draft tokens per slot per step from the request's own history
    (:class:`~paddle_tpu.serving.drafter.NGramDrafter` with
    ``spec_ngram`` as the longest n-gram matched; ``drafter=`` injects
    any object with ``draft(history, max_tokens)``), verifies them all
    in ONE multi-query paged-attention dispatch
    (``kernels/paged_attention.paged_attention_mq``) and accepts the
    longest agreeing prefix plus one corrected token — greedy output is
    token-for-token identical to ``spec_k=0``, only faster when drafts
    accept.  Requires greedy sampling, replaces ``decode_block`` fusion,
    and bills WFQ tenants by ACCEPTED tokens only.  Acceptance telemetry:
    ``stats["spec_drafted"/"spec_accepted"/"spec_rejected"]`` and the
    ``serving_spec_acceptance_rate`` per-request histogram.

    r15 disaggregation knobs: ``role`` splits prefill from decode —
    ``"prefill"`` engines run chunked prefill to completion, then export
    every started slot as a handoff record (request + block-table-order
    page payloads + quantization scales, snapshot v5 wire format) via
    :meth:`drain_handoffs`; ``"decode"``/``"both"`` engines adopt the
    pages bit-exactly through :meth:`ingest_handoff` (layout-guarded,
    prompt pages re-indexed for prefix reuse, zero recompute).
    :class:`~paddle_tpu.serving.router.Router` wires replicas together
    with cache-affinity routing and router-global WFQ.
    ``double_buffer=True`` defers the decode sync one step so the host
    schedules step N+1 while step N runs on device —
    ``stats["decode_sync_s"]`` shows the overlap win; incompatible with
    ``spec_k`` (drafting needs the retired history).
    """

    def __init__(self, model, *, max_slots: int = 8, page_size: int = 32,
                 max_seq_len: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 token_budget: Optional[int] = None,
                 greedy: bool = True, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 int8: Optional[bool] = None, seed: int = 0,
                 decode_block: int = 1,
                 use_paged_kernel: Optional[bool] = None,
                 chunk_tokens: int = 128, prefix_cache: bool = True,
                 max_queue: Optional[int] = None,
                 faults: Optional[FaultPlan] = None,
                 clock: Optional[Callable[[], float]] = None,
                 metrics=None, trace=None, flight=None,
                 policy=None, tenants=None,
                 on_token: Optional[Callable[[int, int], None]] = None,
                 spec_k: int = 0, spec_ngram: int = 3, drafter=None,
                 kv_bits: Optional[int] = None,
                 attn_window: Optional[int] = None,
                 role: str = "both", double_buffer: bool = False):
        cfg = model.cfg
        self.cfg = cfg
        # r15 disaggregation: "prefill" engines run chunked prefill to
        # completion and HAND OFF (request + page payload) instead of
        # decoding; "decode" engines adopt handoffs into fresh pages and
        # decode them; "both" (default) is the monolithic r08-r14 engine.
        if role not in ("prefill", "decode", "both"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'both', got {role!r}")
        self.role = role
        # r15 double-buffered dispatch: defer the decode sync one step —
        # step N's dispatched program runs on device while the host
        # admits/prefills step N+1; finishes deliver one step late,
        # greedy outputs are schedule-invariant so parity holds.
        self.double_buffer = bool(double_buffer)
        if self.double_buffer and spec_k:
            raise ValueError(
                "double_buffer is incompatible with speculative decoding "
                "(spec_k > 0): drafting reads the retired token history "
                "the deferred sync has not produced yet")
        # decode_block > 1 fuses that many decode steps into ONE dispatched
        # lax.scan (multi-step scheduling): admission/finish granularity
        # coarsens to the block, but the host->device dispatch latency —
        # ~65ms through the TPU tunnel (bench._int8_microbench) — is paid
        # once per block instead of once per token.  1 = pure
        # admit-every-step continuous batching (the parity-test mode).
        self.decode_block = max(1, int(decode_block))
        # spec_k > 0 turns the decode dispatch SPECULATIVE (r13): a
        # host-side drafter proposes up to spec_k tokens per slot from the
        # request's own history, one verify dispatch scores carry + all
        # draft positions, and the greedy rejection rule accepts the
        # longest agreeing prefix plus the target's correction token —
        # 1..spec_k+1 tokens per dispatch, token-for-token identical to
        # non-speculative greedy decode.
        self.spec_k = max(0, int(spec_k))
        if self.spec_k:
            if not greedy:
                raise ValueError(
                    "speculative decoding (spec_k > 0) requires greedy "
                    "sampling — the longest-agreeing-prefix rule is the "
                    "greedy special case of rejection sampling")
            if self.decode_block > 1:
                raise ValueError(
                    "spec_k > 0 replaces decode_block fusion: the verify "
                    "dispatch already scores spec_k+1 positions per step")
        self._drafter = drafter if drafter is not None else (
            NGramDrafter(self.spec_k, max_ngram=spec_ngram)
            if self.spec_k else None)
        self.params, _, self.int8 = _decoder_setup(model, int8=int8)
        self.n_heads = cfg.num_heads
        self.n_kv_heads = getattr(cfg, "num_kv_heads", None) or cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.eps = cfg.layer_norm_eps
        # KV-capacity knobs (this PR): kv_bits / attn_window override the
        # model config's defaults; the resolved values fix the pool's page
        # layout and every attention dispatch's masking for the engine's
        # whole lifetime (snapshot v5 records them; restore refuses a
        # mismatched layout)
        self.kv_bits = _resolve_kv_bits(cfg, self.int8, kv_bits)
        win = attn_window if attn_window is not None \
            else getattr(cfg, "attn_window", None)
        if win is not None and int(win) < 1:
            raise ValueError(f"attn_window must be >= 1, got {win}")
        self.window = None if win is None else int(win)
        self.max_slots = max_slots
        self.page_size = page_size
        self.max_seq_len = max_seq_len or cfg.max_seq_len
        if self.max_seq_len > cfg.max_seq_len:
            raise ValueError("max_seq_len exceeds the model's position table")
        self.max_pages = -(-self.max_seq_len // page_size)
        self.eos_token_id = eos_token_id
        self.chunk_tokens = max(1, min(int(chunk_tokens), self.max_seq_len))
        self.max_queue = max_queue
        self.faults = faults
        if clock is not None:
            self._clock = clock
        elif faults is not None:
            self._clock = faults.now
        else:
            # the ONE sanctioned wall-clock binding: when neither an
            # explicit clock nor a FaultPlan is injected, real time is
            # the semantics (production); replay paths always inject
            self._clock = time.monotonic  # graftlint: allow=determinism
        dtype = self.params["wte"].dtype
        n_pages = num_pages or (1 + max_slots * self.max_pages)
        self.pool = KVPool(cfg.num_layers, cfg.num_heads, self.head_dim,
                           n_pages, page_size, dtype=dtype,
                           prefix_cache=prefix_cache,
                           num_kv_heads=self.n_kv_heads,
                           kv_bits=self.kv_bits, window=self.window)
        self.pool.faults = faults
        self.scheduler = FCFSScheduler(max_slots, self.pool,
                                       token_budget=token_budget,
                                       policy=policy, tenants=tenants)
        # per-token observer (r12): called as on_token(rid, token) once
        # for every token the engine samples for a live request —
        # prefill-completion samples and decode tokens alike, in exactly
        # the order they land on FinishedRequest.tokens.  The streaming
        # front end (serving/frontend.py) hangs SSE delivery off this.
        # Settable after construction; like faults/clock it is NOT part
        # of a snapshot.
        self.on_token = on_token
        self._sample = _make_sampler(greedy, temperature, top_k, top_p)
        if use_paged_kernel is None:
            self._use_kernel = pa.available() and pa.supported(
                cfg.num_heads, page_size, self.head_dim,
                n_kv_heads=self.n_kv_heads, kv_bits=self.kv_bits)
            self._use_prefill_kernel = pp.available() and pp.supported(
                cfg.num_heads, page_size, self.head_dim, self.chunk_tokens,
                n_kv_heads=self.n_kv_heads, kv_bits=self.kv_bits)
            self._use_spec_kernel = pa.available() and pa.supported_mq(
                cfg.num_heads, page_size, self.head_dim, self.spec_k + 1,
                n_kv_heads=self.n_kv_heads, kv_bits=self.kv_bits)
        else:
            self._use_kernel = bool(use_paged_kernel)
            self._use_prefill_kernel = bool(use_paged_kernel)
            self._use_spec_kernel = bool(use_paged_kernel)

        # ctor echo for snapshot/restore (serving/snapshot.py): enough to
        # rebuild an equivalent engine around the captured state.  faults
        # and clock are deliberately NOT part of a snapshot.
        self._config = dict(
            max_slots=max_slots, page_size=page_size,
            max_seq_len=self.max_seq_len, num_pages=n_pages,
            token_budget=self.scheduler.token_budget, greedy=greedy,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_token_id=eos_token_id, int8=self.int8, seed=seed,
            decode_block=decode_block, use_paged_kernel=use_paged_kernel,
            chunk_tokens=chunk_tokens, prefix_cache=prefix_cache,
            max_queue=max_queue,
            # resolved KV layout knobs (not the raw ctor args): a restored
            # engine must land on the SAME page layout whatever the model
            # config defaults were at snapshot time
            kv_bits=self.kv_bits, attn_window=self.window,
            # spec_k/spec_ngram rebuild the NGramDrafter at restore; a
            # custom drafter instance is like faults/clock — not
            # snapshot-portable (draft buffers themselves are transient
            # host state, reconstructible from request history)
            spec_k=self.spec_k, spec_ngram=spec_ngram,
            # the POLICY NAME, not the instance: a restored engine
            # rebuilds the named policy and reloads its counters from
            # the snapshot's scheduler state (a custom SchedulerPolicy
            # instance is like faults/clock — not snapshot-portable)
            policy=self.scheduler.policy.name,
            tenants=({t: dataclasses.asdict(c)
                      for t, c in normalize_tenants(tenants).items()}
                     if tenants else None),
            role=role, double_buffer=self.double_buffer)

        # host mirrors of the decode step's device operands
        self._tokens_this_step = 0
        self._phase_s: Dict[str, tuple] = {}
        self._slots: List[Optional[_Slot]] = [None] * max_slots
        self._tok = np.zeros((max_slots,), np.int32)
        self._len = np.zeros((max_slots,), np.int32)
        self._table = np.zeros((max_slots, self.max_pages), np.int32)
        self._key = jax.random.PRNGKey(seed)
        self._step_idx = 0
        self._admit_seq = 0
        # terminals produced OUTSIDE step() (reject at enqueue, cancel,
        # …) park here and are delivered by the next step()
        self._pending: List[FinishedRequest] = []
        # r15 disaggregation queues: a prefill-role engine parks finished
        # handoff records in the OUTBOX (the router pumps them away); a
        # decode/both engine queues ingested records in the INBOX until a
        # slot + pages free up.  Inbox payloads are host numpy — they
        # hold no pool pages, so the leak audits are unaffected.
        self._handoff_out: List[dict] = []
        self._handoff_in: List[dict] = []
        # r15 double-buffered dispatch: the un-retired decode future —
        # ((slot, _Slot) pairs, remaining mirror, device tokens, t_dispatch)
        self._inflight: Optional[tuple] = None
        self.stats = {"prefill_calls": 0, "decode_calls": 0,
                      "prefill_traces": 0, "decode_traces": 0,
                      "tokens_generated": 0,
                      "prefix_hit_tokens": 0, "prompt_tokens": 0,
                      "pages_in_use": 0, "queue_depth": 0,
                      "step_wall_s": 0.0, "last_step_s": 0.0,
                      # per-phase wall time (r11): cumulative + last-step,
                      # so admit/prefill/decode no longer conflate into
                      # one step_wall_s bucket
                      "admit_s": 0.0, "prefill_s": 0.0, "decode_s": 0.0,
                      "handoff_s": 0.0,
                      "last_admit_s": 0.0, "last_prefill_s": 0.0,
                      "last_decode_s": 0.0, "last_handoff_s": 0.0,
                      # host time actually BLOCKED on the decode sync —
                      # under double_buffer the overlap win shows up as
                      # this staying far below the dispatch wall time
                      "decode_sync_s": 0.0, "last_decode_sync_s": 0.0,
                      # disaggregation traffic (r15)
                      "handoffs_out": 0, "handoffs_in": 0,
                      "handoff_bytes": 0, "handoff_faults": 0,
                      "preemptions": 0, "recompute_tokens": 0,
                      "rejected": 0, "expired": 0, "cancelled": 0,
                      "step_faults": 0,
                      # speculative decoding (r13): drafted = proposals
                      # scored by verify, accepted + rejected = drafted;
                      # the bonus/correction token is NOT counted (it is
                      # ordinary decode output, speculation or not)
                      "spec_drafted": 0, "spec_accepted": 0,
                      "spec_rejected": 0}
        # observability (r11/r16): all default OFF — the hot loop pays
        # nothing unless asked to measure itself
        self.metrics: Optional[MetricsRegistry] = None
        self._m = None
        self.tracer: Optional[TraceRecorder] = None
        self.flight: Optional[FlightRecorder] = None
        # replica-namespaced trace lanes: module defaults until
        # attach_tracer assigns a replica identity
        self._pid_eng = PID_ENGINE
        self._pid_req = PID_REQUESTS
        # handoff trace context: monotonic per-export sequence carried on
        # the wire record so cross-replica flow arrows get unique ids
        self._span_seq = 0
        # SLO layer (r16): per-tenant budgets from TenantConfig; the
        # tracker registers its series lazily in attach_metrics
        self._tenant_cfg = normalize_tenants(tenants)
        self._slo: Optional[SLOTracker] = None
        # engine-clock stamp of the last completed step — the /healthz
        # staleness probe (a wedged replica stops advancing this)
        self._last_step_at: Optional[float] = None
        # run(metrics_dir=) arms the crash dump: a real exception
        # escaping step() writes the flight buffer here before re-raising
        # (the Router renames the file per replica)
        self._crash_dump_dir: Optional[str] = None
        self._crash_dump_name = "flight_crash.json"
        # identity tests, not truthiness: an EMPTY registry is falsy
        # (len 0) but still a registry the caller wants fed
        if metrics is not None and metrics is not False:
            self.attach_metrics(
                metrics if isinstance(metrics, MetricsRegistry) else None)
        if trace is not None and trace is not False:
            self.attach_tracer(
                trace if isinstance(trace, TraceRecorder) else None)
        if flight is not None and flight is not False:
            self.attach_flight(
                flight if isinstance(flight, FlightRecorder) else None)
        self._decode_fn = self._build_decode()
        self._prefill_fn = self._build_prefill()
        self._cow_fn = self._build_cow()
        self._verify_fn = self._build_verify() if self.spec_k else None

    # -- device programs --------------------------------------------------

    def _attend(self, q, bufs, li, table, lengths):
        """Paged decode attention for layer ``li`` — kernel or jnp ref."""
        if self.kv_bits is not None:
            kw = dict(k_scales=bufs["ks"][li], v_scales=bufs["vs"][li])
        else:
            kw = {}
        fn = pa.paged_attention if self._use_kernel else pa.paged_attention_ref
        return fn(q, bufs["k"][li], bufs["v"][li], table, lengths,
                  window=self.window, **kw)

    def _attend_prefill(self, q, bufs, li, table_row, start):
        """Paged chunk attention for layer ``li`` — kernel or jnp ref."""
        if self.kv_bits is not None:
            kw = dict(k_scales=bufs["ks"][li], v_scales=bufs["vs"][li])
        else:
            kw = {}
        fn = (pp.paged_prefill if self._use_prefill_kernel
              else pp.paged_prefill_ref)
        return fn(q, bufs["k"][li], bufs["v"][li], table_row, start,
                  window=self.window, **kw)

    def _scatter_kv(self, bufs, li, rows, offs, k1, v1):
        """Write per-token K/V (rows of shape (N, Hkv, D)) into layer
        ``li`` of the page pool at (page ``rows[i]``, offset ``offs[i]``)
        — quantizing to int8 (or nibble-packed int4) pages + fp32
        per-token scales when serving quantized KV.  The ONE
        scatter/quantize sequence shared by the decode and chunk-prefill
        programs, so the exact-parity contract cannot fork between
        them."""
        if self.kv_bits is not None:
            from ..ops.quant_ops import (quantize_int4_per_token,
                                         quantize_per_token)

            qf = (quantize_int4_per_token if self.kv_bits == 4
                  else quantize_per_token)
            kq, ksc = qf(k1)
            vq, vsc = qf(v1)
            bufs["k"] = bufs["k"].at[li, rows, :, offs, :].set(kq)
            bufs["ks"] = bufs["ks"].at[li, rows, :, offs, :].set(ksc)
            bufs["v"] = bufs["v"].at[li, rows, :, offs, :].set(vq)
            bufs["vs"] = bufs["vs"].at[li, rows, :, offs, :].set(vsc)
        else:
            bufs["k"] = bufs["k"].at[li, rows, :, offs, :].set(k1)
            bufs["v"] = bufs["v"].at[li, rows, :, offs, :].set(v1)
        return bufs

    def _build_decode(self):
        n_heads, eps, ps = self.n_heads, self.eps, self.page_size
        maxp, k_steps = self.max_pages, self.decode_block
        n_kv = self.n_kv_heads

        def one_step(p, bufs, table, toks, lengths, active, key):
            s = toks.shape[0]
            x = (p["wte"][toks] + p["wpe"][lengths])[:, None, :]  # (S, 1, h)
            page_idx = jnp.minimum(lengths // ps, maxp - 1)
            # exhausted/inactive lanes park their writes on the null page
            rows = jnp.where(active, table[jnp.arange(s), page_idx], 0)
            offs = lengths % ps
            for li, bp in enumerate(p["blocks"]):
                q, kb, vb = _block_qkv(bp, x, n_heads, eps,
                                       n_kv_heads=n_kv)
                q1, k1, v1 = q[:, :, 0], kb[:, :, 0], vb[:, :, 0]  # (S, H, D)
                bufs = self._scatter_kv(bufs, li, rows, offs, k1, v1)
                out = self._attend(q1, bufs, li, table, lengths + 1)
                out = out.reshape(s, -1)[:, None, :].astype(x.dtype)
                x = _block_finish(bp, x, out, eps)
            logits = _lm_head(p, x[:, 0], eps)                    # (S, V)
            key, sub = jax.random.split(key)
            return bufs, self._sample(logits, sub).astype(jnp.int32)

        def decode(p, bufs, toks, lengths, table, remaining, key):
            self.stats["decode_traces"] += 1  # python side effect: per trace
            if k_steps == 1:
                active = remaining > 0
                bufs, nxt = one_step(p, bufs, table, toks, lengths,
                                     active, key)
                return bufs, nxt[None]                             # (1, S)

            def body(carry, i):
                bufs, toks, lengths, remaining, key = carry
                active = remaining > 0
                key, sub = jax.random.split(key)
                bufs, nxt = one_step(p, bufs, table, toks, lengths,
                                     active, sub)
                toks = jnp.where(active, nxt, toks)
                lengths = jnp.where(active, lengths + 1, lengths)
                remaining = jnp.maximum(remaining - 1, 0)
                return (bufs, toks, lengths, remaining, key), nxt

            (bufs, _, _, _, _), toks_all = jax.lax.scan(
                body, (bufs, toks, lengths, remaining, key),
                jnp.arange(k_steps))
            return bufs, toks_all                                  # (k, S)

        return jax.jit(decode, donate_argnums=(1,))

    def _attend_spec(self, q, bufs, li, table, lengths):
        """Multi-query verify attention for layer ``li`` — kernel or jnp
        ref.  ``lengths`` counts the positions valid BEFORE the verify
        block (the paged_attention_mq contract)."""
        if self.kv_bits is not None:
            kw = dict(k_scales=bufs["ks"][li], v_scales=bufs["vs"][li])
        else:
            kw = {}
        fn = (pa.paged_attention_mq if self._use_spec_kernel
              else pa.paged_attention_mq_ref)
        return fn(q, bufs["k"][li], bufs["v"][li], table, lengths,
                  window=self.window, **kw)

    def _build_verify(self):
        """The speculative verify program: ONE dispatch embeds each
        slot's ``[carry, draft_0 .. draft_{k-1}]`` block at positions
        ``len .. len+k``, scatters all rows' K/V into the slot's pages
        (same quantize/scatter as decode — rows past the slot's draft
        count and inactive lanes park on the null page), runs multi-query
        paged attention (each row sees history + earlier block rows,
        causally), projects every row and samples greedily.  The host
        applies the rejection rule to the returned (S, k+1) predictions.

        Rejected rows leave stale K/V at positions past the accepted
        prefix; that is safe by construction: the next step's scatter
        REWRITES positions ``len' .. len'+k'`` before attending, and no
        query row ever attends past its own position — the same masking
        argument that makes null-page garbage harmless."""
        n_heads, eps, ps = self.n_heads, self.eps, self.page_size
        maxp, t = self.max_pages, self.spec_k + 1
        n_kv = self.n_kv_heads

        def verify(p, bufs, toks, draft, n_draft, lengths, table, key):
            self.stats["decode_traces"] += 1  # python side effect: per trace
            s = toks.shape[0]
            block = jnp.concatenate([toks[:, None], draft], axis=1)  # (S, T)
            pos = lengths[:, None] + jnp.arange(t, dtype=jnp.int32)[None, :]
            # pad rows of short drafts can index positions past the table;
            # clamp for the position embedding (their outputs are unused)
            x = p["wte"][block] + p["wpe"][
                jnp.minimum(pos, p["wpe"].shape[0] - 1)]         # (S, T, h)
            # rows beyond the slot's draft count — and every row of a
            # lane not decoding this step (n_draft == -1) — write to the
            # null page, exactly like inactive decode lanes
            row_ok = jnp.arange(t, dtype=jnp.int32)[None, :] <= \
                n_draft[:, None]
            page_idx = jnp.minimum(pos // ps, maxp - 1)
            rows = jnp.where(
                row_ok, jnp.take_along_axis(table, page_idx, axis=1), 0)
            offs = pos % ps
            for li, bp in enumerate(p["blocks"]):
                q, kb, vb = _block_qkv(bp, x, n_heads, eps,
                                       n_kv_heads=n_kv)     # q (S,H,T,D)
                k1 = jnp.swapaxes(kb, 1, 2)                  # (S, T, Hkv, D)
                v1 = jnp.swapaxes(vb, 1, 2)
                bufs = self._scatter_kv(bufs, li, rows, offs, k1, v1)
                out = self._attend_spec(jnp.swapaxes(q, 1, 2), bufs, li,
                                        table, lengths)
                out = out.reshape(s, t, -1).astype(x.dtype)
                x = _block_finish(bp, x, out, eps)
            logits = _lm_head(p, x, eps)                     # (S, T, V)
            key, sub = jax.random.split(key)
            pred = self._sample(logits.reshape(s * t, -1), sub)
            return bufs, pred.reshape(s, t).astype(jnp.int32)

        return jax.jit(verify, donate_argnums=(1,))

    def _build_prefill(self):
        n_heads, eps, ps = self.n_heads, self.eps, self.page_size
        maxp = self.max_pages
        n_kv = self.n_kv_heads

        def prefill(p, bufs, toks, start, n_valid, table_row, sample_idx,
                    key):
            """One chunk of one prompt: rows [start, start+n_valid) of the
            sequence.  Writes the chunk's K/V into the slot's pages, then
            attends the chunk against every already-written position (the
            cached/previous pages AND itself) through the block table.
            ``sample_idx`` is the chunk row holding the LAST prompt token;
            its sample is used only by the chunk that completes the
            prompt."""
            self.stats["prefill_traces"] += 1
            c = toks.shape[0]
            pos = start + jnp.arange(c, dtype=jnp.int32)
            x = (p["wte"][toks] + p["wpe"][pos])[None]        # (1, C, h)
            # padded rows scatter into the null page (page 0)
            valid = jnp.arange(c) < n_valid
            page_idx = jnp.minimum(pos // ps, maxp - 1)
            rows = jnp.where(valid, table_row[page_idx], 0)
            offs = pos % ps
            for li, bp in enumerate(p["blocks"]):
                q, kb, vb = _block_qkv(bp, x, n_heads, eps,
                                       n_kv_heads=n_kv)
                # (1, H, C, D) -> (C, H, D): the page-scatter layout
                q1 = jnp.swapaxes(q[0], 0, 1)
                k1 = jnp.swapaxes(kb[0], 0, 1)
                v1 = jnp.swapaxes(vb[0], 0, 1)
                bufs = self._scatter_kv(bufs, li, rows, offs, k1, v1)
                out = self._attend_prefill(q1, bufs, li, table_row, start)
                out = out.reshape(c, -1)[None].astype(x.dtype)
                x = _block_finish(bp, x, out, eps)
            # only the sample row's logits are ever consumed (and only by
            # the chunk completing the prompt): project ONE row, not the
            # whole (C, V) chunk — LN + matmul are row-wise, so the
            # sampled logits are bit-identical to the full projection
            h_row = jnp.take(x[0], sample_idx, axis=0)        # (h,)
            last = _lm_head(p, h_row[None, :], eps)           # (1, V)
            key, sub = jax.random.split(key)
            tok = self._sample(last, sub)[0]
            return bufs, tok.astype(jnp.int32)

        return jax.jit(prefill, donate_argnums=(1,))

    def _build_cow(self):
        def cow(bufs, src, dst):
            """Copy-on-write clone of one pool page across all layers —
            the partial-tail prefix match: the new owner will overwrite
            positions past the matched count and decode masks the rest."""
            return {k: b.at[:, dst].set(b[:, src]) for k, b in bufs.items()}

        return jax.jit(cow, donate_argnums=(0,))

    # -- public API -------------------------------------------------------

    def add_request(self, prompt, max_new_tokens: int,
                    arrival: float = 0.0,
                    deadline_s: Optional[float] = None,
                    tenant: Optional[str] = None) -> int:
        """Queue one request; returns its rid.  The prompt + continuation
        must fit ``max_seq_len`` (the slot's block-table width).
        ``deadline_s`` expires the request that many engine-clock seconds
        after enqueue, whatever state it is in.  ``tenant`` names the
        account the request schedules and bills under (WFQ policy;
        ignored by FCFS beyond metric labels)."""
        return self._enqueue(
            Request(prompt=np.asarray(prompt, np.int32).reshape(-1),
                    max_new_tokens=max_new_tokens, arrival=arrival,
                    deadline_s=deadline_s, tenant=tenant))

    def _enqueue(self, req: Request) -> int:
        """Single admission gate for both add_request and run(): every
        request must fit the model's position table / block-table width,
        whichever path it arrives by.  A full waiting queue REJECTS the
        request (backpressure): it still gets a rid and a terminal
        ``rejected`` FinishedRequest from the next step()."""
        if req.total_len > self.max_seq_len:
            raise ValueError(
                f"request needs {req.total_len} positions; engine "
                f"max_seq_len is {self.max_seq_len}")
        req.t_enqueue = self._now()
        if self.metrics is not None:
            self._m["enqueued"].inc()
        if ((self.max_queue is not None
             and self.scheduler.n_waiting >= self.max_queue)
                or self.scheduler.quota_reject(req.tenant)):
            # global queue bound OR the tenant's own max_waiting quota:
            # both are backpressure, both become an explicit terminal
            if self.tracer is not None:
                self.tracer.begin("queued", self._pid_req, req.rid)
            self.stats["rejected"] += 1
            self._pending.append(self._terminal(req, "rejected"))
            return req.rid
        rid = self.scheduler.add(req)
        if self.tracer is not None:
            self.tracer.begin("queued", self._pid_req, req.rid,
                              {"prompt_len": req.prompt_len,
                               "max_new": req.max_new_tokens})
        return rid

    def cancel(self, rid: int) -> bool:
        """Cancel a request in ANY live state — waiting, mid-prefill or
        decoding.  Pages are released immediately (same step); the
        terminal ``cancelled`` FinishedRequest (with any tokens generated
        so far) is delivered by the next step().  Returns False when the
        rid is unknown or already terminal."""
        req = self.scheduler.remove_waiting(rid)
        if req is not None:
            self.stats["cancelled"] += 1
            self._pending.append(self._terminal(req, "cancelled"))
            return True
        for idx, st in enumerate(self._slots):
            if st is not None and st.request.rid == rid:
                self.stats["cancelled"] += 1
                self._pending.append(self._finish(idx, "cancelled"))
                return True
        for i, rec in enumerate(self._handoff_in):
            if rec["request"].rid == rid:
                # queued for handoff admission: no slot, no pages — drop
                # the record, terminalize with whatever was generated
                del self._handoff_in[i]
                self.stats["cancelled"] += 1
                self._pending.append(
                    self._terminal(rec["request"], "cancelled"))
                return True
        return False

    @property
    def has_work(self) -> bool:
        """Work THIS engine can advance by stepping: queue/slots,
        undelivered terminals, queued handoff ingests, or an un-retired
        double-buffered dispatch.  The handoff OUTBOX is deliberately
        excluded — draining it is the router's job, not a step's."""
        return (self.scheduler.has_work or bool(self._pending)
                or bool(self._handoff_in) or self._inflight is not None)

    def prefix_hit_rate(self) -> float:
        """Fraction of prompt tokens served from cached KV pages."""
        return self.stats["prefix_hit_tokens"] / max(
            self.stats["prompt_tokens"], 1)

    # -- router probes (r15) ----------------------------------------------

    def prefix_match_len(self, prompt) -> int:
        """Tokens of ``prompt`` this replica's prefix index already holds
        K/V for — the router's cache-affinity key.  Probes the WORK
        prompt (``prompt[:-1]``, matching the scheduler's admission-time
        lookup) and is strictly read-only: no LRU touch, no retain."""
        if self.pool.prefix is None:
            return 0
        work = np.asarray(prompt, np.int32).reshape(-1)[:-1]
        if work.size == 0:
            return 0
        return self.pool.prefix.probe_len(work)

    def load_score(self) -> float:
        """Scalar busyness for the router's tie-break: resident slots +
        queue depth (both per capacity) + pool pressure.  Lower is
        better; an idle replica scores ~0, a saturated one ~3."""
        cap = max(self.max_slots, 1)
        return (self.scheduler.n_active / cap
                + self.scheduler.n_waiting / cap
                + self.pool.utilization())

    def stats_snapshot(self) -> Dict[str, float]:
        """A COPY of the stats ledger at this instant.  ``engine.stats``
        is the live mutable dict — callers that stash it see it keep
        changing under them; read through this instead."""
        return dict(self.stats)

    # -- observability (r11) ----------------------------------------------

    def attach_metrics(self, registry: Optional[MetricsRegistry] = None
                       ) -> MetricsRegistry:
        """Start feeding ``registry`` (fresh one if None) every step.
        Benches attach AFTER their warmup run so compile time never
        pollutes the measured histograms.  The registry must belong to
        THIS engine alone: ``serving_*`` counters mirror this engine's
        stats ledger via set_total, so a second feeding engine would
        overwrite them, not add — aggregate replicas by summing their
        registries' ``scalars()`` instead."""
        self.metrics = registry if registry is not None else MetricsRegistry()
        self._tenant_metrics = {}   # (family, tenant[, reason]) -> metric
        c = self.metrics.counter
        g = self.metrics.gauge
        h = self.metrics.histogram
        self._m = {
            "enqueued": c("serving_requests_enqueued",
                          "requests that arrived (incl. later rejects)"),
            "terminal": {r: c(f"serving_requests_terminal_{r}",
                              f"requests that ended {r}")
                         for r in TERMINAL_REASONS},
            "steps": c("serving_steps", "engine host-loop iterations"),
            "tokens": c("serving_tokens_generated", "sampled tokens"),
            "prefill_calls": c("serving_prefill_calls",
                               "chunk-prefill dispatches"),
            "decode_calls": c("serving_decode_calls", "decode dispatches"),
            "preemptions": c("serving_preemptions",
                             "slots evicted for recompute"),
            "recompute": c("serving_recompute_tokens",
                           "work-prompt tokens re-prefilled"),
            "prefix_hit": c("serving_prefix_hit_tokens",
                            "prompt tokens served from cached pages"),
            "prompt_tokens": c("serving_prompt_tokens",
                               "admitted work-prompt tokens"),
            "cow": c("serving_cow_clones", "copy-on-write page clones"),
            "step_faults": c("serving_step_faults",
                             "injected mid-step exceptions absorbed"),
            "spec_drafted": c("serving_spec_drafted_tokens",
                              "draft tokens scored by verify dispatches"),
            "spec_accepted": c("serving_spec_accepted_tokens",
                               "draft tokens the verify pass accepted"),
            "spec_rejected": c("serving_spec_rejected_tokens",
                               "draft tokens the verify pass rejected"),
            "spec_accept_rate": h("serving_spec_acceptance_rate",
                                  "per-request accepted/drafted at "
                                  "terminal (requests that drafted)"),
            "alloc_calls": c("serving_alloc_calls",
                             "KVPool.alloc lease attempts"),
            "alloc_failures": c("serving_alloc_failures",
                                "KVPool.alloc calls that returned None"),
            "evictions": c("serving_prefix_evictions",
                           "cached pages LRU-evicted under pressure"),
            "pages_in_use": g("serving_pages_in_use",
                              "pages referenced by live requests"),
            "pages_free": g("serving_pages_free", "free-list pages"),
            "pages_reclaimable": g("serving_pages_reclaimable",
                                   "cached pages with no live reference"),
            "queue_depth": g("serving_queue_depth", "waiting requests"),
            "slots_active": g("serving_slots_active", "occupied slots"),
            "kv_bytes_per_token": g("serving_kv_bytes_per_token",
                                    "pool HBM bytes one token position "
                                    "costs across all layers"),
            "pages_per_slot_p50": g("serving_pages_per_slot_p50",
                                    "median live pages per occupied slot"),
            "hit_rate": g("serving_prefix_hit_rate",
                          "prefix_hit_tokens / prompt_tokens"),
            "budget_util": g("serving_token_budget_utilization",
                             "step tokens / token_budget"),
            "queue_wait": h("serving_queue_wait_s",
                            "enqueue -> first admission (engine clock)"),
            "ttft": h("serving_ttft_s",
                      "enqueue -> first token (engine clock)"),
            "tbt": h("serving_tbt_s",
                     "time between tokens per slot (engine clock)"),
            "e2e": h("serving_e2e_latency_s",
                     "enqueue -> terminal (engine clock)"),
            "step_s": h("serving_step_s", "full step wall time"),
            "admit_s": h("serving_step_admit_s",
                         "expire+admit phase wall time"),
            "prefill_s": h("serving_step_prefill_s",
                           "chunk-prefill phase wall time"),
            "decode_s": h("serving_step_decode_s",
                          "grow+decode phase wall time"),
            "chunk_s": h("serving_prefill_chunk_s",
                         "one chunk-prefill dispatch wall time"),
            "decode_call_s": h("serving_decode_call_s",
                               "one decode dispatch+sync wall time"),
            "handoffs_out": c("serving_handoffs_out",
                              "prefill-complete requests exported to the "
                              "router (prefill-role engines)"),
            "handoffs_in": c("serving_handoffs_in",
                             "handoff records accepted from the router"),
            "handoff_bytes": c("serving_handoff_bytes",
                               "KV payload bytes shipped out (degraded "
                               "transfers ship none)"),
            "handoff_faults": c("serving_handoff_faults",
                                "handoffs degraded by an injected "
                                "transfer fault (payload dropped)"),
            "handoff_inbox": g("serving_handoff_inbox",
                               "ingested records waiting for a slot"),
            "handoff_s": h("serving_step_handoff_s",
                           "handoff export phase wall time"),
            "decode_sync": h("serving_decode_sync_s",
                             "host time blocked on the decode device "
                             "sync (double buffering shrinks this)"),
        }
        # SLO layer (r16): only tenants that DECLARE budgets cost series
        if any(c.ttft_slo_s is not None or c.e2e_slo_s is not None
               for c in self._tenant_cfg.values()):
            self._slo = SLOTracker(self.metrics)
        return self.metrics

    def attach_tracer(self, tracer: Optional[TraceRecorder] = None,
                      replica: Optional[int] = None,
                      replica_name: Optional[str] = None) -> TraceRecorder:
        """Start recording the request lifecycle + engine phases as
        Chrome trace events (fresh recorder if None).  ``replica``
        namespaces this engine's lanes (pid block + label prefix) so N
        replicas' recorders merge into one cluster timeline without
        colliding (:func:`~paddle_tpu.serving.tracing.merge_traces`)."""
        self.tracer = tracer if tracer is not None else TraceRecorder()
        if replica is not None and self.tracer.replica is None:
            self.tracer.set_replica(replica, name=replica_name)
        self._pid_eng = self.tracer.pid(PID_ENGINE)
        self._pid_req = self.tracer.pid(PID_REQUESTS)
        role = "" if self.role == "both" else f" [{self.role}]"
        self.tracer.process_name(
            self._pid_eng,
            self.tracer.lane_label(f"serving engine{role} (step phases)"))
        self.tracer.process_name(
            self._pid_req,
            self.tracer.lane_label("requests (tid = rid)"))
        return self.tracer

    def attach_flight(self, recorder: Optional[FlightRecorder] = None,
                      capacity: int = 1024) -> FlightRecorder:
        """Start the flight recorder (fresh ring of ``capacity`` records
        if None) — every admission / preemption / handoff / alloc
        failure / recycle / fault / terminal lands in the ring, stamped
        on the ENGINE clock for chaos-replay determinism."""
        self.flight = (recorder if recorder is not None
                       else FlightRecorder(capacity, clock=self._clock))
        return self.flight

    def dump_debug(self) -> dict:
        """Debug snapshot for the /debug surface and crash dumps: step
        counter, invariant verdict (the audit RUNS here — a violated
        invariant reports, it doesn't raise), stats ledger, and the
        flight-recorder ring (None when not attached)."""
        try:
            self.check_invariants()
            verdict = "ok"
        except AssertionError as e:
            verdict = f"violated: {e}"
        return {"step": self._step_idx, "role": self.role,
                "invariants": verdict, "stats": self.stats_snapshot(),
                "flight": (self.flight.to_json()
                           if self.flight is not None else None)}

    def _tr_end(self, rid: int, args: Optional[dict] = None) -> None:
        """Close the request's open span, tolerating a tracer attached
        mid-lifecycle (no span open yet)."""
        if self.tracer.open_span(self._pid_req, rid) is not None:
            self.tracer.end(self._pid_req, rid, args)

    def _tenant_counter(self, family: str, help: str, tenant: str,
                        reason: Optional[str] = None):
        """Lazily-created per-tenant labeled counter (r12).  Tenants are
        an open set (requests name them), so these cannot be
        pre-registered in attach_metrics like the label-free families."""
        key = (family, tenant, reason)
        m = self._tenant_metrics.get(key)
        if m is None:
            labels = {"tenant": tenant}
            if reason is not None:
                labels["reason"] = reason
            m = self.metrics.counter(family, help, labels=labels)
            self._tenant_metrics[key] = m
        return m

    def _emit_token(self, req: Request, tok: int) -> None:
        """One sampled token just landed on ``req`` (the caller already
        appended it) — feed the streaming observer and the per-tenant
        token counter.  Called in delivery order, so an on_token stream
        is token-for-token the eventual FinishedRequest.tokens."""
        if self.on_token is not None:
            self.on_token(req.rid, tok)
        if self.metrics is not None and req.tenant is not None:
            self._tenant_counter("serving_tenant_tokens_generated",
                                 "sampled tokens per tenant",
                                 req.tenant).inc()

    def _charge_service(self, req: Request) -> None:
        """Bill the request's first-time-served token delta to its
        tenant's virtual counter (WFQ; no-op under FCFS).  Safe to call
        at every service point — the delta is 0 when nothing new was
        served (including the whole recompute of a preempted request)."""
        delta = req.uncharged_tokens()
        if delta > 0:
            self.scheduler.charge(req, delta)

    def _observe_terminal(self, req: Request, reason: str) -> None:
        """Single funnel for EVERY FinishedRequest creation: terminal
        counters here are exactly one inc per terminal, which is what
        lets the chaos suite assert registry == observed terminals.
        SLO verdicts (r16) ride the same funnel: every terminal is
        judged against its tenant's declared budgets exactly once —
        degraded terminals (reject/expire/cancel) count as misses, so
        attainment cannot be gamed by shedding load."""
        if self.metrics is not None:
            now = self._now()
            self._m["terminal"][reason].inc()
            self._m["e2e"].observe(now - req.t_enqueue)
            if req.spec_drafted > 0:
                self._m["spec_accept_rate"].observe(
                    req.spec_accepted / req.spec_drafted)
            if req.tenant is not None:
                self._tenant_counter("serving_tenant_requests_terminal",
                                     "per-tenant terminals by reason",
                                     req.tenant, reason).inc()
            if self._slo is not None and req.tenant is not None:
                cfg = self._tenant_cfg.get(req.tenant)
                if cfg is not None:
                    if cfg.ttft_slo_s is not None:
                        ok = (req.t_first_token is not None
                              and req.t_first_token - req.t_enqueue
                              <= cfg.ttft_slo_s)
                        self._slo.observe(req.tenant, "ttft", ok, now,
                                          cfg.slo_objective)
                    if cfg.e2e_slo_s is not None:
                        ok = (reason in ("eos", "length")
                              and now - req.t_enqueue <= cfg.e2e_slo_s)
                        self._slo.observe(req.tenant, "e2e", ok, now,
                                          cfg.slo_objective)
        if self.flight is not None:
            self.flight.record("terminal", self._step_idx, rid=req.rid,
                               reason=reason, tokens=len(req.generated),
                               tenant=req.tenant)
        if self.tracer is not None:
            self._tr_end(req.rid)
            self.tracer.instant(reason, self._pid_req, req.rid,
                                {"rid": req.rid,
                                 "tokens": len(req.generated)})

    def snapshot(self) -> dict:
        """Capture the whole engine state (queue, slots, pool, prefix
        index, host mirrors, RNG) as plain numpy/python — see
        serving/snapshot.py.  ``ServingEngine.restore(model, snap)``
        resumes token-for-token."""
        from .snapshot import snapshot_engine

        return snapshot_engine(self)

    @classmethod
    def restore(cls, model, snap: dict, **overrides) -> "ServingEngine":
        """Rebuild an engine around ``model`` (same weights as the
        snapshotted one) and resume from ``snap``."""
        from .snapshot import restore_engine

        return restore_engine(model, snap, **overrides)

    # -- internals --------------------------------------------------------

    def _now(self) -> float:
        return self._clock()

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _fault_point(self, phase: str) -> None:
        if self.faults is not None:
            self.faults.check_raise(phase)

    def _terminal(self, req: Request, reason: str) -> FinishedRequest:
        """Terminal record for a request that is NOT in a slot (waiting
        or rejected at enqueue) — generated tokens from any earlier
        residency ride along."""
        self._observe_terminal(req, reason)
        return FinishedRequest(
            rid=req.rid, prompt=req.prompt,
            tokens=np.asarray(req.generated, np.int32),
            finish_reason=reason, n_steps=0)

    def _finish(self, idx: int, reason: str) -> FinishedRequest:
        st = self._slots[idx]
        self._slots[idx] = None
        self._table[idx] = 0
        self._tok[idx] = 0
        self._len[idx] = 0
        self.scheduler.release(idx, st.pages, st.request)
        self._observe_terminal(st.request, reason)
        return FinishedRequest(
            rid=st.request.rid, prompt=st.request.prompt,
            tokens=np.asarray(st.tokens, np.int32), finish_reason=reason,
            n_steps=self._step_idx - st.born_step + 1)

    def _preempt(self, idx: int) -> None:
        """Evict slot ``idx`` to recompute later: pages freed (cached
        prompt pages park reclaimable in the prefix index — the cheap
        part of the recompute), generated tokens kept on the request,
        request requeued at the HEAD of the waiting queue (FCFS: it
        predates everything still waiting)."""
        st = self._slots[idx]
        self._slots[idx] = None
        self._table[idx] = 0
        self._tok[idx] = 0
        self._len[idx] = 0
        self.scheduler.release(idx, st.pages, st.request)
        st.request.n_preempted += 1
        self.scheduler.requeue(st.request)
        self.stats["preemptions"] += 1
        if self.flight is not None:
            self.flight.record("preempt", self._step_idx,
                               victim=st.request.rid, slot=idx,
                               reason="page_pressure",
                               generated=len(st.request.generated),
                               pages_freed=len(st.pages))
        if self.tracer is not None:
            rid = st.request.rid
            self._tr_end(rid)            # the "resident" span
            self.tracer.instant("preempt", self._pid_req, rid,
                                {"generated": len(st.request.generated)})
            self.tracer.begin("queued", self._pid_req, rid,
                              {"recompute": True})

    def _pick_victim(self) -> Optional[int]:
        """The youngest occupied slot (largest admission seq) — unless it
        is the ONLY one: the oldest request is never preempted, so the
        system always makes forward progress (no livelock)."""
        occ = [(self._slots[i].seq, i) for i in range(self.max_slots)
               if self._slots[i] is not None]
        if len(occ) <= 1:
            return None
        return max(occ)[1]

    def _expire(self, finished: List[FinishedRequest]) -> None:
        """Deadline enforcement, both sides: overdue WAITING requests are
        dropped at queue-pop time (before this step's admissions), and
        overdue SLOTS release their pages mid-flight."""
        now = self._now()
        for req in self.scheduler.pop_expired(now):
            self.stats["expired"] += 1
            finished.append(self._terminal(req, "expired"))
        for idx, st in enumerate(self._slots):
            if st is not None and st.request.expired(now):
                self.stats["expired"] += 1
                finished.append(self._finish(idx, "expired"))
        if self._handoff_in:
            keep = []
            for rec in self._handoff_in:
                if rec["request"].expired(now):
                    self.stats["expired"] += 1
                    finished.append(
                        self._terminal(rec["request"], "expired"))
                else:
                    keep.append(rec)
            self._handoff_in = keep

    def _admit(self, adm) -> None:
        """Apply one scheduling decision: build the slot's block table
        from shared-prefix + owned pages, clone the COW tail page, record
        how much of the prompt needs no recompute."""
        req, idx = adm.request, adm.slot
        pages = list(adm.cached) + list(adm.pages)
        if adm.cow is not None:
            src, _ = adm.cow
            # the first owned page inherits the partial tail's K/V; the
            # source page drops the reference the scheduler pinned for us
            self.pool.buffers = self._cow_fn(
                self.pool.buffers, jnp.int32(src), jnp.int32(adm.pages[0]))
            self.pool.release([src])
        if req.seq is None:
            # first admission fixes the request's age; preemption keeps it
            self._admit_seq += 1
            req.seq = self._admit_seq
        st = _Slot(req, pages, prefilled=adm.matched, seq=req.seq,
                   base_len=req.work_len)
        st.born_step = self._step_idx
        self._slots[idx] = st
        row = np.zeros((self.max_pages,), np.int32)
        row[:len(pages)] = pages
        self._table[idx] = row
        self.stats["prefix_hit_tokens"] += adm.matched
        self.stats["prompt_tokens"] += req.work_len
        if req.n_preempted > 0:
            # the uncached remainder of the work prompt is recomputation
            self.stats["recompute_tokens"] += req.work_len - adm.matched
        now = self._now()
        if self.metrics is not None:
            if req.t_admitted is None:        # first admission only: a
                # re-admission after preemption is not queue wait
                self._m["queue_wait"].observe(now - req.t_enqueue)
            if adm.cow is not None:
                self._m["cow"].inc()
        if req.t_admitted is None:
            req.t_admitted = now
        if self.flight is not None:
            self.flight.record("admit", self._step_idx, rid=req.rid,
                               slot=idx, matched=adm.matched,
                               recompute=req.n_preempted > 0,
                               tenant=req.tenant)
        if self.tracer is not None:
            self._tr_end(req.rid)             # the "queued" span
            if adm.cow is not None:
                self.tracer.instant("cow_clone", self._pid_req, req.rid,
                                    {"matched_tokens": adm.cow[1]})
            self.tracer.begin("resident", self._pid_req, req.rid,
                              {"slot": idx, "matched": adm.matched,
                               "preempted": req.n_preempted})

    def _prefill_chunks(self, finished: List[FinishedRequest]) -> None:
        """Spend the step's chunk budget FCFS over partially-prefilled
        slots: at most ``prefill_budget`` prompt tokens total, each call
        one chunk of one slot's work prompt (prompt + any
        preemption-survived tokens).  A slot whose prompt completes
        samples its next token and joins this step's decode batch."""
        n_decoding = sum(1 for s in self._slots
                         if s is not None and s.started)
        budget = self.scheduler.prefill_budget(
            n_decoding, self.chunk_tokens, decode_cost=1 + self.spec_k)
        partial = sorted(
            (i for i, s in enumerate(self._slots)
             if s is not None and not s.started),
            key=lambda i: self._slots[i].seq)
        for idx in partial:
            st = self._slots[idx]
            req = st.request
            work = req.work_prompt()
            while budget > 0 and not st.started:
                n = min(st.base_len - st.prefilled, budget,
                        self.chunk_tokens)
                c_pad = min(_next_pow2(max(n, 8)),
                            max(self.chunk_tokens, n))
                toks = np.zeros((c_pad,), np.int32)
                toks[:n] = work[st.prefilled:st.prefilled + n]
                if self.tracer is not None:
                    self.tracer.begin("prefill_chunk", self._pid_req,
                                      req.rid, {"start": st.prefilled,
                                                "n": n})
                t_c = time.perf_counter()
                self.pool.buffers, tok = self._prefill_fn(
                    self.params, self.pool.buffers, jnp.asarray(toks),
                    jnp.int32(st.prefilled), jnp.int32(n),
                    jnp.asarray(self._table[idx]), jnp.int32(n - 1),
                    self._next_key())
                if self.metrics is not None:
                    self._m["chunk_s"].observe(time.perf_counter() - t_c)
                if self.tracer is not None:
                    self.tracer.end(self._pid_req, req.rid)
                self.stats["prefill_calls"] += 1
                st.prefilled += n
                budget -= n
                self._tokens_this_step += n
                # WFQ accounting: bill first-time prompt positions (a
                # recomputed chunk below the high-water mark bills 0)
                req.note_prefill_progress(st.prefilled)
                self._charge_service(req)
                if st.prefilled < st.base_len:
                    continue
                # prompt complete: next token sampled; its full pages
                # become matchable for every later request
                st.started = True
                if self.pool.prefix is not None:
                    if (self.window is not None
                            and st.base_len > self.window):
                        # the prompt extends past the window boundary:
                        # its leading pages are already invisible to every
                        # future query, and windowed recycling is about to
                        # free them — indexing would pin dead pages in the
                        # cache, so refuse cleanly and count it
                        self.pool.prefix.window_refusals += 1
                    else:
                        nfull = st.base_len // self.page_size
                        self.pool.prefix.insert(work, st.pages[:nfull])
                tok = int(tok)
                st.tokens.append(tok)
                self._emit_token(req, tok)
                self._charge_service(req)
                self.stats["tokens_generated"] += 1
                now = self._now()
                if req.t_first_token is None:
                    if self.metrics is not None:
                        self._m["ttft"].observe(now - req.t_enqueue)
                    if self.tracer is not None:
                        self.tracer.instant("first_token", self._pid_req,
                                            req.rid)
                    req.t_first_token = now
                elif self.metrics is not None and req.t_last_token is not None:
                    # a recomputed request's first post-readmission token:
                    # the gap since its last delivered token is real
                    # user-visible inter-token stall
                    self._m["tbt"].observe(now - req.t_last_token)
                req.t_last_token = now
                self._tok[idx] = tok
                self._len[idx] = st.base_len
                if (self.eos_token_id is not None
                        and tok == self.eos_token_id):
                    finished.append(self._finish(idx, "eos"))
                elif len(st.tokens) >= st.request.max_new_tokens:
                    finished.append(self._finish(idx, "length"))
            if budget <= 0:
                break

    def _grow_pages(self, idx: int, consumed: int) -> bool:
        """Ensure slot ``idx`` owns every page its next ``consumed``
        decode writes need (positions ``len .. len+consumed-1``) —
        on-demand growth, one admission no longer pays max_new_tokens
        upfront.  On allocation failure, preempt the youngest occupied
        slot and retry; never the oldest.  Returns True when the slot can
        decode this step (False: it was preempted itself, or stalled
        because no victim remains — retried next step)."""
        st = self._slots[idx]
        # grow from the HIGH-WATER page count, not len(pages): windowed
        # recycling shrinks the live page list but table positions keep
        # advancing — logical page i always lives at table column i
        need = self.pool.pages_for(int(self._len[idx]) + consumed) \
            - st.hw_pages
        while need > 0:
            got = self.pool.alloc(need)
            if got is not None:
                row = self._table[idx]
                row[st.hw_pages:st.hw_pages + len(got)] = got
                st.pages.extend(got)
                st.hw_pages += len(got)
                return True
            if self.flight is not None:
                self.flight.record(
                    "alloc_fail", self._step_idx, rid=st.request.rid,
                    need=need, free=self.pool.num_free,
                    reclaimable=self.pool.num_reclaimable)
            if self.pool.num_free + self.pool.num_reclaimable >= need:
                # the pool COULD satisfy the lease, so the failure is a
                # transient allocator fault (fault injection), not real
                # pressure — stall this step rather than evict residents
                # whose pages the retry won't even need
                return False
            victim = self._pick_victim()
            if victim is None:
                return False          # stalled; pool can't shrink further
            self._preempt(victim)
            if victim == idx:
                return False          # the grower was the youngest itself
        return True

    def _recycle_window_pages(self, idx: int) -> None:
        """Sliding-window page recycling: once every position of a slot's
        leading logical page has fallen out of the attention window — page
        j is dead iff ``(j+1)*page_size <= len+1-window``, i.e. the next
        query at position ``len`` cannot see any of it — the page goes
        back to the pool and its table entry becomes the null page (safe:
        the window mask already excludes those positions from every
        kernel and reference).  A slot's live footprint becomes a RING of
        ~ceil(window/page_size)+1 pages, so long generations stop
        growing.  Shared (prefix-cached) pages just drop this slot's
        reference; only STARTED slots recycle (prefill still writes the
        whole prompt)."""
        st = self._slots[idx]
        if st is None or self.window is None or not st.started:
            return
        dead = (int(self._len[idx]) + 1 - self.window) // self.page_size
        done = st.hw_pages - len(st.pages)    # leading pages already freed
        if dead <= done:
            return
        victims = st.pages[:dead - done]
        del st.pages[:dead - done]
        self._table[idx, done:dead] = 0
        self.pool.free(victims)
        if self.flight is not None:
            self.flight.record("window_recycle", self._step_idx,
                               rid=st.request.rid, pages=len(victims))

    # -- disaggregated prefill/decode handoff (r15) -----------------------

    def _release_slot(self, idx: int) -> _Slot:
        """Free slot ``idx`` WITHOUT a terminal — the handoff path: the
        request lives on (on another replica), so no FinishedRequest, no
        terminal counter; pages release normally (full prompt pages the
        prefix index adopted park reclaimable for later local hits)."""
        st = self._slots[idx]
        self._slots[idx] = None
        self._table[idx] = 0
        self._tok[idx] = 0
        self._len[idx] = 0
        self.scheduler.release(idx, st.pages, st.request)
        return st

    def _handoff_started(self) -> None:
        """Prefill-role drain: every STARTED slot (prompt complete, first
        token sampled) serializes into a handoff record and leaves the
        engine.  A scripted "handoff" fault degrades the WHOLE step's
        transfers — records ship without page payloads and the decode
        replica re-prefills them (chunked, prefix-cache-assisted), so a
        dropped fabric costs recompute, never correctness."""
        from .snapshot import handoff_state

        started = sorted((i for i, s in enumerate(self._slots)
                          if s is not None and s.started),
                         key=lambda i: self._slots[i].seq)
        if not started:
            return
        degraded = False
        if self.faults is not None:
            try:
                self.faults.check_raise("handoff")
            except InjectedFault:
                degraded = True
        for idx in started:
            st = self._slots[idx]
            h = handoff_state(self, idx, with_payload=not degraded)
            self.stats["handoffs_out"] += 1
            if degraded:
                self.stats["handoff_faults"] += 1
            else:
                self.stats["handoff_bytes"] += h["nbytes"]
            if self.flight is not None:
                self.flight.record("handoff_out", self._step_idx,
                                   rid=st.request.rid,
                                   nbytes=h["nbytes"],
                                   n_pages=h["n_pages"],
                                   degraded=degraded)
            if self.tracer is not None:
                rid = st.request.rid
                tr = h.get("trace")
                if tr is not None:
                    # INSIDE the resident span (before _tr_end closes
                    # it): the flow arrow leaves from the prefill slice
                    self.tracer.flow_start(
                        "handoff", self._pid_req, rid,
                        flow_id(tr["rid"], tr["seq"]))
                self._tr_end(rid)            # the "resident" span
                self.tracer.instant("handoff", self._pid_req, rid,
                                    {"n_pages": h["n_pages"],
                                     "nbytes": h["nbytes"],
                                     "degraded": degraded})
            self._release_slot(idx)
            self._handoff_out.append(h)

    def drain_handoffs(self) -> List[dict]:
        """Hand the outbox to the caller (the router's pump) — records
        are the caller's to deliver once returned."""
        out, self._handoff_out = self._handoff_out, []
        return out

    def ingest_handoff(self, h: dict) -> int:
        """Accept one prefill-replica handoff record.  Layout-guarded
        EAGERLY (a byte-incompatible payload must fail at the boundary,
        not at admission); timestamps rebase onto this engine's clock
        exactly like snapshot restore.  A payload-bearing record queues
        in the inbox until a slot + pages free up; a DEGRADED record
        (payload None) re-enters the waiting queue at the head — its
        work prompt re-prefills here, recompute-style.  Returns the
        rid."""
        from .snapshot import _request_from_state

        if self.role == "prefill":
            raise ValueError(
                "a prefill-role engine cannot ingest handoffs — route "
                "them to a decode/both replica")
        payload = h["payload"]
        if payload is not None:
            self.pool.check_layout(payload["layout"], what="handoff")
        req = _request_from_state(h["request"])
        delta = self._now() - float(h["clock_now"])
        req.t_enqueue += delta
        for attr in ("t_admitted", "t_first_token", "t_last_token"):
            v = getattr(req, attr)
            if v is not None:
                setattr(req, attr, v + delta)
        self.stats["handoffs_in"] += 1
        if self.flight is not None:
            self.flight.record("handoff_in", self._step_idx, rid=req.rid,
                               nbytes=int(h["nbytes"]),
                               n_pages=int(h["n_pages"]),
                               degraded=payload is None)
        if payload is None:
            # degraded transfer: the request was already accepted and
            # billed, so it bypasses backpressure and requeues at the
            # head — uncharged_tokens()'s monotone high-water mark means
            # the re-prefill bills the tenant nothing.  Accounting-wise
            # this IS a preemption (the work prompt gets recomputed), so
            # the re-admission lands in recompute_tokens like one.
            req.n_preempted += 1
            self.scheduler.requeue(req)
            if self.tracer is not None:
                self.tracer.begin("queued", self._pid_req, req.rid,
                                  {"recompute": True, "handoff": True})
        else:
            self._handoff_in.append(dict(
                request=req, base_len=int(h["base_len"]),
                n_pages=int(h["n_pages"]), payload=payload,
                nbytes=int(h["nbytes"])))
            if self.tracer is not None:
                self.tracer.begin("queued", self._pid_req, req.rid,
                                  {"handoff": True})
        if self.tracer is not None:
            tr = h.get("trace")
            if tr is not None:
                # inside the just-opened "queued" span (bp="e" binds the
                # arrow head to the enclosing slice): the flow lands on
                # the decode replica's lane
                self.tracer.flow_finish("handoff", self._pid_req,
                                        req.rid,
                                        flow_id(tr["rid"], tr["seq"]))
        return req.rid

    def _admit_handoffs(self, finished: List[FinishedRequest]) -> None:
        """Admit queued handoff records FIFO into free slots: lease
        pages, scatter the payload in (bit-exact adoption — no
        recompute), rebuild the slot mirrors as if local prefill had just
        completed, and index the full prompt pages for prefix reuse.
        Head-of-line blocking on slot/page shortage is intentional, same
        as the scheduler's admission loop (a transient alloc fault just
        retries next step — residents drain, so no livelock)."""
        while self._handoff_in:
            rec = self._handoff_in[0]
            if not self._try_admit_handoff(rec):
                break
            self._handoff_in.pop(0)

    def _try_admit_handoff(self, rec: dict) -> bool:
        if not self.scheduler._free_slots:
            return False
        pages = self.pool.alloc(rec["n_pages"])
        if pages is None:
            return False
        req = rec["request"]
        base_len = rec["base_len"]
        self.pool.ingest_pages(rec["payload"], pages)
        if req.seq is None:      # carried from the prefill replica's
            self._admit_seq += 1  # admission normally; None only if the
            req.seq = self._admit_seq   # sender predates admission seqs
        st = _Slot(req, pages, prefilled=base_len, seq=req.seq,
                   base_len=base_len)
        st.born_step = self._step_idx
        st.started = True
        slot = self.scheduler._free_slots.pop()
        self.scheduler.note_restored_slot(req)
        self._slots[slot] = st
        row = np.zeros((self.max_pages,), np.int32)
        row[:len(pages)] = pages
        self._table[slot] = row
        # mirrors exactly as local prefill completion leaves them: the
        # carry token is the last sampled one, the device length is the
        # work-prompt length whose K/V the pages hold
        self._tok[slot] = req.generated[-1]
        self._len[slot] = base_len
        # adopt the full prompt pages into THIS pool's prefix index —
        # same insert (and same windowed refusal) as local prefill; the
        # indexable tokens are the base_len positions the pages actually
        # hold, i.e. the work prompt minus the carry token
        if self.pool.prefix is not None:
            if self.window is not None and base_len > self.window:
                self.pool.prefix.window_refusals += 1
            else:
                work = req.work_prompt()[:base_len]
                nfull = base_len // self.page_size
                self.pool.prefix.insert(work, st.pages[:nfull])
        if self.flight is not None:
            self.flight.record("admit", self._step_idx, rid=req.rid,
                               slot=slot, handoff=True,
                               adopted_pages=len(pages),
                               tenant=req.tenant)
        if self.tracer is not None:
            self._tr_end(req.rid)            # the "queued" span
            self.tracer.begin("resident", self._pid_req, req.rid,
                              {"slot": slot, "handoff": True,
                               "adopted_pages": len(pages)})
        return True

    def step(self) -> List[FinishedRequest]:
        """One engine iteration: expire deadlines, admit into freed
        slots, advance partial prefills by the chunk budget, grow decode
        pages (preempting under pressure), then one decode step over
        every started slot.  Returns every request that reached a
        terminal state this step (including rejects/cancels recorded
        since the last step).  Injected faults abort the remainder of the
        iteration at a phase boundary; the next step resumes."""
        t0 = time.perf_counter()
        self._step_idx += 1
        if self.faults is not None:
            self.faults.begin_step(self._step_idx)
        finished: List[FinishedRequest] = list(self._pending)
        self._pending.clear()
        self._tokens_this_step = 0
        # phase -> (start perf-seconds, duration); filled by _run_step's
        # finally blocks, so a fault aborting a phase still records the
        # time it burned before aborting.  Carried on the instance (not a
        # parameter) so _run_step keeps its r10 signature.
        phase = self._phase_s = {}
        try:
            self._run_step(finished)
        except InjectedFault as e:
            self.stats["step_faults"] += 1
            if self.flight is not None:
                self.flight.record("injected_fault", self._step_idx,
                                   error=str(e))
        except BaseException as e:
            # a REAL fault escaping mid-step must not swallow terminals
            # already recorded this iteration (their pages are freed) —
            # re-park them so a retrying host loop still delivers every
            # request exactly one terminal state
            self._pending = finished + self._pending
            # black box first (r16): before the exception unwinds the
            # host loop, the flight ring lands next to the metrics
            # artifacts — the postmortem starts with the last N
            # decisions, not just a stack trace
            if self.flight is not None:
                self.flight.record("crash", self._step_idx,
                                   error=f"{type(e).__name__}: {e}")
                if self._crash_dump_dir is not None:
                    try:
                        self.flight.dump(os.path.join(
                            self._crash_dump_dir, self._crash_dump_name))
                    except OSError:
                        pass          # the dump must never mask the fault
            raise
        dt = time.perf_counter() - t0
        self._last_step_at = self._now()
        self.stats["pages_in_use"] = self.pool.pages_in_use
        self.stats["queue_depth"] = self.scheduler.n_waiting
        self.stats["step_wall_s"] += dt
        self.stats["last_step_s"] = dt
        for ph in ("admit", "prefill", "handoff", "decode"):
            start_dur = phase.get(ph)
            v = start_dur[1] if start_dur is not None else 0.0
            self.stats[f"{ph}_s"] += v
            self.stats[f"last_{ph}_s"] = v
        if self.tracer is not None:
            for ph, (start, dur) in phase.items():
                self.tracer.complete(ph, start, dur, self._pid_eng, 0,
                                     {"step": self._step_idx})
        if self.metrics is not None:
            self._sync_metrics(dt, phase)
        return finished

    def _sync_metrics(self, dt: float, phase: Dict[str, tuple]) -> None:
        """End-of-step registry feed: monotonic counters sync from the
        stats ledger (one source of truth — they cannot diverge), gauges
        sample the pool/scheduler, histograms take this step's wall
        times.  Terminal counters and request-time histograms are fed
        inline at their event sites instead."""
        m, s = self._m, self.stats
        m["steps"].inc()
        for stat_key, name in (("tokens_generated", "tokens"),
                               ("prefill_calls", "prefill_calls"),
                               ("decode_calls", "decode_calls"),
                               ("preemptions", "preemptions"),
                               ("recompute_tokens", "recompute"),
                               ("prefix_hit_tokens", "prefix_hit"),
                               ("prompt_tokens", "prompt_tokens"),
                               ("step_faults", "step_faults"),
                               ("spec_drafted", "spec_drafted"),
                               ("spec_accepted", "spec_accepted"),
                               ("spec_rejected", "spec_rejected"),
                               ("handoffs_out", "handoffs_out"),
                               ("handoffs_in", "handoffs_in"),
                               ("handoff_bytes", "handoff_bytes"),
                               ("handoff_faults", "handoff_faults")):
            m[name].set_total(s[stat_key])
        m["handoff_inbox"].set(len(self._handoff_in))
        m["alloc_calls"].set_total(self.pool.alloc_calls)
        m["alloc_failures"].set_total(self.pool.alloc_failures)
        if self.pool.prefix is not None:
            m["evictions"].set_total(self.pool.prefix.evictions)
        m["pages_in_use"].set(self.pool.pages_in_use)
        m["pages_free"].set(self.pool.num_free)
        m["pages_reclaimable"].set(self.pool.num_reclaimable)
        m["queue_depth"].set(self.scheduler.n_waiting)
        m["slots_active"].set(self.scheduler.n_active)
        m["kv_bytes_per_token"].set(self.pool.bytes_per_token())
        held = sorted(len(s.pages) for s in self._slots if s is not None)
        m["pages_per_slot_p50"].set(
            held[len(held) // 2] if held else 0)
        m["hit_rate"].set(self.prefix_hit_rate())
        m["budget_util"].set(self._tokens_this_step
                             / max(self.scheduler.token_budget, 1))
        m["step_s"].observe(dt)
        for ph in ("admit", "prefill", "handoff", "decode"):
            if ph in phase:
                m[f"{ph}_s"].observe(phase[ph][1])
        if self._slo is not None:
            # per step, not per terminal: burn-rate windows must page
            # OUT (and the gauges decay) even when nothing terminates
            self._slo.sync(self._now())

    def _run_step(self, finished: List[FinishedRequest]) -> None:
        phase = self._phase_s
        t_a = time.perf_counter()
        try:
            self._expire(finished)
            # handoff ingests admit FIRST: their prefill is already paid
            # for, so they take priority over raw admissions for the
            # slots/pages this step frees up
            self._admit_handoffs(finished)
            for adm in self.scheduler.schedule_step():
                self._admit(adm)
            self._fault_point("admit")
        finally:
            phase["admit"] = (t_a, time.perf_counter() - t_a)
        t_p = time.perf_counter()
        try:
            self._prefill_chunks(finished)
            self._fault_point("prefill")
        finally:
            phase["prefill"] = (t_p, time.perf_counter() - t_p)

        if self.role == "prefill":
            # prefill workers never decode: every slot that completed its
            # prompt this step exports (request, block-table order pages,
            # payload + scales) and frees its slot — the router delivers
            # the records to a decode replica
            t_h = time.perf_counter()
            try:
                self._handoff_started()
            finally:
                phase["handoff"] = (t_h, time.perf_counter() - t_h)
            return

        t_d = time.perf_counter()
        try:
            self._decode_step(finished)
            self._fault_point("decode")
        finally:
            phase["decode"] = (t_d, time.perf_counter() - t_d)

    def _decode_step(self, finished: List[FinishedRequest]) -> None:
        if self.spec_k:
            return self._spec_decode_step(finished)
        # retire LAST step's dispatched decode FIRST (double-buffer mode
        # leaves it un-synced so admit/prefill overlap the device): its
        # finishes free pages before this step's growth asks for them,
        # and growth can therefore never preempt an un-retired slot
        if self._inflight is not None:
            self._retire_decode(finished)
        # decode-page growth, oldest first so preemption victims are
        # always younger than the grower
        order = sorted((i for i, s in enumerate(self._slots)
                        if s is not None and s.started),
                       key=lambda i: self._slots[i].seq)
        run: List[int] = []
        for idx in order:
            if self._slots[idx] is None:      # preempted by an earlier grow
                continue
            st = self._slots[idx]
            consumed = min(self.decode_block, st.request.remaining_new)
            if self._grow_pages(idx, consumed):
                run.append(idx)
        if run:
            remaining = np.zeros((self.max_slots,), np.int32)
            for idx in run:
                remaining[idx] = self._slots[idx].request.remaining_new
            t_c = time.perf_counter()
            self.pool.buffers, toks_all = self._decode_fn(
                self.params, self.pool.buffers, jnp.asarray(self._tok),
                jnp.asarray(self._len), jnp.asarray(self._table),
                jnp.asarray(remaining), self._next_key())
            self.stats["decode_calls"] += 1
            # stash the DISPATCHED call without syncing; slot objects ride
            # along so retirement can detect cancel/expire/slot-reuse
            self._inflight = ([(idx, self._slots[idx]) for idx in run],
                              remaining, toks_all, t_c)
            if not self.double_buffer:
                self._retire_decode(finished)

    def _retire_decode(self, finished: List[FinishedRequest]) -> None:
        """Sync the stashed decode dispatch and apply its results: append
        tokens, bill tenants, finish eos/length, mirror carry state.  In
        double-buffer mode this runs one step LATE — the host scheduled
        step N+1's admissions and prefill while step N's program ran on
        device — so finishes surface a step later, which greedy outputs
        (schedule-invariant per request) don't observe."""
        entries, remaining, toks_all, t_c = self._inflight
        self._inflight = None
        t_s = time.perf_counter()
        toks_all = np.asarray(jax.block_until_ready(toks_all))
        sync_s = time.perf_counter() - t_s
        self.stats["decode_sync_s"] += sync_s
        self.stats["last_decode_sync_s"] = sync_s
        if self.metrics is not None:
            # block_until_ready closed the dispatch, so this is the real
            # device step time, not the async hand-off; sync_s is the
            # part the host actually WAITED — overlap makes it shrink
            self._m["decode_call_s"].observe(time.perf_counter() - t_c)
            self._m["decode_sync"].observe(sync_s)
        now = self._now()
        for idx, st_dispatched in entries:
            st = self._slots[idx]
            if st is not st_dispatched:
                # slot was cancelled/expired (or re-used by a fresh
                # admission) between dispatch and retirement — its
                # sampled tokens are dead, drop them on the floor
                continue
            consumed = int(min(self.decode_block, remaining[idx]))
            reason = None
            n_new = 0
            req = st.request
            for i in range(consumed):
                tok = int(toks_all[i, idx])
                st.tokens.append(tok)
                self._emit_token(req, tok)
                n_new += 1
                self.stats["tokens_generated"] += 1
                if (self.eos_token_id is not None
                        and tok == self.eos_token_id):
                    reason = "eos"
                    break
            self._tokens_this_step += n_new
            self._charge_service(req)
            if (self.metrics is not None and n_new
                    and req.t_last_token is not None):
                self._m["tbt"].observe((now - req.t_last_token) / n_new)
            req.t_last_token = now
            if reason is None and (len(st.tokens)
                                   >= st.request.max_new_tokens):
                reason = "length"
            if reason is not None:
                finished.append(self._finish(idx, reason))
            else:
                # mirror the DEVICE state: it advanced `consumed` steps
                # and its carry token is the last sampled one
                self._tok[idx] = int(toks_all[consumed - 1, idx])
                self._len[idx] += consumed
                self._recycle_window_pages(idx)

    def _spec_decode_step(self, finished: List[FinishedRequest]) -> None:
        """One speculative iteration over the started slots: draft from
        each request's history, grow pages for the whole verify block
        (carry + drafts — up to spec_k+1 positions, the same on-demand
        growth/preemption path as fused decode), one verify dispatch,
        then the greedy rejection rule advances each slot by
        ``accepted + 1`` tokens.  The draft is capped at
        ``remaining_new - 1`` so even full acceptance plus the bonus
        token lands exactly on ``max_new_tokens``."""
        k = self.spec_k
        order = sorted((i for i, s in enumerate(self._slots)
                        if s is not None and s.started),
                       key=lambda i: self._slots[i].seq)
        # -1 marks a lane not decoding this step (empty slot, mid-prefill,
        # stalled growth): the verify program masks all its rows
        n_draft = np.full((self.max_slots,), -1, np.int32)
        draft = np.zeros((self.max_slots, k), np.int32)
        run: List[int] = []
        for idx in order:
            if self._slots[idx] is None:      # preempted by an earlier grow
                continue
            st = self._slots[idx]
            cap = min(k, st.request.remaining_new - 1)
            if cap > 0:
                prop = np.asarray(
                    self._drafter.draft(st.request.work_prompt(), cap),
                    np.int32).reshape(-1)
                st.draft = [int(v) for v in prop[:cap]]
            else:
                st.draft = []
            if self._grow_pages(idx, len(st.draft) + 1):
                run.append(idx)
                n_draft[idx] = len(st.draft)
                if st.draft:
                    draft[idx, :len(st.draft)] = st.draft
        if not run:
            return
        # mid-verify fault point: drafts proposed + pages grown, dispatch
        # not yet issued — an injected fault here leaves the draft
        # buffers populated; the next step's proposal overwrites them
        # (check_invariants audits their bounds meanwhile)
        self._fault_point("verify")
        t_c = time.perf_counter()
        self.pool.buffers, pred = self._verify_fn(
            self.params, self.pool.buffers, jnp.asarray(self._tok),
            jnp.asarray(draft), jnp.asarray(n_draft),
            jnp.asarray(self._len), jnp.asarray(self._table),
            self._next_key())
        self.stats["decode_calls"] += 1
        pred = np.asarray(pred)                      # (max_slots, k+1)
        if self.metrics is not None:
            self._m["decode_call_s"].observe(time.perf_counter() - t_c)
        now = self._now()
        for idx in run:
            st = self._slots[idx]
            req = st.request
            nd = len(st.draft)
            n_acc, emitted = spec_accept_greedy(pred[idx], st.draft)
            st.draft = []
            self.stats["spec_drafted"] += nd
            self.stats["spec_accepted"] += n_acc
            self.stats["spec_rejected"] += nd - n_acc
            req.spec_drafted += nd
            req.spec_accepted += n_acc
            reason = None
            n_new = 0
            for tok in emitted:
                st.tokens.append(tok)
                self._emit_token(req, tok)
                n_new += 1
                self.stats["tokens_generated"] += 1
                if (self.eos_token_id is not None
                        and tok == self.eos_token_id):
                    reason = "eos"
                    break
            self._tokens_this_step += n_new
            self._charge_service(req)
            if (self.metrics is not None and n_new
                    and req.t_last_token is not None):
                self._m["tbt"].observe((now - req.t_last_token) / n_new)
            req.t_last_token = now
            if reason is None and len(st.tokens) >= req.max_new_tokens:
                reason = "length"
            if reason is not None:
                finished.append(self._finish(idx, reason))
            else:
                # mirror the DEVICE state: positions len .. len+n_new-1
                # now hold the accepted block rows' K/V (the carry token
                # and the accepted drafts — exactly the tokens sequential
                # decode would have written there); the new carry is the
                # bonus/correction token, whose K/V the next step writes
                self._tok[idx] = emitted[n_new - 1]
                self._len[idx] += n_new
                self._recycle_window_pages(idx)

    def check_invariants(self) -> None:
        """Page-leak / refcount / scheduler-consistency audit.  The pool's
        internal bookkeeping must balance, the refcount total must equal
        the page references live slots actually hold (so anything waiting
        — including preempted requests — provably holds ZERO pages), no
        rid may be waiting and resident at once, and slot occupancy must
        agree with the scheduler's free-slot list.  The serving tests'
        conftest fixture calls this after every step and cancel."""
        self.pool.check()
        refs = sum(len(s.pages) for s in self._slots if s is not None)
        held = sum(self.pool.refcount)
        if held != refs:
            raise AssertionError(
                f"refcount sum {held} != {refs} page references held by "
                "live slots — a page reference leaked or double-freed")
        waiting_rids = [r.rid for r in self.scheduler.waiting]
        if len(waiting_rids) != len(set(waiting_rids)):
            raise AssertionError("duplicate rid in the waiting queue")
        slot_rids = {s.request.rid for s in self._slots if s is not None}
        both = set(waiting_rids) & slot_rids
        if both:
            raise AssertionError(
                f"rid(s) {sorted(both)} simultaneously waiting and "
                "resident in a slot")
        # handoff inbox (r15): ingested-but-unadmitted records hold NO
        # pool pages here (their payload is host memory until admission),
        # and their rids must collide with neither queue nor slots
        inbox_rids = [rec["request"].rid for rec in self._handoff_in]
        if len(inbox_rids) != len(set(inbox_rids)):
            raise AssertionError("duplicate rid in the handoff inbox")
        clash = set(inbox_rids) & (set(waiting_rids) | slot_rids)
        if clash:
            raise AssertionError(
                f"rid(s) {sorted(clash)} in the handoff inbox AND "
                "waiting/resident")
        free = set(self.scheduler._free_slots)
        for i, s in enumerate(self._slots):
            if (i in free) == (s is not None):
                raise AssertionError(
                    f"slot {i} occupancy disagrees with the scheduler's "
                    "free-slot list")
        # windowed page arithmetic (KV-capacity PR): recycling must keep
        # every started slot's live footprint a bounded ring — high-water
        # never below the live count, and the live count within one
        # step's growth of ceil(window/page_size)+1 pages.  Without a
        # window the high-water mark and the live list must agree exactly.
        cmax = max(self.decode_block, self.spec_k + 1)
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            if s.hw_pages < len(s.pages):
                raise AssertionError(
                    f"slot {i} high-water {s.hw_pages} below live page "
                    f"count {len(s.pages)}")
            if self.window is None:
                if s.hw_pages != len(s.pages):
                    raise AssertionError(
                        f"slot {i} recycled pages without a window "
                        f"(hw {s.hw_pages}, live {len(s.pages)})")
            elif s.started:
                length = int(self._len[i])
                cap = self.pool.pages_for(
                    min(length + cmax, self.window + cmax)) + 1
                if len(s.pages) > cap:
                    raise AssertionError(
                        f"slot {i} holds {len(s.pages)} pages at len "
                        f"{length} under window {self.window}; ring cap "
                        f"is {cap}")
        # speculative draft buffers (r13): a slot's draft must stay
        # within the engine's spec window and the request's remaining
        # budget, and only DECODING slots may hold one — whatever step
        # fault landed between drafting and verify
        for i, s in enumerate(self._slots):
            if s is None or not s.draft:
                continue
            if len(s.draft) > self.spec_k:
                raise AssertionError(
                    f"slot {i} holds {len(s.draft)} draft tokens; "
                    f"spec_k is {self.spec_k}")
            if not s.started:
                raise AssertionError(
                    f"slot {i} holds draft tokens but is still prefilling")
            if len(s.draft) >= s.request.remaining_new:
                raise AssertionError(
                    f"slot {i} draft of {len(s.draft)} could overshoot "
                    f"the remaining budget {s.request.remaining_new}")
        # policy-side accounting (r12): per-tenant residency counts must
        # match the slots, virtual counters must stay finite/non-negative
        self.scheduler.policy.check(
            [s.request for s in self._slots if s is not None])

    def run(self, requests: Optional[Sequence] = None,
            metrics_dir: Optional[str] = None, flush_every: int = 1
            ) -> Dict[int, FinishedRequest]:
        """Drive the host loop to completion over queued (+ given)
        requests; returns {rid: FinishedRequest} — degraded terminals
        (rejected/expired/cancelled) included.

        ``metrics_dir`` turns the drain into an observed run: every
        ``flush_every`` steps the registry's scalars flush to a
        TensorBoard event file under the dir (auto-attaching metrics —
        and a tracer when none is set — if needed), and at drain the dir
        additionally holds ``metrics.prom`` (Prometheus text exposition)
        and ``trace.json`` (Chrome trace events, open in Perfetto)."""
        from .metrics import MetricsFileExporter

        for r in requests or ():
            if isinstance(r, Request):
                self._enqueue(r)
            else:
                prompt, max_new = r
                self.add_request(prompt, max_new)
        exporter = None
        if metrics_dir is not None:
            if self.metrics is None:
                self.attach_metrics()
            if self.tracer is None:
                self.attach_tracer()
            if self.flight is None:
                self.attach_flight()
            os.makedirs(metrics_dir, exist_ok=True)
            # arm the crash dump: a real exception escaping step()
            # writes flight_crash.json here before re-raising
            self._crash_dump_dir = metrics_dir
            exporter = MetricsFileExporter(self.metrics, metrics_dir)
        done: Dict[int, FinishedRequest] = {}
        try:
            while self.has_work:
                for fin in self.step():
                    done[fin.rid] = fin
                if exporter is not None and \
                        self._step_idx % flush_every == 0:
                    exporter.flush(self._step_idx)
        finally:
            if exporter is not None:
                if exporter.last_step != self._step_idx:
                    # flush_every > 1: the tail steps (or a whole run
                    # shorter than the interval) still reach the file
                    exporter.flush(self._step_idx)
                exporter.close()
                if self.tracer is not None:
                    self.tracer.save(
                        os.path.join(metrics_dir, "trace.json"))
                if self.flight is not None:
                    self.flight.dump(
                        os.path.join(metrics_dir, "flight.json"))
        # teardown: with every request terminal the pool must be back at
        # the cached-prefix-only baseline — any page still referenced by
        # a live slot (there are none) is a leak
        if self.scheduler.n_active or self.pool.pages_in_use:
            raise AssertionError(
                f"page leak after drain: {self.scheduler.n_active} slots "
                f"active, {self.pool.pages_in_use} pages still referenced")
        return done
