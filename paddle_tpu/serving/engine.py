"""Continuous-batching serving engine over the paged KV pool.

The static-batch decoder (``models/generation.build_generate_fn``) jits
prefill + ``max_new_tokens`` decode steps as ONE program over a fixed
batch: finished sequences keep burning decode steps until the longest
request ends, and a new request cannot join until the whole batch
drains.  This engine instead runs serving as TWO reusable jitted
programs called from a host loop:

  * ``prefill``: one request's prompt through the model's existing dense
    prefill (``_decoder_setup``'s ``make_run`` — the SAME substrate the
    static decoder compiles, so the numerics cannot fork), its KV
    scattered into the slot's pool pages, first token sampled.  Prompt
    lengths are padded to power-of-two buckets so the program retraces
    per bucket, not per length.
  * ``decode``: ONE token for EVERY occupied slot — embedding,
    ``_block_qkv``, per-slot paged KV write at each slot's own position,
    paged attention through the block table (Pallas kernel on TPU, jnp
    reference elsewhere — kernels/paged_attention.py), ``_block_finish``,
    sampling.  Slot count is static; inactive lanes compute into the
    pool's null page and are ignored.

Every host-loop iteration the FCFS scheduler admits waiting requests
into freed slots (per-step token budget), runs at most a handful of
prefill calls plus exactly one decode call, and returns finished
requests — iteration-level scheduling (Orca) with block-table paging
(vLLM), composed with the int8 W8A8 + int8-KV serving path from the
dense decoder: the per-(layer, batch, head, position) scale layout
carries over to per-page scales unchanged.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..models.generation import (
    _block_finish,
    _block_qkv,
    _decoder_setup,
    _empty_cache,
    _ln,
    _make_sampler,
)
from ..kernels import paged_attention as pa
from .kv_pool import KVPool
from .scheduler import FCFSScheduler, Request


@dataclasses.dataclass
class FinishedRequest:
    """One completed generation: the continuation (prompt excluded)."""

    rid: int
    prompt: np.ndarray
    tokens: np.ndarray            # generated continuation, EOS included
    finish_reason: str            # "eos" | "length"
    n_steps: int                  # engine steps it was resident


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class _Slot:
    """Host-side state of one occupied engine slot."""

    def __init__(self, request: Request, pages: List[int]):
        self.request = request
        self.pages = pages
        self.tokens: List[int] = []
        self.born_step = 0


class ServingEngine:
    """Continuous-batching generation over a paged KV cache.

    ``max_slots`` bounds the decode batch (the step's static shape);
    ``page_size`` the pool granularity; ``num_pages`` the pool size
    (default: enough for every slot at ``max_seq_len``, +1 null page);
    ``token_budget`` the scheduler's per-step admission budget.  Sampling
    knobs mirror ``build_generate_fn``; ``int8`` serves W8A8 projections
    + int8 KV pages.  ``use_paged_kernel`` forces the Pallas kernel (or
    the jnp reference) instead of auto-dispatch — tests use it to pin the
    interpret-mode kernel path on CPU.
    """

    def __init__(self, model, *, max_slots: int = 8, page_size: int = 32,
                 max_seq_len: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 token_budget: Optional[int] = None,
                 greedy: bool = True, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 int8: Optional[bool] = None, seed: int = 0,
                 decode_block: int = 1,
                 use_paged_kernel: Optional[bool] = None):
        cfg = model.cfg
        self.cfg = cfg
        # decode_block > 1 fuses that many decode steps into ONE dispatched
        # lax.scan (multi-step scheduling): admission/finish granularity
        # coarsens to the block, but the host->device dispatch latency —
        # ~65ms through the TPU tunnel (bench._int8_microbench) — is paid
        # once per block instead of once per token.  1 = pure
        # admit-every-step continuous batching (the parity-test mode).
        self.decode_block = max(1, int(decode_block))
        self.params, self._make_run, self.int8 = _decoder_setup(
            model, int8=int8)
        self.n_heads = cfg.num_heads
        self.head_dim = cfg.hidden_size // cfg.num_heads
        self.eps = cfg.layer_norm_eps
        self.max_slots = max_slots
        self.page_size = page_size
        self.max_seq_len = max_seq_len or cfg.max_seq_len
        if self.max_seq_len > cfg.max_seq_len:
            raise ValueError("max_seq_len exceeds the model's position table")
        self.max_pages = -(-self.max_seq_len // page_size)
        self.eos_token_id = eos_token_id
        dtype = self.params["wte"].dtype
        n_pages = num_pages or (1 + max_slots * self.max_pages)
        self.pool = KVPool(cfg.num_layers, cfg.num_heads, self.head_dim,
                           n_pages, page_size, dtype=dtype, int8=self.int8)
        self.scheduler = FCFSScheduler(max_slots, self.pool,
                                       token_budget=token_budget)
        self._sample = _make_sampler(greedy, temperature, top_k, top_p)
        if use_paged_kernel is None:
            use_paged_kernel = pa.available() and pa.supported(
                cfg.num_heads, page_size, self.head_dim)
        self._use_kernel = bool(use_paged_kernel)

        # host mirrors of the decode step's device operands
        self._slots: List[Optional[_Slot]] = [None] * max_slots
        self._tok = np.zeros((max_slots,), np.int32)
        self._len = np.zeros((max_slots,), np.int32)
        self._table = np.zeros((max_slots, self.max_pages), np.int32)
        self._key = jax.random.PRNGKey(seed)
        self._step_idx = 0
        self.stats = {"prefill_calls": 0, "decode_calls": 0,
                      "prefill_traces": 0, "decode_traces": 0,
                      "tokens_generated": 0}
        self._decode_fn = self._build_decode()
        self._prefill_fn = self._build_prefill()

    # -- device programs --------------------------------------------------

    def _attend(self, q, bufs, li, table, lengths):
        """Paged attention for layer ``li`` — kernel or jnp reference."""
        if self.int8:
            kw = dict(k_scales=bufs["ks"][li], v_scales=bufs["vs"][li])
        else:
            kw = {}
        fn = pa.paged_attention if self._use_kernel else pa.paged_attention_ref
        return fn(q, bufs["k"][li], bufs["v"][li], table, lengths, **kw)

    def _build_decode(self):
        n_heads, eps, ps, int8 = (self.n_heads, self.eps, self.page_size,
                                  self.int8)
        maxp, k_steps = self.max_pages, self.decode_block

        def one_step(p, bufs, table, toks, lengths, active, key):
            from ..ops.quant_ops import quantize_per_token

            s = toks.shape[0]
            x = (p["wte"][toks] + p["wpe"][lengths])[:, None, :]  # (S, 1, h)
            page_idx = jnp.minimum(lengths // ps, maxp - 1)
            # exhausted/inactive lanes park their writes on the null page
            rows = jnp.where(active, table[jnp.arange(s), page_idx], 0)
            offs = lengths % ps
            for li, bp in enumerate(p["blocks"]):
                q, kb, vb = _block_qkv(bp, x, n_heads, eps)
                q1, k1, v1 = q[:, :, 0], kb[:, :, 0], vb[:, :, 0]  # (S, H, D)
                if int8:
                    kq, ksc = quantize_per_token(k1)
                    vq, vsc = quantize_per_token(v1)
                    bufs["k"] = bufs["k"].at[li, rows, :, offs, :].set(kq)
                    bufs["ks"] = bufs["ks"].at[li, rows, :, offs, :].set(ksc)
                    bufs["v"] = bufs["v"].at[li, rows, :, offs, :].set(vq)
                    bufs["vs"] = bufs["vs"].at[li, rows, :, offs, :].set(vsc)
                else:
                    bufs["k"] = bufs["k"].at[li, rows, :, offs, :].set(k1)
                    bufs["v"] = bufs["v"].at[li, rows, :, offs, :].set(v1)
                out = self._attend(q1, bufs, li, table, lengths + 1)
                out = out.reshape(s, -1)[:, None, :].astype(x.dtype)
                x = _block_finish(bp, x, out, eps)
            h = _ln(x[:, 0], p["lnf_g"], p["lnf_b"], eps)
            logits = (h @ p["wte"].T).astype(jnp.float32)          # (S, V)
            key, sub = jax.random.split(key)
            return bufs, self._sample(logits, sub).astype(jnp.int32)

        def decode(p, bufs, toks, lengths, table, remaining, key):
            self.stats["decode_traces"] += 1  # python side effect: per trace
            if k_steps == 1:
                active = remaining > 0
                bufs, nxt = one_step(p, bufs, table, toks, lengths,
                                     active, key)
                return bufs, nxt[None]                             # (1, S)

            def body(carry, i):
                bufs, toks, lengths, remaining, key = carry
                active = remaining > 0
                key, sub = jax.random.split(key)
                bufs, nxt = one_step(p, bufs, table, toks, lengths,
                                     active, sub)
                toks = jnp.where(active, nxt, toks)
                lengths = jnp.where(active, lengths + 1, lengths)
                remaining = jnp.maximum(remaining - 1, 0)
                return (bufs, toks, lengths, remaining, key), nxt

            (bufs, _, _, _, _), toks_all = jax.lax.scan(
                body, (bufs, toks, lengths, remaining, key),
                jnp.arange(k_steps))
            return bufs, toks_all                                  # (k, S)

        return jax.jit(decode, donate_argnums=(1,))

    def _build_prefill(self):
        cfg, ps, int8 = self.cfg, self.page_size, self.int8

        def prefill(p, bufs, tokens, length, table_row, key):
            self.stats["prefill_traces"] += 1
            run = self._make_run(p)
            t_pad = tokens.shape[1]
            kc, vc = _empty_cache(cfg, 1, t_pad, p["wte"].dtype, int8=int8)
            logits, kc, vc = run(tokens, 0, kc, vc)
            pos = jnp.arange(t_pad, dtype=jnp.int32)
            # padded positions scatter into the null page (page 0)
            pages = jnp.where(pos < length, table_row[pos // ps], 0)
            offs = pos % ps

            def scatter(buf, blk):
                # blk (L, 1, H, T_pad, D|1) -> advanced-index layout
                # (T_pad, L, H, D|1) for the (page, off) scatter
                val = jnp.einsum("lbhtd->tlhd", blk)
                return buf.at[:, pages, :, offs, :].set(val)

            if int8:
                bufs = dict(bufs, k=scatter(bufs["k"], kc[0]),
                            ks=scatter(bufs["ks"], kc[1]),
                            v=scatter(bufs["v"], vc[0]),
                            vs=scatter(bufs["vs"], vc[1]))
            else:
                bufs = dict(bufs, k=scatter(bufs["k"], kc),
                            v=scatter(bufs["v"], vc))
            last = jnp.take(logits[0], length - 1, axis=0)         # (V,)
            key, sub = jax.random.split(key)
            tok = self._sample(last[None, :], sub)[0]
            return bufs, tok.astype(jnp.int32)

        return jax.jit(prefill, donate_argnums=(1,))

    # -- public API -------------------------------------------------------

    def add_request(self, prompt, max_new_tokens: int,
                    arrival: float = 0.0) -> int:
        """Queue one request; returns its rid.  The prompt + continuation
        must fit ``max_seq_len`` (the slot's block-table width)."""
        return self._enqueue(
            Request(prompt=np.asarray(prompt, np.int32).reshape(-1),
                    max_new_tokens=max_new_tokens, arrival=arrival))

    def _enqueue(self, req: Request) -> int:
        """Single admission gate for both add_request and run(): every
        request must fit the model's position table / block-table width,
        whichever path it arrives by."""
        if req.total_len > self.max_seq_len:
            raise ValueError(
                f"request needs {req.total_len} positions; engine "
                f"max_seq_len is {self.max_seq_len}")
        return self.scheduler.add(req)

    @property
    def has_work(self) -> bool:
        return self.scheduler.has_work

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def _finish(self, idx: int, reason: str) -> FinishedRequest:
        st = self._slots[idx]
        self._slots[idx] = None
        self._table[idx] = 0
        self._tok[idx] = 0
        self._len[idx] = 0
        self.scheduler.release(idx, st.pages)
        return FinishedRequest(
            rid=st.request.rid, prompt=st.request.prompt,
            tokens=np.asarray(st.tokens, np.int32), finish_reason=reason,
            n_steps=self._step_idx - st.born_step + 1)

    def step(self) -> List[FinishedRequest]:
        """One engine iteration: admit into freed slots (prefill), then one
        decode step over every occupied slot.  Returns requests that
        finished this step (EOS or length)."""
        finished: List[FinishedRequest] = []
        self._step_idx += 1

        for adm in self.scheduler.schedule_step():
            req, idx = adm.request, adm.slot
            st = _Slot(req, adm.pages)
            st.born_step = self._step_idx
            self._slots[idx] = st
            row = np.zeros((self.max_pages,), np.int32)
            row[:len(adm.pages)] = adm.pages
            self._table[idx] = row
            t_pad = min(_next_pow2(max(req.prompt_len, 8)), self.max_seq_len)
            tokens = np.zeros((1, t_pad), np.int32)
            tokens[0, :req.prompt_len] = req.prompt
            self.pool.buffers, tok = self._prefill_fn(
                self.params, self.pool.buffers, jnp.asarray(tokens),
                jnp.int32(req.prompt_len), jnp.asarray(row),
                self._next_key())
            self.stats["prefill_calls"] += 1
            tok = int(tok)
            st.tokens.append(tok)
            self.stats["tokens_generated"] += 1
            self._tok[idx] = tok
            self._len[idx] = req.prompt_len
            if self.eos_token_id is not None and tok == self.eos_token_id:
                finished.append(self._finish(idx, "eos"))
            elif len(st.tokens) >= req.max_new_tokens:
                finished.append(self._finish(idx, "length"))

        active = [i for i, s in enumerate(self._slots) if s is not None]
        if active:
            remaining = np.zeros((self.max_slots,), np.int32)
            for idx in active:
                st = self._slots[idx]
                remaining[idx] = st.request.max_new_tokens - len(st.tokens)
            self.pool.buffers, toks_all = self._decode_fn(
                self.params, self.pool.buffers, jnp.asarray(self._tok),
                jnp.asarray(self._len), jnp.asarray(self._table),
                jnp.asarray(remaining), self._next_key())
            self.stats["decode_calls"] += 1
            toks_all = np.asarray(toks_all)                # (k, max_slots)
            for idx in active:
                st = self._slots[idx]
                consumed = int(min(self.decode_block, remaining[idx]))
                reason = None
                for i in range(consumed):
                    tok = int(toks_all[i, idx])
                    st.tokens.append(tok)
                    self.stats["tokens_generated"] += 1
                    if (self.eos_token_id is not None
                            and tok == self.eos_token_id):
                        reason = "eos"
                        break
                if reason is None and (len(st.tokens)
                                       >= st.request.max_new_tokens):
                    reason = "length"
                if reason is not None:
                    finished.append(self._finish(idx, reason))
                else:
                    # mirror the DEVICE state: it advanced `consumed` steps
                    # and its carry token is the last sampled one
                    self._tok[idx] = int(toks_all[consumed - 1, idx])
                    self._len[idx] += consumed
        return finished

    def run(self, requests: Optional[Sequence] = None
            ) -> Dict[int, FinishedRequest]:
        """Drive the host loop to completion over queued (+ given)
        requests; returns {rid: FinishedRequest}."""
        for r in requests or ():
            if isinstance(r, Request):
                self._enqueue(r)
            else:
                prompt, max_new = r
                self.add_request(prompt, max_new)
        done: Dict[int, FinishedRequest] = {}
        while self.has_work:
            for fin in self.step():
                done[fin.rid] = fin
        return done
