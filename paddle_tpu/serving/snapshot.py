"""Engine snapshot / restore (r10) — resume a killed host loop exactly.

The serving engine's device state is small and fully mirrored on the
host: the page-pool buffers, the block tables, the per-slot carry
token/length, and the RNG key.  That makes checkpointing the WHOLE
engine cheap and exact — ``snapshot_engine`` captures

  * the ctor config echo (slots, page size, sampling knobs, …),
  * the scheduler's waiting queue and free-slot list,
  * every occupied slot's metadata (request, pages, prefill progress),
  * the pool: refcounts, free list, page buffers (as numpy), and the
    full prefix-index radix tree,
  * the host mirrors (``_tok``/``_len``/``_table``), the RNG key, step
    and admission counters, stats, and any undelivered terminals,
  * the metrics registry (r11, when attached): counters, gauges and
    histogram buckets restore so the time-series stays monotonic across
    a restart (the tracer does NOT snapshot — a trace is an artifact of
    one process's timeline, like the FaultPlan),
  * the scheduler policy's tenant state (r12): WFQ virtual token
    counters and lazily-learned tenant configs reload, so a restarted
    engine keeps the same fairness ledger — a tenant cannot launder its
    served-token debt through a restart,

all as plain numpy/python (picklable, no live device references).
``restore_engine(model, snap)`` rebuilds an engine around ``model`` —
which must carry the SAME WEIGHTS as the snapshotted one (weights are
deliberately not captured; they belong to the model checkpoint) — and
resumes the host loop with token-for-token identical output
(tests/test_serving.py::test_engine_snapshot_restore_exact).

Heritage: the source Paddle fork ships training-side elasticity
(``incubate/auto_checkpoint.py``); this is the serving-side analogue.

Not captured: a ``FaultPlan`` (chaos schedules don't survive a restart)
and the deadline clock itself — a restored engine defaults to
``time.monotonic``.  The snapshot DOES record the engine clock's reading
at capture time, and restore rebases every request timestamp onto the
new clock (r11): relative intervals are preserved, so deadline-bearing
requests resume with their remaining budget and the latency histograms
never observe a cross-process monotonic base jump.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from .prefix_cache import PrefixIndex
from . import scheduler as _sched
from .scheduler import Request

#: v3 (r12): requests carry ``tenant`` + fair-queueing charge marks, the
#: scheduler section carries the policy's state (WFQ virtual counters
#: survive a restart).  v2 snapshots still load — the new fields default.
#: v4 (r13): requests carry speculative-decoding counters
#: (``spec_drafted`` / ``spec_accepted``).  Draft buffers themselves are
#: deliberately NOT captured — the drafter is deterministic over request
#: history, so a restored engine re-drafts and stays token-exact
#: (tests/test_speculative.py).  Older snapshots load with zero counters.
#: v5 (KV-capacity PR): the snapshot records the pool's KV page LAYOUT
#: (kv heads, page dtype, kv_bits, window, page geometry) and restore
#: refuses an engine whose rebuilt pool lays pages out differently — the
#: captured page bytes would be reinterpreted silently otherwise.  Slots
#: carry ``hw_pages`` (windowed-recycling high-water mark); older
#: snapshots default it to the live page count (exact: they predate
#: recycling, so the two never diverged).
#: r15 (disaggregation) rides on v5 with OPTIONAL keys: the config echo
#: carries ``role``/``double_buffer`` (older snapshots restore as a
#: monolithic synchronous engine), the engine section carries the
#: handoff inbox/outbox (absent = empty), and :func:`handoff_state`
#: reuses the v5 pool-serialization shapes as the prefill→decode WIRE
#: format — an in-flight double-buffered dispatch is retired before
#: capture, so a snapshot never holds a live device future.
SNAPSHOT_VERSION = 5
_READABLE_VERSIONS = (2, 3, 4, 5)


def _request_state(req: Request) -> dict:
    return dict(prompt=np.asarray(req.prompt, np.int32).copy(),
                max_new_tokens=int(req.max_new_tokens), rid=int(req.rid),
                arrival=float(req.arrival), deadline_s=req.deadline_s,
                tenant=req.tenant,
                t_enqueue=float(req.t_enqueue),
                generated=list(req.generated),
                n_preempted=int(req.n_preempted), seq=req.seq,
                t_admitted=req.t_admitted,
                t_first_token=req.t_first_token,
                t_last_token=req.t_last_token,
                vt_charged=int(req.vt_charged),
                max_prompt_prefilled=int(req.max_prompt_prefilled),
                spec_drafted=int(req.spec_drafted),
                spec_accepted=int(req.spec_accepted))


def _request_from_state(st: dict) -> Request:
    req = Request(prompt=st["prompt"], max_new_tokens=st["max_new_tokens"],
                  rid=st["rid"], arrival=st["arrival"],
                  deadline_s=st["deadline_s"], tenant=st.get("tenant"))
    req.t_enqueue = st["t_enqueue"]
    req.generated = list(st["generated"])
    req.n_preempted = st["n_preempted"]
    req.seq = st["seq"]
    req.t_admitted = st.get("t_admitted")
    req.t_first_token = st.get("t_first_token")
    req.t_last_token = st.get("t_last_token")
    req.vt_charged = int(st.get("vt_charged", 0))
    req.max_prompt_prefilled = int(st.get("max_prompt_prefilled", 0))
    req.spec_drafted = int(st.get("spec_drafted", 0))
    req.spec_accepted = int(st.get("spec_accepted", 0))
    return req


def _finished_state(fin) -> dict:
    return dict(rid=fin.rid, prompt=np.asarray(fin.prompt, np.int32).copy(),
                tokens=np.asarray(fin.tokens, np.int32).copy(),
                finish_reason=fin.finish_reason, n_steps=fin.n_steps)


def handoff_state(eng, idx: int, with_payload: bool = True) -> dict:
    """The disaggregated prefill→decode handoff record for slot ``idx``
    of a prefill-role engine (r15): the request's full lifecycle state
    (generated already includes the first sampled token — the decode
    replica's carry), the slot's page payload in block-table order via
    ``KVPool.export_pages`` (snapshot-v5 pool serialization; layout
    embedded, enforced on ingest), and the source engine clock so the
    receiver rebases timestamps exactly like a snapshot restore does.
    ``with_payload=False`` is the DEGRADED form (handoff-phase fault):
    the request ships without KV and re-prefills on the decode replica —
    greedy output is unchanged, only the recompute is paid again.

    The record also carries a TRACE CONTEXT (r16): the rid plus the
    exporting engine's monotonic span sequence.  The pair keys the
    Chrome-trace flow arrow (``tracing.flow_id``) that stitches the
    prefill span, the router pump and the decode ingest into one line
    on the merged cluster timeline."""
    st = eng._slots[idx]
    payload = eng.pool.export_pages(st.pages) if with_payload else None
    eng._span_seq += 1
    return {
        "version": SNAPSHOT_VERSION,
        "request": _request_state(st.request),
        "base_len": int(st.base_len),
        "n_pages": len(st.pages),
        "payload": payload,
        "nbytes": (eng.pool.payload_nbytes(payload)
                   if payload is not None else 0),
        "clock_now": float(eng._now()),
        "trace": {"rid": int(st.request.rid), "seq": int(eng._span_seq)},
    }


def snapshot_engine(eng) -> dict:
    """Capture ``eng`` (a :class:`~paddle_tpu.serving.engine.ServingEngine`)
    as a plain-python dict; see the module docstring for the contract."""
    # double-buffered dispatch (r15): an un-retired decode future is
    # device state a snapshot cannot carry — sync and process it first
    # (its finishes land in _pending, delivered by the restored engine)
    if getattr(eng, "_inflight", None) is not None:
        eng._retire_decode(eng._pending)
    slots = []
    for st in eng._slots:
        if st is None:
            slots.append(None)
        else:
            slots.append(dict(request=_request_state(st.request),
                              pages=list(st.pages),
                              prefilled=int(st.prefilled),
                              started=bool(st.started), seq=int(st.seq),
                              base_len=int(st.base_len),
                              born_step=int(st.born_step),
                              hw_pages=int(st.hw_pages)))
    pool = eng.pool
    return {
        "version": SNAPSHOT_VERSION,
        "config": dict(eng._config),
        "kv_layout": pool.layout(),
        "engine": dict(
            step_idx=int(eng._step_idx), admit_seq=int(eng._admit_seq),
            key=np.asarray(eng._key).copy(), tok=eng._tok.copy(),
            len=eng._len.copy(), table=eng._table.copy(),
            stats=dict(eng.stats),
            # the engine clock's reading AT SNAPSHOT: restore rebases
            # every request timestamp onto the new process's clock, so
            # deadline budgets and latency observations carry relative
            # intervals over — raw time.monotonic values are meaningless
            # across a process boundary (per-boot base)
            clock_now=float(eng._now()),
            # handoff trace-context sequence (r16): restored engines keep
            # minting unique flow ids instead of restarting at 0
            span_seq=int(eng._span_seq),
            pending=[_finished_state(f) for f in eng._pending],
            # r15 handoff queues: inbox records re-serialize their live
            # Request; outbox entries are already wire dicts (numpy
            # payloads) — both restore with clock rebasing
            handoff_in=[dict(request=_request_state(r["request"]),
                             base_len=int(r["base_len"]),
                             n_pages=int(r["n_pages"]),
                             payload=r["payload"],
                             nbytes=int(r["nbytes"]))
                        for r in eng._handoff_in],
            handoff_out=[dict(h) for h in eng._handoff_out]),
        "scheduler": dict(
            waiting=[_request_state(r) for r in eng.scheduler.waiting],
            free_slots=list(eng.scheduler._free_slots),
            policy=eng.scheduler.policy.to_state()),
        "pool": dict(
            refcount=list(pool.refcount), free=list(pool._free),
            alloc_calls=int(pool.alloc_calls),
            alloc_failures=int(pool.alloc_failures),
            buffers={k: np.asarray(v).copy()
                     for k, v in pool.buffers.items()},
            prefix=(pool.prefix.to_state()
                    if pool.prefix is not None else None)),
        "slots": slots,
        "rid_next": _sched._next_rid.n,
        # metrics ride along (r11): a restored engine's registry resumes
        # counting where the snapshot left off — counters stay monotonic
        # and histograms keep their observations across a restart
        "metrics": (eng.metrics.to_state()
                    if eng.metrics is not None else None),
    }


def restore_engine(model, snap: dict, **overrides):
    """Rebuild a ServingEngine around ``model`` from a
    :func:`snapshot_engine` capture.  ``overrides`` patch ctor knobs
    (e.g. ``clock=``); state-bearing knobs (slots, page size, pool size)
    must match the snapshot or the mirrors won't fit."""
    from .engine import FinishedRequest, ServingEngine, _Slot

    if snap.get("version") not in _READABLE_VERSIONS:
        raise ValueError(f"unknown snapshot version {snap.get('version')!r}")
    cfg = dict(snap["config"])
    cfg.update(overrides)
    eng = ServingEngine(model, **cfg)

    # v5: the captured page bytes are only meaningful under the layout
    # that wrote them — a rebuilt pool with different KV heads, page
    # dtype, quantization width or window would reinterpret them
    # silently, so refuse loudly instead (v<5 snapshots predate every
    # non-default layout and skip the check)
    want = snap.get("kv_layout")
    if want is not None:
        have = eng.pool.layout()
        if have != want:
            diff = {k: (want[k], have[k]) for k in want
                    if have.get(k) != want[k]}
            raise ValueError(
                "snapshot KV layout does not match the rebuilt engine's "
                f"pool — snapshot vs engine: {diff}; restore onto a model/"
                "config with the same kv layout (kv heads, page dtype, "
                "kv_bits, window, page geometry)")

    # rids must keep minting above anything the snapshot ever issued
    _sched._next_rid.n = max(_sched._next_rid.n, int(snap["rid_next"]))

    pool, ps = eng.pool, snap["pool"]
    pool.refcount = list(ps["refcount"])
    pool._free = list(ps["free"])
    pool._free_set = set(pool._free)
    pool.alloc_calls = int(ps.get("alloc_calls", 0))
    pool.alloc_failures = int(ps.get("alloc_failures", 0))
    pool.buffers = {k: jnp.asarray(v) for k, v in ps["buffers"].items()}
    if ps["prefix"] is not None:
        pool.prefix = PrefixIndex.from_state(ps["prefix"])

    eng.scheduler.load_waiting(
        [_request_from_state(r) for r in snap["scheduler"]["waiting"]])
    eng.scheduler._free_slots = list(snap["scheduler"]["free_slots"])
    # policy counters load AFTER the queue refill (load_waiting performs
    # no arrival-time lifts, so the snapshotted counters land verbatim);
    # v2 snapshots carry no policy section — fresh counters
    pol_state = snap["scheduler"].get("policy")
    if pol_state is not None:
        eng.scheduler.policy.load_state(pol_state)

    # rebase request timestamps from the snapshotted clock onto this
    # engine's clock: shifted values preserve every relative interval
    # (elapsed-before-snapshot + elapsed-after-restore), so deadlines
    # keep their remaining budget and the latency histograms never see
    # a cross-process monotonic base jump (possibly negative durations)
    delta = eng._now() - float(snap["engine"]["clock_now"])

    def _rebase(req: Request) -> None:
        req.t_enqueue += delta
        for attr in ("t_admitted", "t_first_token", "t_last_token"):
            v = getattr(req, attr)
            if v is not None:
                setattr(req, attr, v + delta)

    for req in eng.scheduler.waiting:
        _rebase(req)

    for idx, sstate in enumerate(snap["slots"]):
        if sstate is None:
            eng._slots[idx] = None
            continue
        req = _request_from_state(sstate["request"])
        st = _Slot(req, list(sstate["pages"]),
                   prefilled=sstate["prefilled"], seq=sstate["seq"],
                   base_len=sstate["base_len"])
        st.started = sstate["started"]
        st.born_step = sstate["born_step"]
        # pre-v5 snapshots predate windowed recycling: hw == live pages
        st.hw_pages = int(sstate.get("hw_pages", len(st.pages)))
        _rebase(req)
        eng._slots[idx] = st
        eng.scheduler.note_restored_slot(req)

    es = snap["engine"]
    eng._step_idx = es["step_idx"]
    eng._admit_seq = es["admit_seq"]
    eng._key = jnp.asarray(es["key"])
    eng._tok = np.asarray(es["tok"], np.int32).copy()
    eng._len = np.asarray(es["len"], np.int32).copy()
    eng._table = np.asarray(es["table"], np.int32).copy()
    eng.stats.update(es["stats"])
    eng._span_seq = int(es.get("span_seq", 0))
    eng._pending = [FinishedRequest(**f) for f in es["pending"]]
    # r15 handoff queues (absent in older snapshots = empty): inbox
    # requests rebase like waiting ones; outbox wire dicts rebase their
    # embedded request timestamps AND their source-clock reading, so a
    # later ingest on another replica computes the same relative delta
    eng._handoff_in = []
    for rec in es.get("handoff_in", ()):
        req = _request_from_state(rec["request"])
        _rebase(req)
        eng._handoff_in.append(dict(
            request=req, base_len=int(rec["base_len"]),
            n_pages=int(rec["n_pages"]), payload=rec["payload"],
            nbytes=int(rec["nbytes"])))
    eng._handoff_out = []
    for h in es.get("handoff_out", ()):
        h = dict(h)
        rq = dict(h["request"])
        rq["t_enqueue"] = float(rq["t_enqueue"]) + delta
        for key in ("t_admitted", "t_first_token", "t_last_token"):
            if rq.get(key) is not None:
                rq[key] = float(rq[key]) + delta
        h["request"] = rq
        h["clock_now"] = float(h["clock_now"]) + delta
        eng._handoff_out.append(h)
    if snap.get("metrics") is not None and "metrics" not in overrides:
        from .metrics import MetricsRegistry

        eng.attach_metrics(MetricsRegistry.from_state(snap["metrics"]))
    eng.check_invariants()
    return eng
