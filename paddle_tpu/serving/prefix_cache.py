"""Page-aligned prefix index for KV page reuse (RadixAttention, SGLang).

Requests to a real service overwhelmingly share prompt PREFIXES — the
system prompt, few-shot examples, the conversation so far — and a paged
KV cache makes sharing free at the kernel level: a page is just a row of
the pool, and two slots whose block tables point at the same row read the
same K/V.  What's missing is the host-side index that says "these tokens
are already in that page".

This module is that index: a radix tree over PAGE-SIZED token chunks.
Each node covers exactly ``page_size`` tokens and names the pool page
holding their K/V; a path from the root spells out a cached prefix.
Children are keyed by the raw chunk bytes (the dict's own hashing is the
token-chunk hash), with the chunk stored on the node so partial-tail
matches — the copy-on-write candidates — can be found by prefix
comparison.

Lifecycle contract with :class:`~paddle_tpu.serving.kv_pool.KVPool`:

  * the index holds NO refcount of its own — ``refcount[page]`` counts
    only live requests.  A cached page with refcount 0 is *reclaimable*:
    it stays out of the free list (its K/V remain valid for future
    matches) until :meth:`evict` hands it back under memory pressure —
    LRU eviction of refcount-0 leaves instead of eager free;
  * only IMMUTABLE pages may be inserted: full prompt pages a request
    will never write again.  The partially-filled tail page is never
    cached — a new request wanting it gets a copy-on-write clone.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class _Node:
    __slots__ = ("chunk", "page", "children", "parent", "tick")

    def __init__(self, chunk: Optional[np.ndarray], page: int,
                 parent: Optional["_Node"]):
        self.chunk = chunk            # the page_size tokens this node covers
        self.page = page              # pool page holding their K/V
        self.children: Dict[bytes, _Node] = {}
        self.parent = parent
        self.tick = 0                 # LRU clock (match/insert refresh it)


class PrefixIndex:
    """Radix tree mapping page-aligned token prefixes to pool pages."""

    def __init__(self, page_size: int):
        self.page_size = page_size
        self.root = _Node(None, -1, None)
        self._by_page: Dict[int, _Node] = {}
        self._tick = 0
        # lifetime eviction count (r11): cache-churn observable the
        # engine mirrors into its metrics registry — rising evictions at
        # a flat hit rate means the working set outgrew the pool
        self.evictions = 0
        # prompts NOT indexed because sliding-window attention would
        # recycle their pages past the window boundary — the clean-refusal
        # counter the engine increments instead of inserting
        self.window_refusals = 0

    def __len__(self) -> int:
        return len(self._by_page)

    def __contains__(self, page: int) -> bool:
        return page in self._by_page

    def _touch(self, node: _Node) -> None:
        """Refresh the LRU tick on ``node`` and its whole prefix chain (a
        parent can never be older than a just-used child)."""
        self._tick += 1
        while node is not None and node.page >= 0:
            node.tick = self._tick
            node = node.parent

    @staticmethod
    def _common_prefix(a: np.ndarray, b: np.ndarray) -> int:
        n = min(a.size, b.size)
        neq = a[:n] != b[:n]
        return int(np.argmax(neq)) if neq.any() else n

    # -- lookup -----------------------------------------------------------

    def match(self, tokens) -> Tuple[List[int], Optional[Tuple[int, int]]]:
        """Longest cached page-aligned prefix of ``tokens``.

        Returns ``(pages, partial)``: ``pages`` cover the first
        ``len(pages) * page_size`` tokens exactly (shareable as-is), and
        ``partial`` is an optional ``(page, m)`` whose first ``m`` (>= 1)
        positions hold K/V for the next ``m`` tokens — usable only via a
        copy-on-write clone, since the request must write later positions
        of that page.  Matched nodes' LRU ticks are refreshed; the caller
        must ``retain`` the returned pages before anything that can evict
        (they may sit at refcount 0).
        """
        toks = np.asarray(tokens, np.int32).reshape(-1)
        ps = self.page_size
        node, pages, i = self.root, [], 0
        while i + ps <= toks.size:
            child = node.children.get(toks[i:i + ps].tobytes())
            if child is None:
                break
            node = child
            pages.append(child.page)
            i += ps
        partial = None
        rest = toks[i:]
        if rest.size:
            best, best_m = None, 0
            for child in node.children.values():
                m = self._common_prefix(rest, child.chunk)
                if m > best_m:
                    best, best_m = child, m
            if best is not None:
                partial = (best.page, best_m)
                self._touch(best)
        if pages:
            self._touch(node)
        return pages, partial

    def probe_len(self, tokens) -> int:
        """Longest cached prefix of ``tokens`` in TOKENS, read-only: the
        same walk as :meth:`match` (full page-aligned chain plus the best
        partial tail) but touching neither the LRU ticks nor refcounts —
        this is how a replica exposes its prefix-index keys to the
        multi-replica router (r15), which probes EVERY replica per
        request and must not distort the caches it merely inspected."""
        toks = np.asarray(tokens, np.int32).reshape(-1)
        ps = self.page_size
        node, i = self.root, 0
        while i + ps <= toks.size:
            child = node.children.get(toks[i:i + ps].tobytes())
            if child is None:
                break
            node = child
            i += ps
        rest = toks[i:]
        best_m = 0
        if rest.size:
            for child in node.children.values():
                best_m = max(best_m, self._common_prefix(rest, child.chunk))
        return i + best_m

    # -- insertion --------------------------------------------------------

    def insert(self, tokens, pages: Sequence[int]) -> List[int]:
        """Record ``pages[i]`` as holding the K/V of ``tokens``' i-th full
        chunk (only ``len(tokens) // page_size`` full chunks insert — the
        tail stays uncached).  A chunk already present keeps its EXISTING
        page; the duplicate is NOT absorbed and stays owned by its
        request alone.  Returns the pages newly adopted by the index
        (reclaimable through :meth:`evict` once their refcount hits 0).
        """
        toks = np.asarray(tokens, np.int32).reshape(-1)
        ps = self.page_size
        node, new = self.root, []
        for j in range(toks.size // ps):
            chunk = toks[j * ps:(j + 1) * ps]
            key = chunk.tobytes()
            child = node.children.get(key)
            if child is None:
                page = int(pages[j])
                if page in self._by_page:
                    raise ValueError(f"page {page} already indexed")
                child = _Node(chunk.copy(), page, node)
                node.children[key] = child
                self._by_page[page] = child
                new.append(page)
            node = child
        if node is not self.root:
            self._touch(node)
        return new

    # -- snapshot (serving/snapshot.py) -----------------------------------

    def to_state(self) -> dict:
        """Plain-python capture of the whole tree: nodes in parent-first
        (DFS) order as ``(parent_page, page, chunk, tick)``, with the
        root named by page -1.  Everything numpy/int — picklable and
        device-free."""
        nodes = []

        def walk(node: _Node) -> None:
            for child in node.children.values():
                nodes.append((node.page, child.page,
                              np.asarray(child.chunk, np.int32).copy(),
                              child.tick))
                walk(child)

        walk(self.root)
        return {"page_size": self.page_size, "tick": self._tick,
                "nodes": nodes, "evictions": self.evictions,
                "window_refusals": self.window_refusals}

    @classmethod
    def from_state(cls, state: dict) -> "PrefixIndex":
        """Rebuild an index from :meth:`to_state`.  Parent-first node
        order means every parent exists before its children link in."""
        idx = cls(state["page_size"])
        by_page: Dict[int, _Node] = {-1: idx.root}
        for parent_page, page, chunk, tick in state["nodes"]:
            parent = by_page[int(parent_page)]
            node = _Node(np.asarray(chunk, np.int32), int(page), parent)
            node.tick = int(tick)
            parent.children[node.chunk.tobytes()] = node
            idx._by_page[node.page] = node
            by_page[node.page] = node
        idx._tick = int(state["tick"])
        idx.evictions = int(state.get("evictions", 0))
        idx.window_refusals = int(state.get("window_refusals", 0))
        return idx

    # -- eviction ---------------------------------------------------------

    def evict(self, n_pages: int, refcount: Sequence[int]) -> List[int]:
        """Reclaim up to ``n_pages`` cached pages, LRU-first, considering
        only LEAVES with ``refcount == 0`` (an interior node becomes
        evictable once its children go).  Returns the evicted pages —
        the pool pushes them back on its free list."""
        out: List[int] = []
        while len(out) < n_pages:
            # one sweep collects every currently-evictable leaf; evicting
            # down the sorted list may expose parents, so sweep again only
            # if the quota isn't met — O(n + k log n) typical instead of a
            # full scan per evicted page
            victims = sorted(
                (node for node in self._by_page.values()
                 if not node.children and refcount[node.page] == 0),
                key=lambda n: n.tick)
            if not victims:
                break
            for node in victims:
                if len(out) >= n_pages:
                    break
                del node.parent.children[node.chunk.tobytes()]
                del self._by_page[node.page]
                out.append(node.page)
        self.evictions += len(out)
        return out
