"""Page-pool KV cache allocator (PagedAttention / vLLM, SOSP '23).

Instead of one dense (B, H, S_max, D) cache per request — which reserves
``max_seq_len`` worth of HBM for every slot whether used or not — the KV
cache is a POOL of fixed-size pages shared by all slots; each sequence
owns just enough pages for its current length, recorded in a per-slot
block table.  Freed pages return to the pool the moment a request
finishes, which is what lets the continuous-batching engine admit a new
request into the slot without draining the batch.

Device layout (one array per side, all layers stacked so the decode jit
threads ONE buffer pair):

  * float pages: ``(L, P, H, page_size, D)`` in the model dtype;
  * int8 pages: the same shape in int8 + an fp32 scale pool
    ``(L, P, H, page_size, 1)`` — one scale per (layer, page-position,
    head), the IDENTICAL per-token quantization layout the dense int8 KV
    cache uses (models/generation.py), so the quantization decisions
    carry over to pages unchanged.

Page 0 is RESERVED as the null page: the allocator never hands it out,
block-table padding points at it, and masked/inactive lanes write their
garbage there — so no gather in the paged-attention kernel can ever
index out of the pool, and no active page can be corrupted by an
inactive lane.  Allocation itself is a host-side free list (LIFO for
locality); the device arrays are threaded functionally through the
engine's jitted programs and donated back each step.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

import jax.numpy as jnp


class KVPool:
    """Fixed-size page pool + free-list allocator for the serving engine."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_pages: int, page_size: int, dtype=jnp.float32,
                 int8: bool = False):
        if num_pages < 2:
            raise ValueError("KVPool needs >= 2 pages (page 0 is the "
                             "reserved null page)")
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.head_dim = head_dim
        self.num_pages = num_pages
        self.page_size = page_size
        self.int8 = int8
        shape = (num_layers, num_pages, num_heads, page_size, head_dim)
        if int8:
            self.buffers: Dict[str, jnp.ndarray] = {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "ks": jnp.zeros(shape[:-1] + (1,), jnp.float32),
                "vs": jnp.zeros(shape[:-1] + (1,), jnp.float32),
            }
        else:
            self.buffers = {"k": jnp.zeros(shape, dtype),
                            "v": jnp.zeros(shape, dtype)}
        # LIFO free list over pages 1..P-1; page 0 stays the null page
        self._free: List[int] = list(range(num_pages - 1, 0, -1))

    # -- allocation -------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` positions."""
        return max(1, math.ceil(n_tokens / self.page_size))

    def alloc(self, n_pages: int) -> Optional[List[int]]:
        """Pop ``n_pages`` from the free list, or None when the pool can't
        satisfy the request (caller keeps the request queued — FCFS)."""
        if n_pages > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n_pages)]
        return got

    def free(self, pages: List[int]) -> None:
        """Return a finished request's pages.  Double-free and null-page
        free are programming errors worth failing loudly on."""
        for p in pages:
            if p <= 0 or p >= self.num_pages:
                raise ValueError(f"free of invalid page id {p}")
            if p in self._free:
                raise ValueError(f"double free of page {p}")
        self._free.extend(reversed(pages))

    # -- stats ------------------------------------------------------------

    def utilization(self) -> float:
        usable = self.num_pages - 1
        return 1.0 - len(self._free) / max(usable, 1)

    def hbm_bytes(self) -> int:
        return sum(b.size * b.dtype.itemsize for b in self.buffers.values())
