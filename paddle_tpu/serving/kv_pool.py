"""Page-pool KV cache allocator (PagedAttention / vLLM, SOSP '23).

Instead of one dense (B, H, S_max, D) cache per request — which reserves
``max_seq_len`` worth of HBM for every slot whether used or not — the KV
cache is a POOL of fixed-size pages shared by all slots; each sequence
owns just enough pages for its current length, recorded in a per-slot
block table.  Freed pages return to the pool the moment a request
finishes, which is what lets the continuous-batching engine admit a new
request into the slot without draining the batch.

Device layout (one array per side, all layers stacked so the decode jit
threads ONE buffer pair):

  * float pages: ``(L, P, H, page_size, D)`` in the model dtype;
  * int8 pages: the same shape in int8 + an fp32 scale pool
    ``(L, P, H, page_size, 1)`` — one scale per (layer, page-position,
    head), the IDENTICAL per-token quantization layout the dense int8 KV
    cache uses (models/generation.py), so the quantization decisions
    carry over to pages unchanged.

Page 0 is RESERVED as the null page: the allocator never hands it out,
block-table padding points at it, and masked/inactive lanes write their
garbage there — so no gather in the paged-attention kernel can ever
index out of the pool, and no active page can be corrupted by an
inactive lane.

Sharing (r09): every page carries a REFCOUNT of live requests holding it.
``alloc`` leases fresh pages at refcount 1; a request matching a cached
prefix ``retain``\\ s the shared pages (+1 each); ``free`` drops one
reference per page and only a page at refcount 0 actually leaves
circulation — back to the free list, unless the pool's
:class:`~paddle_tpu.serving.prefix_cache.PrefixIndex` still names it, in
which case it parks as *reclaimable* (its K/V stay matchable) until LRU
eviction hands it back under pressure.  The free list is mirrored by a
set so alloc/free/double-free checks are all O(1) per page.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from .prefix_cache import PrefixIndex


class KVPool:
    """Fixed-size page pool + refcounted free-list allocator."""

    def __init__(self, num_layers: int, num_heads: int, head_dim: int,
                 num_pages: int, page_size: int, dtype=jnp.float32,
                 int8: bool = False, prefix_cache: bool = False,
                 num_kv_heads: Optional[int] = None,
                 kv_bits: Optional[int] = None,
                 window: Optional[int] = None):
        if num_pages < 2:
            raise ValueError("KVPool needs >= 2 pages (page 0 is the "
                             "reserved null page)")
        if kv_bits is None and int8:
            kv_bits = 8
        if kv_bits not in (None, 4, 8):
            raise ValueError(f"kv_bits must be None, 4 or 8, got {kv_bits}")
        kv_heads = num_kv_heads or num_heads
        if num_heads % kv_heads != 0:
            raise ValueError(f"num_heads={num_heads} not divisible by "
                             f"num_kv_heads={kv_heads}")
        if kv_bits == 4 and head_dim % 2 != 0:
            raise ValueError("kv_bits=4 needs an even head_dim "
                             "(two nibbles per byte)")
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.num_kv_heads = kv_heads
        self.head_dim = head_dim
        self.num_pages = num_pages
        self.page_size = page_size
        self.kv_bits = kv_bits
        self.int8 = kv_bits is not None
        self.window = window
        # int4 pages pack two nibbles per byte: stored last dim is D//2,
        # with the SAME per-(page-position, head) fp32 scale layout as int8
        store_d = head_dim // 2 if kv_bits == 4 else head_dim
        shape = (num_layers, num_pages, kv_heads, page_size, store_d)
        if kv_bits is not None:
            self.buffers: Dict[str, jnp.ndarray] = {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "ks": jnp.zeros(shape[:-1] + (1,), jnp.float32),
                "vs": jnp.zeros(shape[:-1] + (1,), jnp.float32),
            }
        else:
            self.buffers = {"k": jnp.zeros(shape, dtype),
                            "v": jnp.zeros(shape, dtype)}
        # LIFO free list over pages 1..P-1 (page 0 stays the null page),
        # mirrored by a set for O(1) membership
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._free_set = set(self._free)
        self.refcount: List[int] = [0] * num_pages
        self.prefix: Optional[PrefixIndex] = (
            PrefixIndex(page_size) if prefix_cache else None)
        # optional fault-injection plan (serving/faults.py): when set, a
        # scripted step makes every alloc fail — the exact observable a
        # real exhausted pool produces, so callers exercise their
        # backoff/preemption paths deterministically
        self.faults = None
        # allocator traffic counters (r11): the engine mirrors these into
        # its metrics registry each step — alloc-failure rate is the
        # earliest pressure signal an operator sees
        self.alloc_calls = 0
        self.alloc_failures = 0

    # -- allocation -------------------------------------------------------

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_cached(self) -> int:
        """Pages parked in the prefix index (reclaimable + shared)."""
        return len(self.prefix) if self.prefix is not None else 0

    @property
    def num_reclaimable(self) -> int:
        """Cached pages with no live reference — evictable on demand."""
        if self.prefix is None:
            return 0
        return sum(1 for p in self.prefix._by_page if self.refcount[p] == 0)

    @property
    def pages_in_use(self) -> int:
        """Pages referenced by at least one live request."""
        return sum(1 for r in self.refcount if r > 0)

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` positions."""
        return max(1, math.ceil(n_tokens / self.page_size))

    def _check_page(self, p: int) -> None:
        if p <= 0 or p >= self.num_pages:
            raise ValueError(f"invalid page id {p}")

    def _push_free(self, p: int) -> None:
        self._free.append(p)
        self._free_set.add(p)

    def alloc(self, n_pages: int) -> Optional[List[int]]:
        """Lease ``n_pages`` fresh pages at refcount 1, or None when even
        LRU-evicting reclaimable cached pages can't satisfy the request
        (caller keeps the request queued — FCFS)."""
        if n_pages == 0:
            return []
        self.alloc_calls += 1
        if self.faults is not None and self.faults.fail_alloc():
            self.alloc_failures += 1
            return None
        if n_pages > len(self._free) and self.prefix is not None:
            for p in self.prefix.evict(n_pages - len(self._free),
                                       self.refcount):
                self._push_free(p)
        if n_pages > len(self._free):
            self.alloc_failures += 1
            return None
        got = []
        for _ in range(n_pages):
            p = self._free.pop()
            self._free_set.discard(p)
            self.refcount[p] = 1
            got.append(p)
        return got

    def retain(self, pages: List[int]) -> None:
        """Add one reference per page — a request adopting cached prefix
        pages (a reclaimable page at refcount 0 becomes live again)."""
        for p in pages:
            self._check_page(p)
            if p in self._free_set:
                raise ValueError(f"retain of free page {p}")
            self.refcount[p] += 1

    def free(self, pages: List[int]) -> None:
        """Drop one reference per page.  A page reaching refcount 0 goes
        back to the free list unless the prefix index still names it (it
        parks as reclaimable instead).  Over-freeing — more drops than
        references, including duplicates within one call — is a
        programming error worth failing loudly on, BEFORE any mutation."""
        for p, n in Counter(pages).items():
            self._check_page(p)
            if self.refcount[p] < n:
                raise ValueError(f"double free of page {p}")
        for p in pages:
            self.refcount[p] -= 1
            if self.refcount[p] == 0 and not (
                    self.prefix is not None and p in self.prefix):
                self._push_free(p)

    # retain/free bracket one REFERENCE; `release` reads better at call
    # sites that drop a whole lease
    release = free

    # -- page payload transfer (r15 disaggregation) -----------------------

    def export_pages(self, pages: List[int]) -> Dict[str, object]:
        """Serialize the K/V bytes of ``pages`` (in the given block-table
        order) as host numpy — the disaggregated prefill→decode handoff
        payload, using the same per-buffer numpy-copy shape as snapshot
        v5's pool section, so quantized pages travel WITH their fp32
        scale planes automatically (``ks``/``vs`` are just more buffers).
        The payload embeds :meth:`layout`; :meth:`ingest_pages` on the
        receiving pool refuses a mismatch."""
        idx = [int(p) for p in pages]
        for p in idx:
            self._check_page(p)
        return {
            "layout": self.layout(),
            "buffers": {k: np.asarray(v[:, idx]).copy()
                        for k, v in self.buffers.items()},
        }

    @staticmethod
    def payload_nbytes(payload: Dict[str, object]) -> int:
        """Wire size of an :meth:`export_pages` payload (page bytes +
        scale planes; the layout dict is negligible)."""
        return sum(int(a.nbytes) for a in payload["buffers"].values())

    def check_layout(self, want: Dict[str, object],
                     what: str = "page payload") -> None:
        """Refuse a foreign KV layout loudly, with the per-key diff —
        the same guard shape snapshot restore uses: mixed layouts would
        reinterpret page bytes silently (wrong dtype, wrong head count,
        wrong nibble packing), which is strictly worse than failing."""
        have = self.layout()
        if have != want:
            diff = {k: (want.get(k), have.get(k))
                    for k in set(want) | set(have)
                    if have.get(k) != want.get(k)}
            raise ValueError(
                f"{what} KV layout does not match this pool — sender vs "
                f"receiver: {diff}; prefill and decode replicas must "
                "share kv heads, page dtype, kv_bits, window and page "
                "geometry for pages to be byte-compatible")

    def ingest_pages(self, payload: Dict[str, object],
                     pages: List[int]) -> None:
        """Adopt an :meth:`export_pages` payload into freshly leased
        ``pages`` (same order).  Layout-guarded; the scatter is a plain
        eager ``.at[].set`` per buffer, so the round-trip
        export→host→ingest is bit-exact for fp, int8 and nibble-packed
        int4 pages and their scales alike."""
        self.check_layout(payload["layout"])
        bufs = payload["buffers"]
        if set(bufs) != set(self.buffers):
            raise ValueError(
                f"payload buffers {sorted(bufs)} != pool buffers "
                f"{sorted(self.buffers)}")
        idx = [int(p) for p in pages]
        for p in idx:
            self._check_page(p)
        n = len(idx)
        rows = jnp.asarray(idx, jnp.int32)
        for name, arr in bufs.items():
            if arr.shape[1] != n:
                raise ValueError(
                    f"payload buffer {name!r} carries {arr.shape[1]} "
                    f"pages for a {n}-page lease")
            self.buffers[name] = self.buffers[name].at[:, rows].set(
                jnp.asarray(arr))

    # -- invariants -------------------------------------------------------

    def check(self) -> None:
        """Refcount / free-list / prefix-index consistency — every page is
        exactly one of: free, live (refcount > 0), or cached-reclaimable.
        The serving tests' leak fixture calls this after every step."""
        if len(self._free) != len(self._free_set) or \
                set(self._free) != self._free_set:
            raise AssertionError("free list and free set diverged")
        if 0 in self._free_set or self.refcount[0] != 0:
            raise AssertionError("null page entered circulation")
        cached = set(self.prefix._by_page) if self.prefix is not None else set()
        for p in range(1, self.num_pages):
            free = p in self._free_set
            rc = self.refcount[p]
            if rc < 0:
                raise AssertionError(f"negative refcount on page {p}")
            if free and (rc != 0 or p in cached):
                raise AssertionError(f"page {p} free while referenced/cached")
            if not free and rc == 0 and p not in cached:
                raise AssertionError(f"leaked page {p}: unreferenced, "
                                     "uncached, not free")

    # -- stats ------------------------------------------------------------

    def utilization(self) -> float:
        """Fraction of usable pages out of the free list (live + cached)."""
        usable = self.num_pages - 1
        return 1.0 - len(self._free) / max(usable, 1)

    def hbm_bytes(self) -> int:
        return sum(b.size * b.dtype.itemsize for b in self.buffers.values())

    def bytes_per_token(self) -> int:
        """HBM bytes one token position costs across all layers and both
        sides — the capacity denominator the KV-capacity bench reports
        (GQA divides it by the group factor, int8 by ~4, int4 by ~8)."""
        per_side = sum(
            b.dtype.itemsize * self.num_kv_heads
            * (b.shape[-1] if name in ("k", "v") else 1)
            for name, b in self.buffers.items())
        return self.num_layers * per_side

    def layout(self) -> Dict[str, object]:
        """The pool's KV storage layout — everything that must MATCH for
        another pool's pages to be byte-compatible with this one (what
        snapshot v5 records and restore() refuses to mix)."""
        return {
            "kv_heads": self.num_kv_heads,
            "page_dtype": str(self.buffers["k"].dtype),
            "kv_bits": self.kv_bits,
            "window": self.window,
            "page_size": self.page_size,
            "head_dim": self.head_dim,
        }
