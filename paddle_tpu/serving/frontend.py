"""Streaming HTTP front end for the serving engine (r12 tentpole).

The engine has had the hard serving parts since r10/r11 — lifecycle
terminals, deadlines, ``cancel``, backpressure, metrics — but no network
surface: nothing to point real traffic at.  This module is that surface,
built ONLY on stdlib ``asyncio`` + hand-rolled HTTP/1.1 (the serving
package's no-new-deps contract; the AST guard in tests/test_metrics.py
scopes the ``asyncio/http/socket/json`` exemption to THIS file), the
same split the reference Paddle fork draws between its compute engine
and its brpc service layer (PAPER.md layers 3/7).

Endpoints:

  * ``POST /v1/completions`` — OpenAI-style completion over TOKEN IDS
    (the repo ships no tokenizer; clients send ``{"prompt": [ids...],
    "max_tokens": n}``).  With ``"stream": true`` (default) the response
    is Server-Sent Events: one ``data:`` JSON per sampled token,
    delivered per ENGINE STEP through the engine's ``on_token`` observer
    — the streamed sequence is token-for-token the eventual
    ``FinishedRequest.tokens`` — then a final event carrying
    ``finish_reason``/usage and ``data: [DONE]``.  Optional fields:
    ``tenant`` (WFQ accounting/isolation), ``deadline_ms`` (SLO),
    ``stream: false`` (single JSON response).
  * ``GET /metrics`` — the r11 registry's Prometheus text exposition
    (per-tenant labeled series included), scrapeable in place.
  * ``GET /healthz`` — liveness + queue/slot/pool gauges as JSON, plus
    per-replica ``last_step_age_s`` staleness (r16).
  * ``GET /debug/{state,flight,trace}`` (r16, ``debug=True`` only) —
    read-only introspection: ledgers + invariant verdicts, one
    replica's flight-recorder ring (``?replica=N``), and the (merged,
    for a cluster) Chrome trace.

SLO semantics at the HTTP layer:

  * queue overflow (the engine's global ``max_queue`` OR the tenant's
    ``max_waiting`` quota) → **429 Too Many Requests** with
    ``Retry-After`` — the request is NEVER enqueued, matching the
    engine's explicit-``rejected``-terminal posture;
  * deadline expiry BEFORE the first token → **408 Request Timeout**
    (after streaming starts the status line is gone — expiry then ends
    the stream with ``finish_reason: "expired"``);
  * client disconnect mid-stream → ``engine.cancel(rid)`` the moment
    the broken pipe is seen, so an abandoned request frees its slot and
    KV pages instead of decoding to nobody.

Concurrency model: ONE event loop runs both the socket handlers and the
engine driver — a cooperative task stepping ``engine.step()`` whenever
there is work and yielding between steps.  ``step()`` blocks the loop
for one device dispatch; that is deliberate (the engine's host mirrors
are not thread-safe, and a blocked accept queue is exactly the
backpressure a saturated engine should present).  Handlers talk to the
driver through per-request ``asyncio.Queue`` channels fed by the
``on_token`` hook and ``step()``'s FinishedRequests.
"""

from __future__ import annotations

import asyncio
import json
from http import HTTPStatus
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["ServingFrontend", "serve"]

#: Response cap on request bodies (token-id lists are small; anything
#: bigger is a client bug, not a workload).
MAX_BODY_BYTES = 1 << 20


class _BadRequest(Exception):
    """Malformed HTTP from the client — answered with a 400, never a
    bare connection drop."""


class ServingFrontend:
    """Asyncio HTTP server over one :class:`ServingEngine` — or over a
    :class:`~paddle_tpu.serving.router.Router` (r15): anything with the
    engine's driving surface (``add_request`` / ``cancel`` / ``step`` /
    ``has_work`` / ``on_token``) serves; a Router is detected by its
    ``replicas`` attribute, ``/healthz`` then aggregates the fleet and
    ``/metrics`` renders the replica-labeled cluster scrape page.

    ``port=0`` binds an ephemeral port (read ``frontend.port`` after
    :meth:`start` — the test client does).  The ctor chains onto any
    existing ``engine.on_token`` observer and attaches a metrics
    registry when none is present (``/metrics`` needs one).
    """

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0,
                 idle_sleep_s: float = 0.002, max_tenants: int = 256,
                 debug: bool = False):
        self.engine = engine
        # the read-only /debug surface (state, flight ring, trace) is
        # OFF by default: it exposes internals and full rings — opt in
        # per deployment (examples/serve_gpt.py --debug)
        self.debug = debug
        # a Router drives like an engine; only observability and the
        # backpressure probe need to know there is a fleet behind it
        self._is_cluster = hasattr(engine, "replicas")
        self.host = host
        self.port = port
        self.idle_sleep_s = idle_sleep_s
        # clients name tenants freely (WFQ learns them lazily), but the
        # NETWORK surface must bound the distinct names it will relay —
        # every new tenant mints permanent labeled metric series and
        # policy state, the same unbounded-cardinality hole the 404
        # handler guards against for paths
        self.max_tenants = max_tenants
        self._seen_tenants: set = set()
        self._channels: Dict[int, asyncio.Queue] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._driver: Optional[asyncio.Task] = None
        self._driver_error: Optional[BaseException] = None
        if self._is_cluster:
            from .metrics import MetricsRegistry

            # per-replica registries stay per-replica (the engine's
            # one-registry rule); HTTP-surface series live in their own
            # registry, concatenated onto the cluster scrape page
            if engine._parts is None:
                engine.attach_metrics()
            self._http_registry = MetricsRegistry()
        else:
            if engine.metrics is None:
                engine.attach_metrics()
            self._http_registry = engine.metrics
        self._http_requests = \
            lambda route, code: self._http_registry.counter(
                "serving_http_requests",
                "front-end requests by route/status",
                labels={"route": route, "code": str(code)})
        self._streams_open = self._http_registry.gauge(
            "serving_http_streams_open", "SSE streams currently open")
        self._prev_on_token = engine.on_token

        def _chained(rid, tok, _prev=self._prev_on_token):
            if _prev is not None:
                _prev(rid, tok)
            ch = self._channels.get(rid)
            if ch is not None:
                ch.put_nowait(("token", tok))

        self._chained_on_token = _chained
        engine.on_token = _chained

    # -- lifecycle --------------------------------------------------------

    async def start(self) -> "ServingFrontend":
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        self._driver = asyncio.ensure_future(self._drive())
        return self

    async def stop(self) -> None:
        if self._driver is not None:
            self._driver.cancel()
            try:
                await self._driver
            except asyncio.CancelledError:
                pass
            except Exception:
                pass    # a dead driver already recorded _driver_error
            self._driver = None
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        # leave the token path the way we found it — but only if we are
        # still the installed observer (someone chaining after us keeps
        # their hook, and our closure forwards to the original anyway)
        if self.engine.on_token is self._chained_on_token:
            self.engine.on_token = self._prev_on_token

    async def _drive(self) -> None:
        """The engine host loop as a cooperative task: step while there
        is work (yielding between steps so handlers run), deliver every
        terminal to its channel, idle-sleep when drained.  A real
        exception escaping ``step()`` must not strand the server in a
        half-alive state: every open stream is aborted (clients see an
        error instead of hanging forever) and ``/healthz`` flips to 503
        until the process is restarted."""
        try:
            while True:
                if self.engine.has_work:
                    for fin in self.engine.step():
                        ch = self._channels.get(fin.rid)
                        if ch is not None:
                            ch.put_nowait(("done", fin))
                    await asyncio.sleep(0)
                else:
                    await asyncio.sleep(self.idle_sleep_s)
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            self._driver_error = e
            raise
        finally:
            # EVERY driver exit — death or clean stop() cancellation —
            # must wake the open handlers, or they block on channel.get()
            # forever with nobody left to feed them (their requests would
            # never cancel and stop()'s wait_closed would deadlock on
            # 3.12+, which waits for active connection handlers)
            for ch in self._channels.values():
                ch.put_nowait(("abort", None))

    # -- HTTP plumbing ----------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            try:
                req = await self._read_request(reader)
            except _BadRequest as e:
                await self._send(writer, "bad-request", 400, json.dumps(
                    {"error": str(e)}).encode())
                return
            if req is None:
                return
            method, path, headers, body = req
            await self._route(method, path, headers, body, reader, writer)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    @staticmethod
    async def _read_request(reader) -> Optional[Tuple[str, str, dict, bytes]]:
        try:
            line = await reader.readline()
        except ValueError:
            # a request line over the StreamReader limit (64 KiB) —
            # answer 400, don't die with an unhandled LimitOverrun
            raise _BadRequest("request line too long")
        if not line:
            return None
        try:
            method, path, _version = line.decode("latin-1").split()
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        while True:
            try:
                h = await reader.readline()
            except ValueError:
                raise _BadRequest("header line too long")
            if h in (b"\r\n", b"\n", b""):
                break
            if len(headers) >= 100:
                raise _BadRequest("too many headers")
            key, _, val = h.decode("latin-1").partition(":")
            headers[key.strip().lower()] = val.strip()
        try:
            n = int(headers.get("content-length", "0") or 0)
        except ValueError:
            raise _BadRequest("Content-Length is not an integer")
        if not 0 <= n <= MAX_BODY_BYTES:
            raise _BadRequest(f"Content-Length must be 0..{MAX_BODY_BYTES}")
        body = await reader.readexactly(n) if n else b""
        return method, path, headers, body

    @staticmethod
    def _response(status: int, body: bytes,
                  ctype: str = "application/json",
                  extra_headers: str = "") -> bytes:
        phrase = HTTPStatus(status).phrase
        return (f"HTTP/1.1 {status} {phrase}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n{extra_headers}\r\n"
                ).encode("latin-1") + body

    async def _send(self, writer, route: str, status: int, body: bytes,
                    ctype: str = "application/json",
                    extra_headers: str = "") -> None:
        self._http_requests(route, status).inc()
        writer.write(self._response(status, body, ctype, extra_headers))
        await writer.drain()

    async def _route(self, method, path, headers, body, reader, writer):
        if method == "GET" and path == "/healthz":
            eng = self.engine
            dead = self._driver_error is not None
            if self._is_cluster:
                reps = eng.replicas
                payload = json.dumps({
                    "status": "driver dead" if dead else "ok",
                    "error": repr(self._driver_error) if dead else None,
                    "replicas": len(reps),
                    "roles": [r.role for r in reps],
                    "step": max(r._step_idx for r in reps),
                    "queue_depth": eng.queue_depth,
                    "slots_active": sum(r.scheduler.n_active
                                        for r in reps),
                    "slots_total": sum(r.max_slots for r in reps),
                    "pages_in_use": sum(r.pool.pages_in_use
                                        for r in reps),
                    "pages_free": sum(r.pool.num_free for r in reps),
                    "policy": reps[0].scheduler.policy.name,
                    # staleness per replica: seconds (engine clock)
                    # since its last completed step — a wedged replica
                    # shows a growing age while the fleet looks alive
                    "last_step_age_s": [
                        (r._now() - r._last_step_at)
                        if r._last_step_at is not None else None
                        for r in reps],
                }).encode()
            else:
                payload = json.dumps({
                    "status": "driver dead" if dead else "ok",
                    "error": repr(self._driver_error) if dead else None,
                    "step": eng._step_idx,
                    "queue_depth": eng.scheduler.n_waiting,
                    "slots_active": eng.scheduler.n_active,
                    "slots_total": eng.max_slots,
                    "pages_in_use": eng.pool.pages_in_use,
                    "pages_free": eng.pool.num_free,
                    "policy": eng.scheduler.policy.name,
                    "last_step_age_s": (
                        (eng._now() - eng._last_step_at)
                        if eng._last_step_at is not None else None),
                }).encode()
            await self._send(writer, "/healthz", 503 if dead else 200,
                             payload)
        elif method == "GET" and path == "/metrics":
            if self._is_cluster:
                # replica-labeled fleet page + the HTTP-surface series
                # (distinct families, so concatenation stays one valid
                # exposition page)
                text = (self.engine.to_prometheus()
                        + self._http_registry.to_prometheus()).encode()
            else:
                text = self.engine.metrics.to_prometheus().encode()
            await self._send(writer, "/metrics", 200, text,
                             ctype="text/plain; version=0.0.4")
        elif method == "GET" and \
                path.partition("?")[0].startswith("/debug/"):
            await self._debug(path, writer)
        elif method == "POST" and path == "/v1/completions":
            await self._completions(body, reader, writer)
        else:
            # FIXED label, not the client-supplied path: arbitrary paths
            # must not mint unbounded counter series in the registry
            await self._send(writer, "unknown", 404,
                             b'{"error": "not found"}')

    # -- /debug -----------------------------------------------------------

    @staticmethod
    def _flight_summary(dump: dict) -> dict:
        """Strip a dump_debug payload's flight ring to its counters —
        /debug/state stays light; the full ring is /debug/flight."""
        fl = dump.get("flight")
        if fl is not None:
            dump["flight"] = {k: fl[k]
                              for k in ("capacity", "recorded", "dropped")}
        return dump

    async def _debug(self, path: str, writer) -> None:
        """Read-only introspection (``debug=True`` only — 404 when off,
        indistinguishable from absent): ``/debug/state`` (ledgers +
        invariant verdicts), ``/debug/flight?replica=N`` (one black-box
        ring, full), ``/debug/trace`` (Chrome trace JSON — merged
        across the fleet for a cluster)."""
        base, _, query = path.partition("?")
        eng = self.engine
        if not self.debug:
            await self._send(writer, "unknown", 404,
                             b'{"error": "not found"}')
            return
        if base == "/debug/state":
            if self._is_cluster:
                payload = eng.dump_debug()
                payload["replicas"] = [self._flight_summary(d)
                                       for d in payload["replicas"]]
            else:
                payload = self._flight_summary(eng.dump_debug())
            await self._send(writer, "/debug/state", 200,
                             json.dumps(payload, default=float).encode())
        elif base == "/debug/flight":
            replica = 0
            for part in query.split("&"):
                if part.startswith("replica="):
                    try:
                        replica = int(part[len("replica="):])
                    except ValueError:
                        await self._send(
                            writer, "/debug/flight", 400,
                            b'{"error": "replica must be an integer"}')
                        return
            engines = eng.replicas if self._is_cluster else [eng]
            if not 0 <= replica < len(engines):
                await self._send(
                    writer, "/debug/flight", 400, json.dumps(
                        {"error": f"replica must be in "
                                  f"0..{len(engines) - 1}"}).encode())
                return
            fl = engines[replica].flight
            if fl is None:
                await self._send(
                    writer, "/debug/flight", 404,
                    b'{"error": "flight recorder not attached"}')
                return
            await self._send(writer, "/debug/flight", 200,
                             json.dumps(fl.to_json(),
                                        default=float).encode())
        elif base == "/debug/trace":
            tracer = eng.tracer
            if tracer is None:
                await self._send(writer, "/debug/trace", 404,
                                 b'{"error": "tracer not attached"}')
                return
            trace = (eng.merged_trace() if self._is_cluster
                     else tracer.to_json())
            await self._send(writer, "/debug/trace", 200,
                             json.dumps(trace).encode())
        else:
            await self._send(writer, "unknown", 404,
                             b'{"error": "not found"}')

    # -- /v1/completions --------------------------------------------------

    def _parse_completion(self, body: bytes) -> Tuple[Optional[dict], str]:
        try:
            req = json.loads(body.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError):
            return None, "body is not JSON"
        if not isinstance(req, dict):
            return None, "body must be a JSON object"
        prompt = req.get("prompt")
        # type(t) is int, NOT isinstance: JSON true/false are bools,
        # which subclass int and would silently decode as 1/0
        if (not isinstance(prompt, list) or not prompt
                or not all(type(t) is int and 0 <= t < 2 ** 31
                           for t in prompt)):
            return None, ("prompt must be a non-empty list of token ids "
                          "(int32 range)")
        max_tokens = req.get("max_tokens", 16)
        if type(max_tokens) is not int or max_tokens < 1:
            return None, "max_tokens must be a positive integer"
        if len(prompt) + max_tokens > self.engine.max_seq_len:
            return None, (f"prompt+max_tokens exceeds engine max_seq_len "
                          f"{self.engine.max_seq_len}")
        deadline_ms = req.get("deadline_ms")
        if deadline_ms is not None and not (
                isinstance(deadline_ms, (int, float)) and deadline_ms > 0):
            return None, "deadline_ms must be a positive number"
        tenant = req.get("tenant")
        if tenant is not None:
            if not isinstance(tenant, str) or not tenant or \
                    len(tenant) > 64 or not all(
                        c.isalnum() or c in "-_.:" for c in tenant):
                return None, ("tenant must be 1-64 chars of "
                              "[alnum - _ . :]")
        return {"prompt": prompt, "max_tokens": max_tokens,
                "tenant": tenant, "deadline_ms": deadline_ms,
                "stream": bool(req.get("stream", True))}, ""

    def _overloaded(self, tenant: Optional[str]) -> bool:
        eng = self.engine
        if self._is_cluster:
            if (eng.max_queue is not None
                    and eng.queue_depth >= eng.max_queue):
                return True
            # with a shared ClusterWFQState any member answers for the
            # whole fleet; without one, quotas are per-replica and the
            # first prefill target is where this request would land-ish
            return eng.prefill_targets[0].scheduler.quota_reject(tenant)
        if (eng.max_queue is not None
                and eng.scheduler.n_waiting >= eng.max_queue):
            return True
        return eng.scheduler.quota_reject(tenant)

    async def _completions(self, body, reader, writer):
        route = "/v1/completions"
        parsed, err = self._parse_completion(body)
        if parsed is None:
            await self._send(writer, route, 400,
                             json.dumps({"error": err}).encode())
            return
        if self._driver_error is not None:
            await self._send(writer, route, 503,
                             b'{"error": "engine driver died"}')
            return
        if self._overloaded(parsed["tenant"]):
            # backpressure maps to HTTP BEFORE the engine ever sees the
            # request — the 429 is the network face of the engine's
            # "rejected" terminal, with a hint to come back later
            await self._send(writer, route, 429,
                             b'{"error": "queue full, retry later"}',
                             extra_headers="Retry-After: 1\r\n")
            return
        tenant = parsed["tenant"]
        if tenant is not None and tenant not in self._seen_tenants:
            # cardinality gate AFTER the overload check: names on
            # requests that were shed never burn a slot, so a 429 storm
            # cannot exhaust the tenant budget for real accounts
            if len(self._seen_tenants) >= self.max_tenants:
                await self._send(writer, route, 400, json.dumps(
                    {"error": f"over {self.max_tenants} distinct tenants "
                              "— tenant names are accounts, not request "
                              "ids"}).encode())
                return
            self._seen_tenants.add(tenant)
        eng = self.engine
        rid = eng.add_request(
            np.asarray(parsed["prompt"], np.int32),
            parsed["max_tokens"], tenant=parsed["tenant"],
            deadline_s=(parsed["deadline_ms"] / 1e3
                        if parsed["deadline_ms"] is not None else None))
        channel: asyncio.Queue = asyncio.Queue()
        self._channels[rid] = channel
        watcher = asyncio.ensure_future(
            self._watch_disconnect(reader, channel))
        finished = False
        try:
            if parsed["stream"]:
                finished = await self._stream_sse(rid, channel, writer,
                                                  parsed)
            else:
                finished = await self._respond_json(rid, channel, writer,
                                                    parsed)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            watcher.cancel()
            self._channels.pop(rid, None)
            if not finished:
                # broken pipe / handler death with the request still
                # live: release its slot and pages NOW
                eng.cancel(rid)

    @staticmethod
    async def _watch_disconnect(reader, channel: asyncio.Queue) -> None:
        """Drain the (finished) request side of the socket and wake the
        handler on a connection RESET.  A clean EOF alone is NOT a
        disconnect — a conforming client may half-close its write side
        (shutdown(SHUT_WR)) while still reading the response; a client
        that fully went away surfaces as a reset here or as a write
        failure on the next SSE event, both of which cancel."""
        try:
            while True:
                data = await reader.read(4096)
                if not data:
                    return                  # half-close: keep serving
        except asyncio.CancelledError:
            return
        except ConnectionError:
            channel.put_nowait(("disconnect", None))

    async def _first_event(self, channel) -> Tuple[str, object]:
        """The earliest thing that happens to the request decides the
        status line: a token → 200 (stream on), a degraded terminal →
        429/408, disconnect → nothing to send."""
        kind, payload = await channel.get()
        return kind, payload

    @staticmethod
    def _sse(obj: dict) -> bytes:
        return f"data: {json.dumps(obj)}\n\n".encode()

    def _final_event(self, rid: int, fin, parsed: dict) -> dict:
        return {"id": rid, "object": "completion",
                "finish_reason": fin.finish_reason,
                "tokens": [int(t) for t in fin.tokens],
                "usage": {"prompt_tokens": len(parsed["prompt"]),
                          "completion_tokens": int(fin.tokens.size)}}

    async def _stream_sse(self, rid, channel, writer, parsed) -> bool:
        """SSE delivery; returns True once the request is terminal (the
        caller cancels otherwise)."""
        route = "/v1/completions"
        kind, payload = await self._first_event(channel)
        if kind == "disconnect":
            return False
        if kind == "abort":
            await self._send(writer, route, 503,
                             b'{"error": "engine stopped"}')
            return False
        if kind == "done" and payload.finish_reason == "rejected":
            await self._send(writer, route, 429,
                             b'{"error": "queue full, retry later"}',
                             extra_headers="Retry-After: 1\r\n")
            return True
        if kind == "done" and payload.finish_reason == "expired" \
                and payload.tokens.size == 0:
            await self._send(writer, route, 408,
                             b'{"error": "deadline expired in queue"}')
            return True
        self._http_requests(route, 200).inc()
        self._streams_open.inc()
        try:
            writer.write((
                "HTTP/1.1 200 OK\r\n"
                "Content-Type: text/event-stream\r\n"
                "Cache-Control: no-cache\r\n"
                "Connection: close\r\n\r\n").encode("latin-1"))
            index = 0
            while True:
                if kind in ("disconnect", "abort"):
                    # abort mid-stream: headers are gone; ending the
                    # stream without [DONE] is the error signal
                    return False
                if kind == "token":
                    writer.write(self._sse(
                        {"id": rid, "object": "completion.chunk",
                         "index": index, "token": int(payload)}))
                    index += 1
                    await writer.drain()
                elif kind == "done":
                    writer.write(self._sse(
                        self._final_event(rid, payload, parsed)))
                    writer.write(b"data: [DONE]\n\n")
                    await writer.drain()
                    return True
                kind, payload = await channel.get()
        finally:
            self._streams_open.dec()

    async def _respond_json(self, rid, channel, writer, parsed) -> bool:
        """Non-streaming mode: buffer until terminal, one JSON body."""
        route = "/v1/completions"
        while True:
            kind, payload = await channel.get()
            if kind == "disconnect":
                return False
            if kind == "abort":
                await self._send(writer, route, 503,
                                 b'{"error": "engine stopped"}')
                return False
            if kind == "done":
                fin = payload
                if fin.finish_reason == "rejected":
                    status = 429
                elif fin.finish_reason == "expired" and fin.tokens.size == 0:
                    status = 408
                else:
                    status = 200
                await self._send(writer, route, status, json.dumps(
                    self._final_event(rid, fin, parsed)).encode())
                return True
            # tokens accumulate on the FinishedRequest; nothing to do


def serve(engine, host: str = "127.0.0.1", port: int = 8000,
          banner: bool = True, debug: bool = False) -> None:
    """Blocking convenience: run the front end until interrupted
    (examples/serve_gpt.py ``--http``)."""
    async def _main():
        fe = await ServingFrontend(engine, host, port,
                                   debug=debug).start()
        if banner:
            print(f"serving on http://{fe.host}:{fe.port}  "
                  f"(POST /v1/completions, GET /metrics, GET /healthz)")
            print(f"  curl -N http://{fe.host}:{fe.port}/v1/completions "
                  f"-d '{{\"prompt\": [1, 2, 3], \"max_tokens\": 8, "
                  f"\"tenant\": \"a\"}}'")
        try:
            await asyncio.Event().wait()
        finally:
            await fe.stop()

    try:
        asyncio.run(_main())
    except KeyboardInterrupt:
        pass
