"""Multi-tenant scheduling policies for the serving engine (r12).

The r08–r11 engine schedules admission strictly FCFS: one deque, one
queue-head, head-of-line blocking by design.  That is the right default
for parity tests (admission order is trivially deterministic) but has no
notion of WHO a request belongs to — one tenant flooding the queue
starves everyone else, which is exactly the failure mode a multi-tenant
front end (serving/frontend.py) must not have.

This module extracts the waiting-queue half of the scheduler into a
pluggable :class:`SchedulerPolicy` (pop / peek / requeue-at-head — the
three operations ``FCFSScheduler`` and the engine's preempt-and-recompute
path actually use) and adds a weighted-fair-queueing policy on the
Virtual Token Counter shape (Sheng et al., "Fairness in Serving Large
Language Models", OSDI '24):

  * every tenant owns a FIFO queue (FCFS *within* a tenant) and a
    **virtual token counter** — total tokens served on the tenant's
    behalf divided by its weight;
  * admission picks the eligible tenant with the LOWEST counter (ties
    break deterministically), so over time served tokens converge to the
    weight ratio — the Sarathi/Orca per-step token budget is unchanged,
    WFQ only decides *whose* request the budget admits next;
  * both prefill and decode tokens charge the counter (the engine calls
    :meth:`SchedulerPolicy.charge` with first-time-served token deltas —
    a preempted request's recompute is NOT re-charged, see
    ``Request.uncharged_tokens``);
  * a tenant going idle and returning has its counter LIFTED to the
    minimum over active tenants, so banked idle time cannot be spent
    starving everyone later (the VTC no-starvation lift);
  * per-tenant quotas: ``max_resident`` caps concurrent slots (the
    tenant stays queued past it), ``max_waiting`` caps queue depth
    (overflow becomes an explicit ``rejected`` terminal — per-tenant
    backpressure, same shape as the engine's global ``max_queue``);
  * ``priority`` is a strict tier above the counters: a higher-priority
    tenant with waiting work always admits first (use sparingly — within
    a tier, weights share).

FCFS stays the DEFAULT policy (``FCFSPolicy`` reproduces the pre-r12
deque semantics operation-for-operation), so every existing parity /
preemption / snapshot / chaos test runs unmodified.
"""

from __future__ import annotations

from collections import Counter as _Tally
from collections import deque
from dataclasses import asdict, dataclass
from typing import Deque, Dict, Iterable, Iterator, List, Optional, Union

__all__ = ["DEFAULT_TENANT", "TenantConfig", "SchedulerPolicy",
           "FCFSPolicy", "WFQPolicy", "ClusterWFQState",
           "normalize_tenants", "make_policy"]

#: Requests carrying no tenant name account under this one.
DEFAULT_TENANT = "default"


@dataclass
class TenantConfig:
    """Per-tenant scheduling knobs.

    ``weight`` — share of served tokens relative to other tenants in the
    same priority tier (2.0 gets twice the tokens of 1.0 under
    contention); ``priority`` — strict admission tier, higher first;
    ``max_resident`` — max concurrently admitted requests (slot quota);
    ``max_waiting`` — max queued requests (per-tenant backpressure;
    overflow rejects at enqueue).

    SLO targets (r16, all optional — a tenant without them costs no
    metric series): ``ttft_slo_s`` budgets time-to-first-token,
    ``e2e_slo_s`` budgets enqueue-to-terminal latency; each terminal is
    judged against the set budgets and feeds the per-tenant attainment
    gauge and fast/slow burn-rate windows
    (:class:`~paddle_tpu.serving.metrics.SLOTracker`).
    ``slo_objective`` is the attainment target the error budget derives
    from (0.99 → a 1% budget)."""

    weight: float = 1.0
    priority: int = 0
    max_resident: Optional[int] = None
    max_waiting: Optional[int] = None
    ttft_slo_s: Optional[float] = None
    e2e_slo_s: Optional[float] = None
    slo_objective: float = 0.99

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {self.weight}")
        if self.max_resident is not None and self.max_resident < 1:
            raise ValueError("max_resident must be >= 1")
        if self.max_waiting is not None and self.max_waiting < 0:
            raise ValueError("max_waiting must be >= 0")
        if self.ttft_slo_s is not None and self.ttft_slo_s <= 0:
            raise ValueError("ttft_slo_s must be > 0")
        if self.e2e_slo_s is not None and self.e2e_slo_s <= 0:
            raise ValueError("e2e_slo_s must be > 0")
        if not 0.0 < self.slo_objective < 1.0:
            raise ValueError(
                f"slo_objective must be in (0, 1), got {self.slo_objective}")


def normalize_tenants(tenants) -> Dict[str, TenantConfig]:
    """Accept ``{name: TenantConfig | dict | weight-number}`` (the shapes
    a ctor echo / CLI flag / snapshot produce) and return proper
    configs."""
    out: Dict[str, TenantConfig] = {}
    for name, cfg in (tenants or {}).items():
        if isinstance(cfg, TenantConfig):
            out[name] = cfg
        elif isinstance(cfg, dict):
            out[name] = TenantConfig(**cfg)
        else:
            out[name] = TenantConfig(weight=float(cfg))
    return out


class SchedulerPolicy:
    """Waiting-queue policy contract used by ``FCFSScheduler``.

    The scheduler owns slots/pages/budget arithmetic; the policy owns
    ONLY queue order and tenant accounting.  The operations mirror what
    the pre-r12 deque supported: ``push`` (arrival), ``peek``/``pop``
    (admission — ``pop`` must return exactly the request the immediately
    preceding ``peek`` returned), ``requeue_head`` (a preempted request
    goes back in FRONT of its queue), ``remove`` (cancel),
    ``pop_expired`` (deadline sweep).  ``charge``/``on_admit``/
    ``on_release`` are accounting hooks that FCFS ignores."""

    name = "abstract"

    # -- queue order ------------------------------------------------------

    def push(self, req) -> None:
        raise NotImplementedError

    def requeue_head(self, req) -> None:
        raise NotImplementedError

    def peek(self):
        raise NotImplementedError

    def pop(self):
        raise NotImplementedError

    def remove(self, rid: int):
        raise NotImplementedError

    def pop_expired(self, now: float) -> List:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError

    def __iter__(self) -> Iterator:
        raise NotImplementedError

    def load_waiting(self, reqs: Iterable) -> None:
        """Restore path (serving/snapshot.py): re-enqueue in iteration
        order WITHOUT arrival side effects (idle lifts, quota checks) —
        counters load separately via :meth:`load_state`."""
        for req in reqs:
            self.push(req)

    # -- tenant accounting (no-ops for FCFS) ------------------------------

    def quota_reject(self, tenant: Optional[str]) -> bool:
        """True when an arriving request for ``tenant`` must be rejected
        (per-tenant backpressure).  Consulted by the engine BEFORE
        ``push``."""
        return False

    def on_admit(self, req) -> None:
        pass

    def on_release(self, req) -> None:
        """The request left its slot — terminal OR preemption."""
        pass

    def charge(self, req, n_tokens: int) -> None:
        """``n_tokens`` of first-time service (prefill positions + decode
        tokens) were delivered for ``req`` — charged against the Orca/
        Sarathi token budget already spent by the engine."""
        pass

    # -- snapshot ---------------------------------------------------------

    def to_state(self) -> dict:
        return {"name": self.name}

    def load_state(self, st: dict) -> None:
        pass

    def check(self, resident_requests: List) -> None:
        """Internal-consistency audit (engine.check_invariants)."""
        pass


class FCFSPolicy(SchedulerPolicy):
    """The pre-r12 deque, verbatim: global arrival order, head-of-line
    blocking, preempted requests requeue at the head."""

    name = "fcfs"

    def __init__(self):
        self.queue: Deque = deque()

    def push(self, req) -> None:
        self.queue.append(req)

    def requeue_head(self, req) -> None:
        self.queue.appendleft(req)

    def peek(self):
        return self.queue[0] if self.queue else None

    def pop(self):
        return self.queue.popleft()

    def remove(self, rid: int):
        for req in self.queue:
            if req.rid == rid:
                self.queue.remove(req)
                return req
        return None

    def pop_expired(self, now: float) -> List:
        expired = [r for r in self.queue if r.expired(now)]
        for req in expired:
            self.queue.remove(req)
        return expired

    def __len__(self) -> int:
        return len(self.queue)

    def __iter__(self) -> Iterator:
        return iter(self.queue)


class ClusterWFQState(object):
    """Router-global WFQ ledger (r15): ONE virtual-counter dict + tenant
    config map shared by every replica's :class:`WFQPolicy` in a
    multi-replica cluster, plus the member list that makes activity and
    quota checks cluster-wide.  Each member policy still owns its LOCAL
    queue and residency (a request waits/runs on exactly one replica),
    but ``charge`` lands on the shared counters — so
    ``vt[tenant] == total served tokens / weight`` ACROSS the cluster,
    and tenant fairness holds no matter which replica served the tokens.
    Build one state, pass ``WFQPolicy(state=...)`` per engine
    (``serving.router.make_cluster`` does this wiring)."""

    def __init__(self, tenants=None):
        self.tenants: Dict[str, TenantConfig] = normalize_tenants(tenants)
        self.vt: Dict[str, float] = {}
        self.members: List["WFQPolicy"] = []


class WFQPolicy(SchedulerPolicy):
    """Weighted fair queueing over per-tenant virtual token counters.

    ``tenants`` maps tenant name -> :class:`TenantConfig` (or a bare
    weight number); tenants not named get ``TenantConfig()`` lazily on
    first arrival, so the policy never rejects an unknown tenant — it
    just shares at weight 1.

    ``state`` (r15) plugs this policy into a shared
    :class:`ClusterWFQState`: counters and tenant configs ALIAS the
    shared dicts, and activity / idle-lift / quota checks consider every
    member replica — a tenant busy on replica A is not "idle" (no unfair
    counter lift) and not under-quota (no double admission) on replica
    B.  A standalone policy is just a one-member cluster, so the r12
    single-engine semantics are unchanged."""

    name = "wfq"

    def __init__(self, tenants=None, state: Optional[ClusterWFQState] = None):
        self._state = state
        if state is not None:
            if tenants:
                raise ValueError(
                    "pass tenants to the ClusterWFQState, not to member "
                    "policies — one config map per cluster")
            # alias, don't copy: every member reads/writes the ONE ledger
            self.tenants = state.tenants
            self.vt = state.vt
            state.members.append(self)
        else:
            self.tenants = normalize_tenants(tenants)
            self.vt = {}                     # served tokens / weight
        self.queues: Dict[str, Deque] = {}
        self.resident: Dict[str, int] = {}   # requests currently in slots

    # -- helpers ----------------------------------------------------------

    @staticmethod
    def tenant_of(req) -> str:
        return getattr(req, "tenant", None) or DEFAULT_TENANT

    def config(self, tenant: str) -> TenantConfig:
        cfg = self.tenants.get(tenant)
        if cfg is None:
            cfg = self.tenants[tenant] = TenantConfig()
        return cfg

    def _queue(self, tenant: str) -> Deque:
        q = self.queues.get(tenant)
        if q is None:
            q = self.queues[tenant] = deque()
            self.vt.setdefault(tenant, 0.0)
            self.resident.setdefault(tenant, 0)
        return q

    def _peers(self) -> List["WFQPolicy"]:
        """Every policy sharing this ledger (just self when standalone):
        activity, lifts and quotas are judged over the whole cluster."""
        return self._state.members if self._state is not None else [self]

    def _active(self, tenant: str) -> bool:
        """Waiting or resident work ANYWHERE in the cluster — the tenant
        is consuming/contending."""
        return any(bool(p.queues.get(tenant))
                   or p.resident.get(tenant, 0) > 0
                   for p in self._peers())

    def _resident_total(self, tenant: str) -> int:
        """Cluster-wide slots the tenant holds (max_resident quota)."""
        return sum(p.resident.get(tenant, 0) for p in self._peers())

    def _waiting_total(self, tenant: str) -> int:
        """Cluster-wide queue depth for the tenant (max_waiting quota)."""
        return sum(len(p.queues.get(tenant, ())) for p in self._peers())

    def _eligible(self) -> Optional[str]:
        """The tenant whose queue head admits next: highest priority
        tier, then lowest virtual counter, then name (deterministic).
        Slot-quota-blocked tenants are skipped — their requests wait
        without blocking anyone else's admission."""
        best = None
        for t, q in self.queues.items():
            if not q:
                continue
            cfg = self.config(t)
            if cfg.max_resident is not None and \
                    self._resident_total(t) >= cfg.max_resident:
                continue
            key = (-cfg.priority, self.vt.get(t, 0.0), t)
            if best is None or key < best[0]:
                best = (key, t)
        return best[1] if best is not None else None

    # -- queue order ------------------------------------------------------

    def push(self, req) -> None:
        t = self.tenant_of(req)
        was_idle = not self._active(t)
        q = self._queue(t)
        if was_idle:
            # the VTC lift: an idle tenant's counter stopped moving while
            # active tenants' kept rising — raise it to the smallest
            # active counter so banked idle time is not a starvation
            # weapon.  (Never lowered: a tenant ahead of the pack stays
            # ahead by exactly its surplus.)  Active spans queued AND
            # resident-only tenants — after a snapshot restore a tenant
            # can be fully in slots with no queue entry yet.  Under a
            # shared cluster ledger "active" and the candidate set span
            # every member replica: a tenant mid-flight on another
            # replica both blocks the lift for itself and anchors it for
            # others.
            names = set()
            for p in self._peers():
                names |= set(p.queues) | set(p.resident)
            active = [self.vt.get(u, 0.0) for u in names
                      if u != t and self._active(u)]
            if active:
                self.vt[t] = max(self.vt.get(t, 0.0), min(active))
        q.append(req)

    def requeue_head(self, req) -> None:
        """A PREEMPTED request: front of its tenant's queue (it predates
        everything the tenant still has waiting).  No idle lift — the
        tenant was resident a moment ago, and its counter must carry
        over unchanged so recompute is not double-charged."""
        self._queue(self.tenant_of(req)).appendleft(req)

    def peek(self):
        t = self._eligible()
        return self.queues[t][0] if t is not None else None

    def pop(self):
        t = self._eligible()
        if t is None:
            raise IndexError("pop from an empty/blocked WFQ policy")
        return self.queues[t].popleft()

    def remove(self, rid: int):
        for q in self.queues.values():
            for req in q:
                if req.rid == rid:
                    q.remove(req)
                    return req
        return None

    def pop_expired(self, now: float) -> List:
        expired = []
        for q in self.queues.values():
            for req in [r for r in q if r.expired(now)]:
                q.remove(req)
                expired.append(req)
        return expired

    def __len__(self) -> int:
        return sum(len(q) for q in self.queues.values())

    def __iter__(self) -> Iterator:
        """Deterministic order (snapshot / invariants): tenants by name,
        FIFO within each."""
        for t in sorted(self.queues):
            yield from self.queues[t]

    # -- tenant accounting ------------------------------------------------

    def quota_reject(self, tenant: Optional[str]) -> bool:
        t = tenant or DEFAULT_TENANT
        # read-only: a rejected arrival must not mint permanent tenant
        # state (unknown tenants have no quota to exceed anyway).  The
        # depth is CLUSTER-wide under a shared ledger — max_waiting is a
        # per-tenant promise, not a per-replica one.
        cfg = self.tenants.get(t)
        return cfg is not None and cfg.max_waiting is not None and \
            self._waiting_total(t) >= cfg.max_waiting

    def on_admit(self, req) -> None:
        t = self.tenant_of(req)
        self.resident[t] = self.resident.get(t, 0) + 1

    def on_release(self, req) -> None:
        t = self.tenant_of(req)
        n = self.resident.get(t, 0) - 1
        if n < 0:
            raise AssertionError(
                f"tenant {t!r} released more requests than admitted")
        self.resident[t] = n

    def charge(self, req, n_tokens: int) -> None:
        t = self.tenant_of(req)
        self.vt[t] = self.vt.get(t, 0.0) + n_tokens / self.config(t).weight

    # -- snapshot ---------------------------------------------------------

    def to_state(self) -> dict:
        return {"name": self.name,
                "vt": dict(self.vt),
                "tenants": {t: asdict(c) for t, c in self.tenants.items()}}

    def load_state(self, st: dict) -> None:
        if st.get("name") != self.name:
            raise ValueError(
                f"policy state is {st.get('name')!r}, engine runs {self.name}")
        for t, cfg in normalize_tenants(st.get("tenants")).items():
            self.tenants.setdefault(t, cfg)
        self.vt.update({t: float(v) for t, v in st.get("vt", {}).items()})

    def check(self, resident_requests: List) -> None:
        actual = _Tally(self.tenant_of(r) for r in resident_requests)
        for t, n in self.resident.items():
            if n != actual.get(t, 0):
                raise AssertionError(
                    f"tenant {t!r} resident count {n} != {actual.get(t, 0)} "
                    "requests actually in slots")
            if n < 0:
                raise AssertionError(f"negative resident count for {t!r}")
        for t, v in self.vt.items():
            if not (v >= 0.0):                 # catches NaN too
                raise AssertionError(f"tenant {t!r} virtual counter {v}")
        for t, cfg in self.tenants.items():
            if cfg.max_resident is not None and \
                    actual.get(t, 0) > cfg.max_resident:
                raise AssertionError(
                    f"tenant {t!r} holds {actual.get(t, 0)} slots over "
                    f"its quota {cfg.max_resident}")


def make_policy(policy: Union[None, str, SchedulerPolicy],
                tenants=None) -> SchedulerPolicy:
    """Resolve the engine's ``policy=``/``tenants=`` ctor knobs: None
    defaults to FCFS unless tenants are configured (then WFQ — naming
    tenants means wanting isolation); strings name the built-ins; an
    instance passes through (snapshot/restore cannot rebuild instances —
    prefer the names)."""
    if isinstance(policy, SchedulerPolicy):
        return policy
    if policy is None:
        policy = "wfq" if tenants else "fcfs"
    if policy == "fcfs":
        if tenants:
            raise ValueError(
                "tenants= requires the wfq policy (FCFS has no tenant "
                "accounting) — pass policy='wfq' or drop tenants")
        return FCFSPolicy()
    if policy == "wfq":
        return WFQPolicy(tenants)
    raise ValueError(f"unknown scheduler policy {policy!r}")
