"""Dependency-free metrics registry for the serving engine (r11).

The engine's ``stats`` dict is a flat ledger read once at drain time —
good enough for a test assertion, useless for operating a service: you
cannot route on a number you only see after the load is gone.  ROADMAP
items 1 (multi-replica routing) and 4 (SLO-aware scheduling) both need
per-request TTFT / time-between-token percentiles and queue/pool
time-series to make decisions on.  This module is that substrate,
hand-rolled on stdlib only (the serving package's no-new-imports
contract — ``tests/test_metrics.py`` guards it):

  * :class:`Counter` — monotonic totals (terminals, preemptions,
    tokens);
  * :class:`Gauge` — point-in-time levels (pool occupancy, queue
    depth, budget utilization);
  * :class:`Histogram` — exponential ("log-linear") buckets with
    p50/p90/p99 readout, the same shape Prometheus client libraries use
    for latency: fixed memory, O(1) observe, quantiles by linear
    interpolation within the straddling bucket.  Exact min/max/sum ride
    along so readouts stay honest at small counts;
  * :class:`MetricsRegistry` — the namespace: get-or-create by name (+
    optional ``labels=`` dict, r12: one instance per (name, labels)
    combination, rendered ``name{tenant="a"}`` in Prometheus and
    flattened ``name.tenant=a`` for TB scalars — the multi-tenant front
    end's per-tenant series), ``scalars()`` flattens everything
    (histograms expand to
    ``_count/_sum/_mean/_min/_max/_p50/_p90/_p99``) for the TensorBoard
    exporter, ``to_prometheus()`` emits the text exposition format
    (cumulative ``_bucket{le=...}`` lines, one HELP/TYPE per family),
    ``to_state()`` / ``from_state()`` make metrics survive engine
    snapshot/restore.

Exporters (both file-based, both dependency-free):

  * :class:`MetricsFileExporter` — periodic scalar flush through the
    hand-rolled :class:`~paddle_tpu.utils.tensorboard.SummaryWriter`
    (one tag per scalar, ``step`` = engine step; ``tensorboard
    --logdir`` opens it directly) plus a Prometheus ``metrics.prom``
    text dump on close — the node-exporter "textfile collector" shape,
    so a real scrape pipeline picks it up without the engine growing an
    HTTP server.

Determinism: time-valued observations fed from the engine's injectable
clock (``serving/faults.py``'s virtual clock under a FaultPlan) make
histogram readouts bit-reproducible across chaos runs — asserted in
tests/test_serving_faults.py.
"""

from __future__ import annotations

import math
import os
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "MetricsFileExporter", "SLOTracker", "merge_registries",
           "cluster_prometheus", "aggregate_scalars"]


def _sanitize(name: str) -> str:
    """Prometheus metric names: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = "".join(c if (c.isalnum() or c == "_") else "_" for c in name)
    return out if out and not out[0].isdigit() else "_" + out


def _escape_label(v: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _canon_labels(labels) -> Tuple[Tuple[str, str], ...]:
    """Sorted (key, value) pairs — the canonical identity of a labeled
    series, so ``{"a": 1, "b": 2}`` and ``{"b": 2, "a": 1}`` name the
    same metric."""
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _prom_labels(labels, extra: str = "") -> str:
    """``{k="v",...}`` rendering (empty string for no labels); ``extra``
    appends a pre-rendered pair (the histogram ``le`` bound)."""
    parts = [f'{_sanitize(k)}="{_escape_label(v)}"'
             for k, v in _canon_labels(labels)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _scalar_suffix(labels) -> str:
    """Flat-tag rendering for TensorBoard scalars: ``name.tenant=a``."""
    return "".join(f".{k}={v}" for k, v in _canon_labels(labels))


def _series_key(name: str, labels) -> str:
    """Registry key: base name + canonical label rendering, so each
    (name, labels) combination is its own series."""
    return name + _prom_labels(labels)


class Counter:
    """Monotonic counter.  ``set_total`` exists ONLY for mirror-sync and
    snapshot-restore (the engine keeps some counters in lockstep with its
    ``stats`` ledger); user code should ``inc``."""

    __slots__ = ("name", "help", "labels", "value")

    kind = "counter"

    def __init__(self, name: str, help: str = "", labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else {}
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def set_total(self, v: float) -> None:
        self.value = float(v)

    def scalars(self) -> Dict[str, float]:
        return {self.name + _scalar_suffix(self.labels): self.value}

    def to_state(self) -> dict:
        return {"kind": self.kind, "help": self.help, "name": self.name,
                "labels": dict(self.labels), "value": self.value}

    def load_state(self, st: dict) -> None:
        self.value = float(st["value"])


class Gauge:
    """Point-in-time level; ``set`` replaces, ``inc``/``dec`` adjust."""

    __slots__ = ("name", "help", "labels", "value")

    kind = "gauge"

    def __init__(self, name: str, help: str = "", labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else {}
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n

    def scalars(self) -> Dict[str, float]:
        return {self.name + _scalar_suffix(self.labels): self.value}

    def to_state(self) -> dict:
        return {"kind": self.kind, "help": self.help, "name": self.name,
                "labels": dict(self.labels), "value": self.value}

    def load_state(self, st: dict) -> None:
        self.value = float(st["value"])


class Histogram:
    """Exponential-bucket histogram with quantile readout.

    Bucket upper bounds grow geometrically: ``start * factor**i`` for
    ``n_buckets`` finite buckets plus the +Inf overflow — the default
    (100µs .. ~28min at factor 2) covers every latency the engine can
    produce, with ~2x relative quantile error (one factor step), tight
    enough to schedule on.  ``quantile`` finds the straddling bucket by
    cumulative rank and interpolates linearly inside it, clamped to the
    exact observed min/max so tiny samples don't report a bound nobody
    measured.
    """

    __slots__ = ("name", "help", "labels", "bounds", "counts", "count",
                 "sum", "min", "max")

    kind = "histogram"

    def __init__(self, name: str, help: str = "", start: float = 1e-4,
                 factor: float = 2.0, n_buckets: int = 24, labels=None):
        if start <= 0 or factor <= 1.0 or n_buckets < 1:
            raise ValueError("need start > 0, factor > 1, n_buckets >= 1")
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else {}
        self.bounds: List[float] = [start * factor ** i
                                    for i in range(n_buckets)]
        self.counts: List[int] = [0] * (n_buckets + 1)  # last = +Inf
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def observe(self, v: float) -> None:
        v = float(v)
        self.counts[bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def quantile(self, q: float) -> float:
        """q in [0, 1].  0.0 with no observations (a readout, not NaN)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if cum >= rank and c > 0:
                hi = (self.bounds[i] if i < len(self.bounds)
                      else self.max)
                lo = self.bounds[i - 1] if i > 0 else 0.0
                # linear interpolation of the rank within the bucket
                frac = 1.0 - (cum - rank) / c
                est = lo + (hi - lo) * frac
                return min(max(est, self.min), self.max)
        return self.max

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def scalars(self) -> Dict[str, float]:
        n, sfx = self.name, _scalar_suffix(self.labels)
        return {f"{n}_count{sfx}": float(self.count),
                f"{n}_sum{sfx}": self.sum,
                f"{n}_mean{sfx}": self.mean,
                f"{n}_min{sfx}": self.min if self.min is not None else 0.0,
                f"{n}_max{sfx}": self.max if self.max is not None else 0.0,
                f"{n}_p50{sfx}": self.quantile(0.50),
                f"{n}_p90{sfx}": self.quantile(0.90),
                f"{n}_p99{sfx}": self.quantile(0.99)}

    def to_state(self) -> dict:
        return {"kind": self.kind, "help": self.help, "name": self.name,
                "labels": dict(self.labels),
                "bounds": list(self.bounds), "counts": list(self.counts),
                "count": self.count, "sum": self.sum,
                "min": self.min, "max": self.max}

    def load_state(self, st: dict) -> None:
        self.bounds = [float(b) for b in st["bounds"]]
        self.counts = [int(c) for c in st["counts"]]
        self.count = int(st["count"])
        self.sum = float(st["sum"])
        self.min = st["min"]
        self.max = st["max"]

    def merge(self, other: "Histogram") -> None:
        """Fold another histogram's buckets into this one element-wise —
        the cluster-quantile primitive: summed bucket counts over N
        replicas give the SAME quantile estimate a single histogram fed
        the union of samples would (identical bounds required; engines
        built from one config always share them)."""
        if self.bounds != other.bounds:
            raise ValueError(
                f"cannot merge {self.name!r}: bucket bounds differ")
        self.counts = [a + b for a, b in zip(self.counts, other.counts)]
        self.count += other.count
        self.sum += other.sum
        if other.min is not None:
            self.min = (other.min if self.min is None
                        else min(self.min, other.min))
        if other.max is not None:
            self.max = (other.max if self.max is None
                        else max(self.max, other.max))


class MetricsRegistry:
    """Get-or-create namespace of metrics, one per name.

    Re-requesting a name returns the SAME instance (a second caller
    asking for a different kind under an existing name is a programming
    error and raises) — so code observing ONE engine (its scheduler, a
    bench harness, a train loop using its own ``train_*`` names) can
    feed one registry without coordination.

    One engine per registry: the engine keeps its ``serving_*`` counters
    in lockstep with its stats ledger via ``set_total``, so TWO engines
    sharing a registry would overwrite each other's mirrored totals
    (last stepper wins) instead of aggregating.  Give each engine its
    own registry and sum ``scalars()`` downstream — that is the
    multi-replica aggregation shape (ROADMAP item 1).
    """

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._family_kind: Dict[str, str] = {}

    def __len__(self) -> int:
        return len(self._metrics)

    def __contains__(self, name: str) -> bool:
        return name in self._metrics

    def get(self, name: str, labels=None):
        return self._metrics.get(_series_key(name, labels))

    def _get_or_create(self, cls, name, help, labels=None, **kw):
        key = _series_key(name, labels)
        m = self._metrics.get(key)
        if m is None:
            # every labeled series of one FAMILY (base name) must share a
            # kind — Prometheus exposition emits one TYPE per family
            known = self._family_kind.get(name)
            if known is not None and known != cls.kind:
                raise ValueError(
                    f"metric {name!r} already registered as {known}")
            m = cls(name, help=help, labels=labels, **kw)
            self._metrics[key] = m
            self._family_kind[name] = cls.kind
        elif not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}")
        return m

    def counter(self, name: str, help: str = "", labels=None) -> Counter:
        """``labels={"tenant": "a"}`` makes this a labeled series:
        rendered ``name{tenant="a"}`` in the Prometheus exposition and
        flattened ``name.tenant=a`` in :meth:`scalars` — one instance
        per distinct (name, labels) combination."""
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels=None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "", start: float = 1e-4,
                  factor: float = 2.0, n_buckets: int = 24,
                  labels=None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   start=start, factor=factor,
                                   n_buckets=n_buckets)

    # -- readouts ---------------------------------------------------------

    def scalars(self) -> Dict[str, float]:
        """Every metric flattened to {tag: float} — the TensorBoard /
        bench-JSON surface.  Histograms expand to 8 derived tags."""
        out: Dict[str, float] = {}
        for m in self._metrics.values():
            out.update(m.scalars())
        return out

    def to_prometheus(self) -> str:
        """Text exposition format (one scrape page).  Histograms emit the
        standard cumulative ``_bucket{le="..."}`` series + ``_sum`` +
        ``_count``; +Inf is always present and equals ``_count``.
        Labeled series render ``name{k="v"}``; every series of one
        family emits CONTIGUOUSLY under a single HELP/TYPE header
        (lazily-created tenant series register interleaved, but the
        exposition format requires family grouping — strict parsers
        reject split families)."""
        families: Dict[str, List] = {}
        for m in self._metrics.values():
            families.setdefault(m.name, []).append(m)
        lines: List[str] = []
        for fam in families.values():
            name = _sanitize(fam[0].name)
            helps = [m.help for m in fam if m.help]
            if helps:
                lines.append(f"# HELP {name} {helps[0]}")
            lines.append(f"# TYPE {name} {fam[0].kind}")
            for m in fam:
                lines.extend(self._prom_series(name, m))
        return "\n".join(lines) + "\n"

    @staticmethod
    def _prom_series(name: str, m) -> List[str]:
        """The sample lines of ONE series (header emitted by caller)."""
        lines: List[str] = []
        lbl = _prom_labels(m.labels)
        if isinstance(m, Histogram):
            cum = 0
            for bound, c in zip(m.bounds, m.counts):
                cum += c
                le = _prom_labels(m.labels, f'le="{bound:.6g}"')
                lines.append(f"{name}_bucket{le} {cum}")
            inf = _prom_labels(m.labels, 'le="+Inf"')
            lines.append(f"{name}_bucket{inf} {m.count}")
            lines.append(f"{name}_sum{lbl} {m.sum:.9g}")
            lines.append(f"{name}_count{lbl} {m.count}")
        else:
            v = m.value
            lines.append(f"{name}{lbl} {int(v) if v == int(v) else v}")
        return lines

    # -- snapshot (serving/snapshot.py) -----------------------------------

    def to_state(self) -> dict:
        return {key: m.to_state() for key, m in self._metrics.items()}

    @classmethod
    def from_state(cls, state: dict) -> "MetricsRegistry":
        reg = cls()
        kinds = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}
        for key, st in state.items():
            # pre-r12 states keyed by bare name and carried no labels
            name = st.get("name", key)
            m = kinds[st["kind"]](name, help=st.get("help", ""),
                                  labels=st.get("labels") or None)
            m.load_state(st)
            reg._metrics[key] = m
            reg._family_kind.setdefault(name, m.kind)
        return reg


class MetricsFileExporter:
    """TensorBoard scalar time-series + Prometheus textfile dump.

    ``flush(step)`` writes every ``registry.scalars()`` tag at ``step``
    into an event file under ``out_dir`` (open with ``tensorboard
    --logdir out_dir``); ``close()`` writes the final scrape page to
    ``out_dir/metrics.prom`` (Prometheus node-exporter textfile-collector
    format) and closes the event file.  Context-manager friendly.
    """

    def __init__(self, registry: MetricsRegistry, out_dir: str,
                 prom_name: str = "metrics.prom"):
        from ..utils.tensorboard import SummaryWriter

        self.registry = registry
        self.out_dir = out_dir
        self.prom_path = os.path.join(out_dir, prom_name)
        self.writer = SummaryWriter(out_dir)
        self.last_step = -1

    def flush(self, step: int) -> None:
        self.last_step = step
        for tag, v in self.registry.scalars().items():
            if math.isfinite(v):
                self.writer.add_scalar(tag, v, step=step)
        self.writer.flush()

    def dump_prometheus(self) -> str:
        text = self.registry.to_prometheus()
        with open(self.prom_path, "w") as f:
            f.write(text)
        return self.prom_path

    def close(self) -> None:
        self.dump_prometheus()
        self.writer.close()

    def __enter__(self) -> "MetricsFileExporter":
        return self

    def __exit__(self, *a) -> None:
        self.close()


# -- multi-replica aggregation (r15) ----------------------------------------
#
# One engine per registry is a hard rule (set_total mirroring), so a
# routed fleet holds a DICT of registries — {"replica0": reg, ...} from
# Router.attach_metrics().  These two functions are the sanctioned ways
# to read that dict as one thing: a labeled scrape page, or a rolled-up
# scalar table.


def cluster_prometheus(parts: Dict[str, "MetricsRegistry"]) -> str:
    """One Prometheus scrape page over per-replica registries: every
    series gains a ``replica="<key>"`` label, and every family still
    renders contiguously under a single HELP/TYPE header (strict parsers
    reject split families).  Replica keys iterate sorted, so the page is
    deterministic for a given fleet state."""
    import copy

    families: Dict[str, List] = {}
    for rep in sorted(parts):
        for m in parts[rep]._metrics.values():
            mm = copy.copy(m)
            mm.labels = {**m.labels, "replica": str(rep)}
            families.setdefault(m.name, []).append(mm)
    lines: List[str] = []
    for fam in families.values():
        name = _sanitize(fam[0].name)
        helps = [m.help for m in fam if m.help]
        if helps:
            lines.append(f"# HELP {name} {helps[0]}")
        lines.append(f"# TYPE {name} {fam[0].kind}")
        for m in fam:
            lines.extend(MetricsRegistry._prom_series(name, m))
    return "\n".join(lines) + "\n"


def merge_registries(parts: Dict[str, "MetricsRegistry"]
                     ) -> MetricsRegistry:
    """Fold per-replica registries into ONE merged registry: counters
    and gauges sum values, histograms sum bucket counts element-wise
    (:meth:`Histogram.merge`) and combine exact min/max — so quantiles
    read off the merged registry are REAL cluster quantiles, identical
    to a single registry fed the union of samples (up to the shared
    bucket resolution; asserted against that oracle in
    tests/test_observability.py).  Replica keys iterate sorted, so the
    result is deterministic.  The merged registry is a read-only
    rollup — don't attach an engine to it."""
    kinds = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}
    merged = MetricsRegistry()
    for rep in sorted(parts):
        for key, m in parts[rep]._metrics.items():
            cur = merged._metrics.get(key)
            if cur is None:
                st = m.to_state()
                mm = kinds[st["kind"]](m.name, help=m.help,
                                       labels=m.labels or None)
                mm.load_state(st)
                merged._metrics[key] = mm
                merged._family_kind.setdefault(m.name, m.kind)
            elif cur.kind != m.kind:
                raise ValueError(
                    f"metric {m.name!r} is {cur.kind} on one replica "
                    f"and {m.kind} on another")
            elif isinstance(cur, Histogram):
                cur.merge(m)
            else:
                cur.value += m.value
    return merged


def aggregate_scalars(parts: Dict[str, "MetricsRegistry"]
                      ) -> Dict[str, float]:
    """Cluster rollup of per-replica registries as one scalar table:
    counters and gauges SUM across replicas; histogram buckets merge
    element-wise so ``_p50``/``_p90``/``_p99`` are REAL cluster
    quantiles (pre-r16 this dropped quantiles outright), ``_min`` /
    ``_max`` combine exactly, and ``_mean`` is the merged sum/count.
    Ratio gauges (hit rate, budget utilization) still sum like any
    gauge: divide by the replica count, or read the per-replica
    registries, when you want the level."""
    return merge_registries(parts).scalars()


# -- SLO attainment + burn rate (r16) ----------------------------------------


class _RollingWindow:
    """Bucketed rolling good/bad tally on an injectable clock.

    ``n_buckets`` fixed slots of ``window_s / n_buckets`` seconds each,
    recycled by epoch number — O(1) observe, O(n_buckets) readout, no
    timestamps stored, fully deterministic under the chaos virtual
    clock.  A slot whose epoch fell out of the window reads as empty
    (and is zeroed on reuse), so the tally always covers the trailing
    ``window_s`` seconds to bucket resolution."""

    __slots__ = ("window_s", "n_buckets", "bucket_s", "good", "bad",
                 "epoch")

    def __init__(self, window_s: float, n_buckets: int = 30):
        self.window_s = float(window_s)
        self.n_buckets = int(n_buckets)
        self.bucket_s = self.window_s / self.n_buckets
        self.good = [0] * self.n_buckets
        self.bad = [0] * self.n_buckets
        self.epoch: List[Optional[int]] = [None] * self.n_buckets

    def _slot(self, now: float) -> int:
        e = int(now // self.bucket_s)
        i = e % self.n_buckets
        if self.epoch[i] != e:
            self.epoch[i] = e
            self.good[i] = 0
            self.bad[i] = 0
        return i

    def observe(self, now: float, ok: bool) -> None:
        i = self._slot(now)
        if ok:
            self.good[i] += 1
        else:
            self.bad[i] += 1

    def bad_fraction(self, now: float) -> float:
        e_now = int(now // self.bucket_s)
        good = bad = 0
        for i in range(self.n_buckets):
            e = self.epoch[i]
            if e is not None and 0 <= e_now - e < self.n_buckets:
                good += self.good[i]
                bad += self.bad[i]
        total = good + bad
        return bad / total if total else 0.0


class SLOTracker:
    """Per-tenant SLO attainment + multi-window burn rate.

    The SRE error-budget idiom: for each (tenant, slo-kind) pair track
    lifetime attainment (``serving_slo_attainment{tenant=,slo=}``, the
    fraction of requests inside budget) and TWO rolling windows —
    ``fast`` (1-min-equivalent, pages quickly) and ``slow``
    (1-hr-equivalent, resists flapping) — whose **burn rate** is the
    window's bad fraction divided by the error budget
    ``1 - objective``; burn > 1 means the budget is being spent faster
    than the objective allows.  All series register lazily in the
    engine's registry, so tenants without SLOs cost nothing; all time
    comes from the engine clock, so chaos replays are deterministic.
    """

    FAST_WINDOW_S = 60.0
    SLOW_WINDOW_S = 3600.0

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self._series: Dict[tuple, dict] = {}

    def _get(self, tenant: str, kind: str, objective: float) -> dict:
        key = (tenant, kind)
        s = self._series.get(key)
        if s is None:
            lb = {"tenant": tenant, "slo": kind}
            reg = self.registry
            s = {
                "total": reg.counter(
                    "serving_slo_total",
                    "requests evaluated against this SLO", labels=lb),
                "miss": reg.counter(
                    "serving_slo_miss",
                    "requests that missed their SLO budget", labels=lb),
                "attain": reg.gauge(
                    "serving_slo_attainment",
                    "lifetime fraction of requests inside the SLO "
                    "budget", labels=lb),
                "burn_fast": reg.gauge(
                    "serving_slo_burn_rate",
                    "windowed bad-fraction / error budget; > 1 burns "
                    "the budget faster than the objective allows",
                    labels={**lb, "window": "fast"}),
                "burn_slow": reg.gauge(
                    "serving_slo_burn_rate", "",
                    labels={**lb, "window": "slow"}),
                "fast": _RollingWindow(self.FAST_WINDOW_S),
                "slow": _RollingWindow(self.SLOW_WINDOW_S),
                "objective": float(objective),
            }
            self._series[key] = s
        return s

    def observe(self, tenant: str, kind: str, ok: bool, now: float,
                objective: float) -> None:
        """Record one terminal's verdict against one SLO kind."""
        s = self._get(tenant, kind, objective)
        s["total"].inc()
        if not ok:
            s["miss"].inc()
        s["attain"].set(1.0 - s["miss"].value / s["total"].value)
        s["fast"].observe(now, ok)
        s["slow"].observe(now, ok)

    def sync(self, now: float) -> None:
        """Refresh the burn-rate gauges at ``now`` (called per step —
        windows page OUT even when no new terminals arrive)."""
        for s in self._series.values():
            budget = max(1.0 - s["objective"], 1e-9)
            s["burn_fast"].set(s["fast"].bad_fraction(now) / budget)
            s["burn_slow"].set(s["slow"].bad_fraction(now) / budget)
