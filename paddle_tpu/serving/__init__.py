"""Continuous-batching serving subsystem (ISSUE r08 tentpole).

Composes three pieces:

  * :class:`~paddle_tpu.serving.kv_pool.KVPool` — page-pool KV cache
    allocator with a reserved null page (PagedAttention, SOSP '23);
  * :class:`~paddle_tpu.serving.scheduler.FCFSScheduler` — FCFS
    iteration-level admission with a per-step token budget (Orca,
    OSDI '22);
  * :class:`~paddle_tpu.serving.engine.ServingEngine` — the host loop
    over TWO reusable jitted programs (bucketed prefill-into-slot +
    single decode step over the slot batch), backed by the Pallas
    paged-attention kernel (kernels/paged_attention.py).

See README "Serving" for the architecture and knobs;
``examples/serve_gpt.py`` for the end-to-end loop.
"""

from .kv_pool import KVPool
from .scheduler import Admission, FCFSScheduler, Request
from .engine import FinishedRequest, ServingEngine

__all__ = ["KVPool", "FCFSScheduler", "Request", "Admission",
           "ServingEngine", "FinishedRequest"]
