"""Continuous-batching serving subsystem (ISSUE r08 tentpole, r09 prefix
caching + chunked prefill).

Composes four pieces:

  * :class:`~paddle_tpu.serving.kv_pool.KVPool` — page-pool KV cache
    allocator with a reserved null page and per-page refcounts
    (PagedAttention, SOSP '23);
  * :class:`~paddle_tpu.serving.prefix_cache.PrefixIndex` — page-aligned
    radix index over token chunks for KV page reuse across requests
    sharing a prompt prefix, with LRU eviction of reclaimable pages
    (RadixAttention / SGLang);
  * :class:`~paddle_tpu.serving.scheduler.FCFSScheduler` — FCFS
    iteration-level admission with a Sarathi-style per-step chunk budget
    (Orca, OSDI '22; Sarathi-Serve, OSDI '24);
  * :class:`~paddle_tpu.serving.engine.ServingEngine` — the host loop
    over TWO reusable jitted programs (chunked prefill-into-pages +
    single decode step over the slot batch), backed by the Pallas
    paged-attention decode and paged-prefill chunk kernels
    (kernels/paged_attention.py, kernels/paged_prefill.py);
  * observability (r11): dependency-free
    :class:`~paddle_tpu.serving.metrics.MetricsRegistry` (counters /
    gauges / exponential-bucket histograms with p50/p90/p99) fed by the
    engine every step, per-request lifecycle tracing to Chrome
    trace-event JSON (:mod:`~paddle_tpu.serving.tracing`, opens in
    Perfetto, unified with ``profiler.RecordEvent`` host spans), and
    TensorBoard + Prometheus file exporters
    (``ServingEngine(metrics=..., trace=...)``,
    ``engine.run(metrics_dir=...)``);
  * multi-tenant serving front end (r12): pluggable
    :class:`~paddle_tpu.serving.tenancy.SchedulerPolicy` over the
    waiting queue — FCFS default, Virtual-Token-Counter weighted fair
    queueing (:class:`~paddle_tpu.serving.tenancy.WFQPolicy`) with
    per-tenant weights/priorities/quotas — and a stdlib-asyncio
    streaming HTTP API
    (:class:`~paddle_tpu.serving.frontend.ServingFrontend`: SSE
    ``/v1/completions`` per engine step via ``on_token``, ``/metrics``
    Prometheus scrape, ``/healthz``, disconnect→cancel, 429/408 SLO
    mapping);
  * speculative decoding (r13): host-side n-gram self-drafting
    (:class:`~paddle_tpu.serving.drafter.NGramDrafter`, prompt-lookup /
    PLD) proposes up to ``spec_k`` tokens per slot, one multi-query
    paged-attention verify dispatch scores every draft position
    (kernels/paged_attention.py ``paged_attention_mq``), and greedy
    rejection sampling accepts the longest agreeing prefix plus one
    corrected token — token-for-token identical to non-speculative
    decode (``ServingEngine(spec_k=...)``);
  * disaggregated multi-replica serving (r15):
    :class:`~paddle_tpu.serving.router.Router` routes each request to
    the replica with the longest cached prefix (read-only
    ``prefix_match_len`` probes, load tie-break), separates prefill
    workers from decode workers (``ServingEngine(role=...)`` + snapshot
    v5 page-payload handoffs, layout-guarded, adopted bit-exactly into
    the destination pool + prefix index), lifts WFQ virtual-token
    counters router-global
    (:class:`~paddle_tpu.serving.tenancy.ClusterWFQState`), and
    ``double_buffer=True`` overlaps host scheduling of step N+1 with
    the device's step N (``make_cluster`` builds the whole fleet);
  * cluster-wide observability (r16): replica-namespaced tracing with
    Chrome flow events stitching prefill export → router pump → decode
    ingest into ONE merged Perfetto timeline
    (:func:`~paddle_tpu.serving.tracing.merge_traces` /
    :func:`~paddle_tpu.serving.tracing.validate_trace`), a bounded
    per-step :class:`~paddle_tpu.serving.flight_recorder.FlightRecorder`
    black box on the engine clock (chaos replays dump bit-identically;
    crashes dump before re-raising), per-tenant SLO attainment + fast /
    slow burn-rate gauges (:class:`~paddle_tpu.serving.metrics.
    SLOTracker`, targets on :class:`~paddle_tpu.serving.tenancy.
    TenantConfig`), histogram-merging cluster aggregation
    (:func:`~paddle_tpu.serving.metrics.merge_registries`), and the
    front end's read-only ``/debug`` surface;
  * fault tolerance (r10): on-demand page growth with
    preempt-and-recompute under pool pressure, per-request deadlines /
    ``cancel`` / bounded-queue backpressure,
    :func:`~paddle_tpu.serving.snapshot.snapshot_engine` /
    :func:`~paddle_tpu.serving.snapshot.restore_engine` for exact
    resume, and the deterministic
    :class:`~paddle_tpu.serving.faults.FaultPlan` chaos harness.

See README "Serving" for the architecture and knobs;
``examples/serve_gpt.py`` for the end-to-end loop.
"""

from .kv_pool import KVPool
from .prefix_cache import PrefixIndex
from .scheduler import Admission, FCFSScheduler, Request
from .tenancy import (DEFAULT_TENANT, ClusterWFQState, FCFSPolicy,
                      SchedulerPolicy, TenantConfig, WFQPolicy)
from .metrics import (Counter, Gauge, Histogram, MetricsFileExporter,
                      MetricsRegistry, SLOTracker, aggregate_scalars,
                      cluster_prometheus, merge_registries)
from .tracing import (PID_ENGINE, PID_HOST, PID_REQUESTS, PID_ROUTER,
                      TraceRecorder, attach_profiler, detach_profiler,
                      flow_id, merge_traces, validate_trace)
from .drafter import NGramDrafter
from .flight_recorder import FlightRecorder
from .engine import TERMINAL_REASONS, FinishedRequest, ServingEngine
from .faults import FaultPlan, InjectedFault
from .snapshot import handoff_state, restore_engine, snapshot_engine
from .frontend import ServingFrontend
from .router import Router, make_cluster

__all__ = ["KVPool", "PrefixIndex", "FCFSScheduler", "Request", "Admission",
           "ServingEngine", "FinishedRequest", "TERMINAL_REASONS",
           "FaultPlan", "InjectedFault", "snapshot_engine",
           "restore_engine", "handoff_state", "MetricsRegistry", "Counter",
           "Gauge", "Histogram", "MetricsFileExporter", "TraceRecorder",
           "attach_profiler", "detach_profiler", "PID_ENGINE",
           "PID_REQUESTS", "PID_HOST", "PID_ROUTER",
           "SchedulerPolicy", "FCFSPolicy", "WFQPolicy", "TenantConfig",
           "ClusterWFQState", "DEFAULT_TENANT", "ServingFrontend",
           "NGramDrafter", "Router", "make_cluster",
           "aggregate_scalars", "cluster_prometheus", "merge_registries",
           "SLOTracker", "FlightRecorder", "flow_id", "merge_traces",
           "validate_trace"]
