"""Static-graph program representation.

Capability parity with the reference's ProgramDesc stack:
  - proto schema: ``/root/reference/paddle/fluid/framework/framework.proto``
    (OpDesc:43, VarDesc:169, BlockDesc:178, ProgramDesc:202)
  - Python wrappers: ``/root/reference/python/paddle/fluid/framework.py``
    (Variable:805, Operator:1921, Block:2522, Program)

TPU-first design notes
----------------------
The reference keeps a C++ proto mirror of every desc because its executor is a
C++ interpreter.  Here the executor lowers a whole Block into ONE traced JAX
function compiled by XLA, so descs are plain Python data with dict
serialization (save/load_inference_model parity) — there is no per-op C++
dispatch to feed.  Shape inference runs through ``jax.eval_shape`` over the
registered kernel, so InferShape is exactly the compiled semantics (no
separate shape-function zoo like the reference's InferShapeContext).
"""

from __future__ import annotations

import contextlib
import copy
import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from . import unique_name
from .dtype import convert_dtype

__all__ = [
    "Variable",
    "Parameter",
    "Operator",
    "Block",
    "Program",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "in_dygraph_mode",
    "enable_static",
    "disable_static",
    "name_scope",
    "grad_var_name",
]

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    """Parity: ``framework::GradVarName`` in the reference C++ core."""
    return name + GRAD_SUFFIX


class Variable:
    """A named tensor slot inside a Block.

    Parity: ``framework.py:805`` Variable.  A Variable in a static Program is
    a symbolic handle; its value lives in a Scope at run time (jax.Array).
    """

    def __bool__(self):
        # Parity: the reference raises here too (math_op_patch) — without
        # this, `if some_var:` / `while some_var:` in UNCONVERTED static
        # code silently takes the true branch (object default truthiness)
        # or spins forever, instead of failing at the broken line.
        raise TypeError(
            f"bool(Variable '{self.name}') is undefined in a static graph: "
            "a Variable has no value at trace time.  Use "
            "paddle.static.nn.cond / while_loop, or run the function "
            "through paddle.jit.to_static so `if`/`while` convert.")

    def __init__(
        self,
        block: "Block",
        name: Optional[str] = None,
        shape: Optional[Sequence[int]] = None,
        dtype: Any = "float32",
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
        type: str = "lod_tensor",
    ):
        self.block = block
        if name is None:
            name = unique_name.generate("_generated_var")
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else ()
        self.dtype = convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.type = type
        self.op: Optional["Operator"] = None  # producing op

    # -- helpers ---------------------------------------------------------
    @property
    def ndim(self) -> int:
        return len(self.shape)

    def numel(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    def astype(self, dtype):
        from ..ops.dispatch import dispatch_static

        return dispatch_static(
            "cast", {"X": [self]}, {"out_dtype": convert_dtype(dtype)}, block=self.block
        )["Out"][0]

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "shape": list(self.shape),
            "dtype": self.dtype,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "type": self.type,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", None),
        }

    def __repr__(self):
        return (
            f"var {self.name} : shape={self.shape} dtype={self.dtype} "
            f"persistable={self.persistable} stop_gradient={self.stop_gradient}"
        )

    __str__ = __repr__


class Parameter(Variable):
    """Parity: ``framework.py`` Parameter — persistable trainable Variable."""

    def __deepcopy__(self, memo):
        """Create a NEW parameter (fresh name) in the same block, replaying
        the initializer into the startup program — used when layers are
        deep-copied (e.g. TransformerEncoder stacking)."""
        new = self.block.create_parameter(
            shape=self.shape,
            dtype=self.dtype,
            name=unique_name.generate(self.name.rsplit("_", 1)[0]),
            trainable=self.trainable,
            initializer=self.initializer,
            regularizer=self.regularizer,
            need_clip=self.need_clip,
        )
        memo[id(self)] = new
        if self.initializer is not None:
            from ..nn.initializer import Initializer

            if isinstance(self.initializer, Initializer):
                from . import program as _fw

                self.initializer.apply_static(
                    new, _fw.default_startup_program().global_block()
                )
        return new

    def __init__(self, block, shape, dtype, name=None, trainable=True, **kwargs):
        initializer = kwargs.pop("initializer", None)
        regularizer = kwargs.pop("regularizer", None)
        need_clip = kwargs.pop("need_clip", True)
        is_distributed = kwargs.pop("is_distributed", False)
        kwargs.pop("persistable", None)
        super().__init__(
            block,
            name=name,
            shape=shape,
            dtype=dtype,
            persistable=True,
            stop_gradient=not trainable,
            **kwargs,
        )
        self.trainable = trainable
        self.initializer = initializer
        self.regularizer = regularizer
        self.need_clip = need_clip
        self.is_distributed = is_distributed


def raise_with_op_site(op, what: str, e: Exception):
    """Re-raise an op failure annotated with the op type and (when
    FLAGS_call_stack_level >= 2) its Python creation stack — the single
    error-provenance formatter (reference op_call_stack.cc role) shared by
    shape inference and the executor's lowering loop."""
    site = getattr(op, "callstack", None)
    raise RuntimeError(
        f"op {op.type!r} {what}: {e}"
        + (f"\n[operator creation stack]\n{site}" if site else
           "\n(set FLAGS_call_stack_level=2 for the operator creation "
           "stack)")
    ) from e


class Operator:
    """Parity: ``framework.py:1921`` Operator / OpDesc (framework.proto:43).

    inputs/outputs are slot-name -> list of variable names (strings).
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, List[str]]] = None,
        outputs: Optional[Dict[str, List[str]]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        # FLAGS_call_stack_level >= 2: remember where this op was built so
        # executor errors can point at user code (ref op_call_stack.cc role)
        from . import flags as _flags

        if _flags.flag("FLAGS_call_stack_level") >= 2:
            import traceback

            self.callstack = "".join(traceback.format_stack(limit=12)[:-2])
        else:
            self.callstack = None

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self) -> List[str]:
        return [n for v in self.inputs.values() for n in v]

    @property
    def output_arg_names(self) -> List[str]:
        return [n for v in self.outputs.values() for n in v]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def _set_attr(self, name: str, val):
        self.attrs[name] = val

    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "inputs": {k: list(v) for k, v in self.inputs.items()},
            "outputs": {k: list(v) for k, v in self.outputs.items()},
            "attrs": copy.deepcopy(self.attrs),
        }

    def __repr__(self):
        ins = ", ".join(f"{k}={v}" for k, v in self.inputs.items())
        outs = ", ".join(f"{k}={v}" for k, v in self.outputs.items())
        return f"{{Op({self.type}) inputs:[{ins}] outputs:[{outs}] attrs:{self.attrs}}}"


class Block:
    """Parity: ``framework.py:2522`` Block / BlockDesc (framework.proto:178)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = {}
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # -- vars ------------------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        var = Variable(self, **kwargs)
        self.vars[var.name] = var
        return var

    def create_parameter(self, **kwargs) -> Parameter:
        # Parameters always live in the program's global block (parity:
        # Block.create_parameter in the reference creates in global block).
        gblock = self.program.global_block()
        param = Parameter(gblock, **kwargs)
        gblock.vars[param.name] = param
        return param

    def has_var(self, name: str) -> bool:
        return name in self.vars

    def var(self, name: str) -> Variable:
        v = self.vars.get(name)
        if v is None:
            raise ValueError(f"Variable {name!r} not found in block {self.idx}")
        return v

    def _var_recursive(self, name: str) -> Variable:
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        raise ValueError(f"Variable {name!r} not found (recursive)")

    def _has_var_recursive(self, name: str) -> bool:
        try:
            self._var_recursive(name)
            return True
        except ValueError:
            return False

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops -------------------------------------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        """Append an op; resolves Variable objects to names and runs shape
        inference through the op registry (jax.eval_shape over the kernel).
        """
        inputs = _normalize_io(inputs)
        outputs = _normalize_io(outputs)
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._version += 1
        self._infer_shape(op)
        for slot_vars in outputs.values():
            for name in slot_vars:
                if name in self.vars:
                    self.vars[name].op = op
        return op

    def _prepend_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        inputs = _normalize_io(inputs)
        outputs = _normalize_io(outputs)
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._version += 1
        self._infer_shape(op)
        return op

    def _infer_shape(self, op: Operator):
        from ..ops import registry

        try:
            registry.infer_shape(self, op)
        except registry.OpNotRegistered:
            pass
        except Exception as e:
            raise_with_op_site(op, "failed shape inference", e)

    def to_dict(self) -> dict:
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }


class Program:
    """Parity: ``framework.py`` Program / ProgramDesc (framework.proto:202)."""

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = 0
        self._seed_counter = 0
        self._version = 0
        self._is_start_up_program = False

    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx: Optional[int] = None) -> Block:
        if parent_idx is None:
            parent_idx = self.current_block_idx
        blk = Block(self, len(self.blocks), parent_idx)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        return blk

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def all_parameters(self) -> List[Parameter]:
        return [p for b in self.blocks for p in b.all_parameters()]

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def clone(self, for_test: bool = False) -> "Program":
        """Parity: Program.clone. for_test strips is_test-sensitive behavior."""
        p = Program()
        p.random_seed = self.random_seed
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            for v in b.vars.values():
                nv_cls = Parameter if isinstance(v, Parameter) else Variable
                if nv_cls is Parameter:
                    nv = Parameter(
                        nb, shape=v.shape, dtype=v.dtype, name=v.name, trainable=v.trainable
                    )
                else:
                    nv = Variable(
                        nb,
                        name=v.name,
                        shape=v.shape,
                        dtype=v.dtype,
                        persistable=v.persistable,
                        stop_gradient=v.stop_gradient,
                        is_data=v.is_data,
                        type=v.type,
                    )
                nb.vars[v.name] = nv
            for op in b.ops:
                attrs = copy.deepcopy(op.attrs)
                if for_test and op.type in _IS_TEST_OPS:
                    attrs["is_test"] = True
                nb.ops.append(Operator(nb, op.type, op.inputs, op.outputs, attrs))
            p.blocks.append(nb)
        p.current_block_idx = 0
        return p

    def to_dict(self) -> dict:
        return {
            "version": 1,
            "random_seed": self.random_seed,
            "blocks": [b.to_dict() for b in self.blocks],
        }

    @staticmethod
    def from_dict(d: dict) -> "Program":
        p = Program()
        p.random_seed = d.get("random_seed", 0)
        p.blocks = []
        for bd in d["blocks"]:
            blk = Block(p, bd["idx"], bd["parent_idx"])
            for vd in bd["vars"]:
                if vd.get("is_parameter"):
                    v = Parameter(
                        blk,
                        shape=vd["shape"],
                        dtype=vd["dtype"],
                        name=vd["name"],
                        trainable=vd.get("trainable", True),
                    )
                else:
                    v = Variable(
                        blk,
                        name=vd["name"],
                        shape=vd["shape"],
                        dtype=vd["dtype"],
                        persistable=vd["persistable"],
                        stop_gradient=vd["stop_gradient"],
                        is_data=vd.get("is_data", False),
                        type=vd.get("type", "lod_tensor"),
                    )
                blk.vars[v.name] = v
            for od in bd["ops"]:
                blk.ops.append(
                    Operator(blk, od["type"], od["inputs"], od["outputs"], od["attrs"])
                )
            p.blocks.append(blk)
        return p

    def __repr__(self):
        lines = []
        for b in self.blocks:
            lines.append(f"-- block {b.idx} (parent {b.parent_idx}) --")
            for v in b.vars.values():
                lines.append("  " + repr(v))
            for op in b.ops:
                lines.append("  " + repr(op))
        return "\n".join(lines)

    __str__ = __repr__


# ops whose attr set includes is_test (for clone(for_test=True))
_IS_TEST_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
}


def _normalize_io(io) -> Dict[str, List[str]]:
    out: Dict[str, List[str]] = {}
    if not io:
        return out
    for slot, vs in io.items():
        if vs is None:
            continue
        if isinstance(vs, (Variable, str)):
            vs = [vs]
        out[slot] = [v.name if isinstance(v, Variable) else str(v) for v in vs]
    return out


# ---------------------------------------------------------------------------
# Global program state (parity: framework.py default_main_program etc.)
# ---------------------------------------------------------------------------

_main_program_ = Program()
_startup_program_ = Program()
_startup_program_._is_start_up_program = True


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(program: Program) -> Program:
    global _main_program_
    old = _main_program_
    _main_program_ = program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program_
    old = _startup_program_
    _startup_program_ = program
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


@contextlib.contextmanager
def name_scope(prefix: str):
    with unique_name.guard(prefix + "/"):
        yield


# ---------------------------------------------------------------------------
# Dygraph mode switch (parity: framework.py:185 in_dygraph_mode, paddle 2.x
# defaults to dygraph; paddle.enable_static flips to static graphs).
# ---------------------------------------------------------------------------

_dygraph_state = threading.local()


def in_dygraph_mode() -> bool:
    return getattr(_dygraph_state, "enabled", True)


def enable_static():
    _dygraph_state.enabled = False


def disable_static():
    _dygraph_state.enabled = True


@contextlib.contextmanager
def _dygraph_guard(enabled: bool):
    old = in_dygraph_mode()
    _dygraph_state.enabled = enabled
    try:
        yield
    finally:
        _dygraph_state.enabled = old
