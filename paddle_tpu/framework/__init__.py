"""Framework core: descs, places, dtypes, scope.

Parity: layer 2 of the reference (``python/paddle/fluid/framework.py`` and
the C++ descs under ``paddle/fluid/framework/``) — see SURVEY.md §1.
"""

from . import dtype, unique_name  # noqa: F401
from .dtype import convert_dtype, to_jax_dtype, to_numpy_dtype  # noqa: F401
from .place import (  # noqa: F401
    CPUPlace,
    CUDAPinnedPlace,
    CUDAPlace,
    Place,
    TPUPlace,
    XPUPlace,
    get_device,
    is_compiled_with_cuda,
    is_compiled_with_tpu,
    set_device,
    _get_current_place,
)
from .program import (  # noqa: F401
    Block,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    disable_static,
    enable_static,
    grad_var_name,
    in_dygraph_mode,
    name_scope,
    program_guard,
    _dygraph_guard,
)
from .scope import Scope, global_scope, scope_guard  # noqa: F401
