"""FLAGS registry: env-bootstrapped global configuration.

Role parity: the reference's three-tier gflags system —
``/root/reference/paddle/fluid/platform/flags.cc:44`` (C++ DEFINE_bool
``check_nan_inf`` et al), the pybind getter/setter bridge
(``pybind/global_value_getter_setter.cc``) and the env bootstrap in
``/root/reference/python/paddle/fluid/__init__.py:147`` (``__bootstrap__``
whitelists ``read_env_flags`` and forwards ``FLAGS_*`` env vars).

TPU-native reading: most reference flags tune subsystems XLA owns outright
(allocator strategy, GC thresholds, cudnn autotune).  Those names are still
*accepted* — scripts that set them keep working — but marked inert.  Flags
that do steer this runtime (nan/inf checking, benchmark sync, matmul
precision, flash-attention gating, profiler dir) are live and read at use
sites via :func:`flag`.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Sequence, Union


class _FlagDef:
    __slots__ = ("name", "type", "default", "help", "writable", "inert", "on_set")

    def __init__(self, name, type_, default, help_="", writable=True,
                 inert=False, on_set=None):
        self.name = name
        self.type = type_
        self.default = default
        self.help = help_
        self.writable = writable
        self.inert = inert
        self.on_set = on_set


_DEFS: Dict[str, _FlagDef] = {}
_VALUES: Dict[str, Any] = {}


def _parse(defn: _FlagDef, raw: Any) -> Any:
    if defn.type is bool:
        if isinstance(raw, str):
            return raw.strip().lower() in ("1", "true", "yes", "on")
        return bool(raw)
    return defn.type(raw)


def define_flag(name: str, default: Any, help: str = "", *, type: type = None,
                writable: bool = True, inert: bool = False, on_set=None) -> None:
    """Register a flag (and bootstrap its value from the environment)."""
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    defn = _FlagDef(name, type if type is not None else default.__class__,
                    default, help, writable, inert, on_set)
    _DEFS[name] = defn
    env = os.environ.get(name)
    value = _parse(defn, env) if env is not None else default
    _VALUES[name] = value
    if defn.on_set is not None and env is not None:
        defn.on_set(value)


def flag(name: str) -> Any:
    """Fast internal getter (no validation; KeyError on unknown flag)."""
    return _VALUES[name]


def get_flags(flags: Union[str, Sequence[str]]) -> Dict[str, Any]:
    """``paddle.get_flags`` parity: value lookup for one or many flags."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for n in flags:
        if n not in _VALUES:
            raise ValueError(f"Flag {n!r} is not registered "
                             f"(known: {len(_VALUES)} FLAGS_* names)")
        out[n] = _VALUES[n]
    return out


def set_flags(flags: Dict[str, Any]) -> None:
    """``paddle.set_flags`` parity: update writable flags."""
    if not isinstance(flags, dict):
        raise TypeError("set_flags expects a dict of {flag_name: value}")
    for n, v in flags.items():
        defn = _DEFS.get(n)
        if defn is None:
            raise ValueError(f"Flag {n!r} is not registered")
        if not defn.writable:
            raise ValueError(f"Flag {n!r} is not public/writable")
        val = _parse(defn, v)
        _VALUES[n] = val
        if defn.on_set is not None:
            defn.on_set(val)


def all_flags() -> List[str]:
    return sorted(_DEFS)


def is_inert(name: str) -> bool:
    return _DEFS[name].inert


# ---------------------------------------------------------------------------
# flag definitions
# ---------------------------------------------------------------------------

def _set_matmul_precision(v: str) -> None:
    import jax

    if v:
        jax.config.update("jax_default_matmul_precision", v)


# live flags (read at use sites)
define_flag("FLAGS_check_nan_inf", False,
            "check every op output for NaN/Inf and raise naming the op "
            "(ref flags.cc:44; framework/details/nan_inf_utils_detail.cc)")
define_flag("FLAGS_benchmark", False,
            "block on every eager op so profiler timings are real kernel "
            "times, not async dispatch times (ref flags.cc benchmark)")
define_flag("FLAGS_call_stack_level", 1,
            "error verbosity: >=2 attaches the Python build stack to "
            "executor errors (ref op_call_stack.cc role)")
define_flag("FLAGS_tpu_flash_attention", True,
            "allow nn.functional attention to route to the Pallas flash "
            "kernel when geometry supports it (TPU-specific)")
define_flag("FLAGS_tpu_matmul_precision", "",
            "jax default_matmul_precision override: one of '', 'default', "
            "'bfloat16', 'tensorfloat32', 'float32' (TPU-specific)",
            type=str, on_set=_set_matmul_precision)
define_flag("FLAGS_profiler_logdir", "/tmp/paddle_tpu_profile",
            "TensorBoard trace directory used by paddle_tpu.profiler")
define_flag("FLAGS_selected_tpus", "",
            "comma list of visible device indices (role of "
            "FLAGS_selected_gpus in launch_utils.py)", type=str)

# accepted-but-inert reference flags: the subsystem they tune is owned by
# XLA here (buffer assignment ≙ memory passes, async runtime ≙ executor
# knobs).  Kept so reference scripts' set_flags calls don't break.
for _name, _default in [
    ("FLAGS_allocator_strategy", "auto_growth"),
    ("FLAGS_eager_delete_tensor_gb", 0.0),
    ("FLAGS_fast_eager_deletion_mode", True),
    ("FLAGS_memory_fraction_of_eager_deletion", 1.0),
    ("FLAGS_fraction_of_gpu_memory_to_use", 0.92),
    ("FLAGS_initial_cpu_memory_in_mb", 500),
    ("FLAGS_init_allocated_mem", False),
    ("FLAGS_paddle_num_threads", 1),
    ("FLAGS_inner_op_parallelism", 0),
    ("FLAGS_cudnn_deterministic", False),
    ("FLAGS_cudnn_exhaustive_search", False),
    ("FLAGS_conv_workspace_size_limit", 512),
    ("FLAGS_sync_nccl_allreduce", True),
    ("FLAGS_fuse_parameter_groups_size", 3),
    ("FLAGS_fuse_parameter_memory_size", -1.0),
    ("FLAGS_check_kernel_launch", False),
    ("FLAGS_max_inplace_grad_add", 0),
    ("FLAGS_use_mkldnn", False),
    ("FLAGS_use_ngraph", False),
]:
    define_flag(_name, _default, "accepted for script compatibility; the "
                "underlying subsystem is owned by XLA on TPU", inert=True)
