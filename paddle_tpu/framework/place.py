"""Device places.

Parity target: ``/root/reference/paddle/fluid/platform/place.h`` (CPUPlace,
CUDAPlace, XPUPlace, NPUPlace, CUDAPinnedPlace) and the Python surface
``paddle.set_device`` (``/root/reference/python/paddle/device.py``).

TPU-first design: a "place" maps to a jax backend + device index.  The
framework's north star is ``paddle.set_device('tpu')`` as the only user-facing
change, so ``TPUPlace`` is first-class and ``CUDAPlace`` is accepted as an
alias that resolves to whatever accelerator jax exposes.
"""

from __future__ import annotations

import os
import threading


class Place:
    _backend = "cpu"

    def __init__(self, device_id: int = 0):
        self._device_id = int(device_id)

    def get_device_id(self) -> int:
        return self._device_id

    @property
    def backend(self) -> str:
        return self._backend

    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self._backend == other._backend
            and self._device_id == other._device_id
        )

    def __hash__(self):
        return hash((self._backend, self._device_id))

    def __repr__(self):
        return f"{type(self).__name__}({self._device_id})"

    def jax_device(self):
        import jax

        devs = jax.devices() if self._backend != "cpu" else jax.devices("cpu")
        return devs[self._device_id % len(devs)]


class CPUPlace(Place):
    _backend = "cpu"

    def __init__(self):
        super().__init__(0)


class TPUPlace(Place):
    _backend = "tpu"


class CUDAPlace(Place):
    """Accepted for API parity; resolves to the default accelerator."""

    _backend = "accel"


class CUDAPinnedPlace(CPUPlace):
    pass


class XPUPlace(TPUPlace):
    pass


class NPUPlace(TPUPlace):
    """Accepted for API parity (the fork's Ascend place); resolves to the
    default accelerator like CUDAPlace."""


_state = threading.local()


def _default_device_str() -> str:
    env = os.environ.get("PADDLE_TPU_DEVICE")
    if env:
        return env
    try:
        import jax

        plat = jax.default_backend()
    except Exception:
        return "cpu"
    if plat in ("tpu", "axon"):
        return "tpu"
    if plat in ("gpu", "cuda", "rocm"):
        return "gpu"
    return "cpu"


def set_device(device: str):
    """``paddle.set_device('tpu')`` / ``('cpu')`` / ``('tpu:0')``."""
    device = device.lower()
    if ":" in device:
        kind, idx = device.split(":", 1)
        idx = int(idx)
    else:
        kind, idx = device, 0
    if kind in ("tpu", "gpu", "cuda", "xpu", "npu", "accel"):
        place = TPUPlace(idx)
    elif kind == "cpu":
        place = CPUPlace()
    else:
        raise ValueError(f"Unknown device {device!r}")
    _state.place = place
    return place


def get_device() -> str:
    p = _get_current_place()
    if isinstance(p, CPUPlace):
        return "cpu"
    return f"tpu:{p.get_device_id()}"


def _get_current_place() -> Place:
    place = getattr(_state, "place", None)
    if place is None:
        kind = _default_device_str()
        place = CPUPlace() if kind == "cpu" else TPUPlace(0)
        _state.place = place
    return place


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    try:
        import jax

        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False
