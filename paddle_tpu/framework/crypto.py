"""Model encryption (AES) — the reference's crypto IO.

Parity: ``/root/reference/paddle/fluid/framework/io/crypto/``
(``Cipher::Encrypt/Decrypt/EncryptToFile/DecryptFromFile`` cipher.h:24,
``CipherUtils::GenKey/GenKeyToFile`` cipher_utils.h:24, AES-GCM cipher) —
used to ship encrypted inference models.  Implemented over the
``cryptography`` package (AESGCM with a random 12-byte nonce prepended to
the ciphertext).
"""

from __future__ import annotations

import os

__all__ = ["Cipher", "CipherFactory", "CipherUtils", "is_available"]


def is_available() -> bool:
    """True when the optional ``cryptography`` package is importable.
    Key generation works without it; encrypt/decrypt do not."""
    try:
        import cryptography  # noqa: F401

        return True
    except ImportError:
        return False


def _aesgcm_cls():
    """Import AESGCM at USE-time with an actionable error, so merely
    importing this module (or collecting its tests) never requires the
    optional dependency in minimal environments."""
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ImportError as e:
        raise ImportError(
            "paddle_tpu.framework.crypto needs the optional 'cryptography' "
            "package for AES-GCM encrypt/decrypt; install it with "
            "`pip install cryptography` (key generation alone does not "
            "require it)") from e
    return AESGCM


class Cipher:
    """AES-GCM cipher (reference default: AES-256-GCM)."""

    _NONCE = 12

    def _aes(self, key: bytes):
        AESGCM = _aesgcm_cls()

        if len(key) not in (16, 24, 32):
            raise ValueError(
                f"AES key must be 16/24/32 bytes, got {len(key)}")
        return AESGCM(key)

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        nonce = os.urandom(self._NONCE)
        return nonce + self._aes(key).encrypt(nonce, bytes(plaintext), None)

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        nonce, body = ciphertext[:self._NONCE], ciphertext[self._NONCE:]
        return self._aes(key).decrypt(nonce, body, None)

    def encrypt_to_file(self, plaintext: bytes, key: bytes, filename: str):
        with open(filename, "wb") as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key: bytes, filename: str) -> bytes:
        with open(filename, "rb") as f:
            return self.decrypt(f.read(), key)

    # reference C++ casing
    Encrypt = encrypt
    Decrypt = decrypt
    EncryptToFile = encrypt_to_file
    DecryptFromFile = decrypt_from_file


class CipherFactory:
    @staticmethod
    def create_cipher(config_fname: str = "") -> Cipher:
        """Only the AES-GCM default cipher is implemented; a config
        selecting another cipher must raise, not silently differ."""
        if config_fname:
            import os as _os

            if not _os.path.exists(config_fname):
                raise FileNotFoundError(config_fname)
            cfg = open(config_fname).read().lower()
            if "gcm" not in cfg:
                raise NotImplementedError(
                    f"cipher config {config_fname!r} selects a non-GCM "
                    f"cipher; only AES-GCM is implemented")
        return Cipher()

    CreateCipher = create_cipher


class CipherUtils:
    @staticmethod
    def gen_key(length: int = 256) -> bytes:
        """``length`` in BITS (reference GenKey semantics)."""
        if length % 8:
            raise ValueError("key length must be a multiple of 8 bits")
        return os.urandom(length // 8)

    @staticmethod
    def gen_key_to_file(length: int, filename: str) -> bytes:
        key = CipherUtils.gen_key(length)
        with open(filename, "wb") as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(filename: str) -> bytes:
        with open(filename, "rb") as f:
            return f.read()

    GenKey = gen_key
    GenKeyToFile = gen_key_to_file
    ReadKeyFromFile = read_key_from_file
