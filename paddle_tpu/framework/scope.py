"""Scope: name -> runtime value (jax.Array) store.

Parity: ``/root/reference/paddle/fluid/framework/scope.h:52`` (hierarchical
``Scope::NewScope/FindVar``).  Values are jax Arrays (device-resident); the
executor reads persistables out of the scope, threads them through the jitted
step function, and rebinds the results — the functional replacement for the
reference's mutable ``Variable::GetMutable<LoDTensor>()``.
"""

from __future__ import annotations

from typing import Dict, Optional


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, object] = {}
        self._parent = parent
        self._kids = []

    def new_scope(self) -> "Scope":
        kid = Scope(self)
        self._kids.append(kid)
        return kid

    def var(self, name: str):
        """Find or create (returns None placeholder until set)."""
        if name not in self._vars and (self._parent is None or not self._parent.has(name)):
            self._vars[name] = None
        return self.find_var(name)

    def set(self, name: str, value) -> None:
        self._vars[name] = value

    def find_var(self, name: str):
        if name in self._vars:
            return self._vars[name]
        if self._parent is not None:
            return self._parent.find_var(name)
        return None

    def has(self, name: str) -> bool:
        if name in self._vars:
            return True
        return self._parent.has(name) if self._parent is not None else False

    def local_names(self):
        return list(self._vars)

    def drop_kids(self):
        self._kids.clear()

    def erase(self, name: str) -> None:
        self._vars.pop(name, None)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        global _global_scope
        old = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = old

    return guard()
