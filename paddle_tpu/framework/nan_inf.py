"""NaN/Inf numerical sanitizer (``FLAGS_check_nan_inf``).

Role parity: ``/root/reference/paddle/fluid/framework/details/
nan_inf_utils_detail.{cc,cu}`` + the enforce hook at ``operator.cc:1040-1047``
— with the flag set, every op's outputs are scanned and the first offending
op aborts the run with its name.

TPU-native shape: inside a jitted program we cannot raise from device code,
so the static Executor threads a per-op ``all-finite`` bool vector out of the
compiled step and raises host-side naming the first bad op; the eager tracer
checks after each kernel (a host sync per op — debug-flag cost, exactly like
the reference's device-to-host copy in CheckVarHasNanOrInf).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def _float_arrays(outs):
    for slot, vals in outs.items():
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for i, v in enumerate(vals):
            if v is not None and jnp.issubdtype(jnp.asarray(v).dtype, jnp.inexact):
                yield slot, i, v


def op_all_finite(outs) -> jnp.ndarray:
    """Traced scalar bool: every inexact output of this op is finite."""
    ok = jnp.asarray(True)
    for _, _, v in _float_arrays(outs):
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(v)))
    return ok


def assert_all_finite_eager(op_type: str, outs) -> None:
    """Eager-mode check: host-syncs and raises on the first non-finite output.

    Ops traced inside jit/shard_map/grad (functional train steps, the
    pipeline engine) are skipped — a tracer can't be host-synced; traced
    steps use :func:`op_all_finite` + :func:`raise_first_bad_op` instead."""
    import jax

    for slot, i, v in _float_arrays(outs):
        if isinstance(v, jax.core.Tracer):
            continue
        a = np.asarray(v)
        if not np.isfinite(a).all():
            n_nan = int(np.isnan(a).sum())
            n_inf = int(np.isinf(a).sum())
            raise RuntimeError(
                f"FLAGS_check_nan_inf: op {op_type!r} output "
                f"{slot}[{i}] (shape {a.shape}, dtype {a.dtype}) contains "
                f"{n_nan} NaN and {n_inf} Inf values")


def raise_first_bad_op(ok_vector, op_labels) -> None:
    """Host-side: raise naming the first op whose finite-check failed."""
    oks = np.asarray(ok_vector)
    if oks.all():
        return
    idx = int(np.argmin(oks))  # first False
    raise RuntimeError(
        f"FLAGS_check_nan_inf: op #{idx} {op_labels[idx]} produced NaN/Inf "
        f"({int((~oks.astype(bool)).sum())} op(s) non-finite in this step)")
