"""Global RNG for dygraph mode — explicit JAX PRNG key chain.

Parity role: the reference's global Generator + ``paddle.seed``
(`/root/reference/python/paddle/fluid/framework.py` seed plumbing, CUDA
generator state).  TPU-first: a split-chain of PRNG keys (stateless under
jit; the static Executor threads its own fold_in(seed, step) keys instead).
"""

from __future__ import annotations

import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0


def seed(s: int):
    """Parity: ``paddle.seed`` — reseeds the dygraph RNG chain and the
    default static programs' random_seed."""
    global _DEFAULT_SEED
    _DEFAULT_SEED = int(s)
    _state.key = jax.random.PRNGKey(int(s))
    from . import program as fw

    fw.default_main_program().random_seed = int(s)
    fw.default_startup_program().random_seed = int(s)
    return _state.key


def next_rng_key():
    key = getattr(_state, "key", None)
    if key is None:
        key = jax.random.PRNGKey(_DEFAULT_SEED)
    key, sub = jax.random.split(key)
    _state.key = key
    return sub


def get_rng_state():
    return getattr(_state, "key", jax.random.PRNGKey(_DEFAULT_SEED))


def set_rng_state(key):
    _state.key = key
