"""Global RNG for dygraph mode — explicit JAX PRNG key chain.

Parity role: the reference's global Generator + ``paddle.seed``
(`/root/reference/python/paddle/fluid/framework.py` seed plumbing, CUDA
generator state).  TPU-first: a split-chain of PRNG keys (stateless under
jit; the static Executor threads its own fold_in(seed, step) keys instead).
"""

from __future__ import annotations

import threading

import jax

_state = threading.local()
_DEFAULT_SEED = 0


def seed(s: int):
    """Parity: ``paddle.seed`` — reseeds the dygraph RNG chain and the
    default static programs' random_seed."""
    global _DEFAULT_SEED
    _DEFAULT_SEED = int(s)
    _state.key = jax.random.PRNGKey(int(s))
    from . import program as fw

    fw.default_main_program().random_seed = int(s)
    fw.default_startup_program().random_seed = int(s)
    return _state.key


class trace_rng_scope:
    """Thread a TRACED key through ops dispatched inside a jitted function.

    Functional train steps (pipeline engine, custom jit wrappers) pass a
    fresh per-step key as a jit argument and install it here around tracing;
    rng consumers (dropout etc.) then draw traced subkeys from it, so every
    executed step gets fresh randomness.  Without a scope, trace-time rng
    draws fall back to baking a concrete key into the compiled program
    (identical masks every step — fine only for deterministic eval)."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        self._prev = getattr(_state, "trace_key", None)
        _state.trace_key = self._key
        return self

    def __exit__(self, *exc):
        _state.trace_key = self._prev
        return False


def next_rng_key():
    tk = getattr(_state, "trace_key", None)
    if tk is not None:
        tk, sub = jax.random.split(tk)
        _state.trace_key = tk
        return sub
    key = getattr(_state, "key", None)
    if key is None:
        key = jax.random.PRNGKey(_DEFAULT_SEED)
    if isinstance(key, jax.core.Tracer):
        # A pre-fix trace leaked a tracer into the chain; re-anchor. (The
        # eval below keeps the chain concrete so this should not recur.)
        key = jax.random.PRNGKey(_DEFAULT_SEED)
    # The split must stay CONCRETE even when an op is being traced (jit /
    # shard_map stage all binds, including ones on concrete inputs): storing
    # a tracer into _state.key would poison every later eager op with a
    # leaked tracer carrying the old trace's mesh context.  Trace-time rng
    # consumers thus get a constant key baked into the compiled program —
    # jitted training paths that need fresh per-step randomness thread their
    # own keys (static executor: fold_in(seed, step)).
    with jax.ensure_compile_time_eval():
        key, sub = jax.random.split(key)
    _state.key = key
    return sub


def get_rng_state():
    return getattr(_state, "key", jax.random.PRNGKey(_DEFAULT_SEED))


def set_rng_state(key):
    _state.key = key
