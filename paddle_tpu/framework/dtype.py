"""Dtype registry for the TPU-native framework.

Capability parity with the reference's ``VarType.Type`` proto enum
(``/root/reference/paddle/fluid/framework/framework.proto:106``) and the
Python-side dtype conversion helpers
(``/root/reference/python/paddle/fluid/data_feeder.py`` convert_dtype).

TPU-first notes: the canonical training dtype on TPU is bfloat16 (MXU-native);
float16 is accepted for API parity but bf16 is preferred by AMP.
"""

from __future__ import annotations

import numpy as np

try:
    import jax.numpy as jnp

    _HAS_JAX = True
except Exception:  # pragma: no cover
    _HAS_JAX = False


class DataType:
    """Mirrors VarType.Type values that matter for tensors."""

    BOOL = "bool"
    INT8 = "int8"
    UINT8 = "uint8"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    FP16 = "float16"
    BF16 = "bfloat16"
    FP32 = "float32"
    FP64 = "float64"
    COMPLEX64 = "complex64"
    COMPLEX128 = "complex128"


# Public aliases mirroring ``paddle.float32`` etc.
bool = "bool"  # noqa: A001
int8 = "int8"
uint8 = "uint8"
int16 = "int16"
int32 = "int32"
int64 = "int64"
float16 = "float16"
bfloat16 = "bfloat16"
float32 = "float32"
float64 = "float64"
complex64 = "complex64"
complex128 = "complex128"

_ALL_DTYPES = {
    "bool",
    "int8",
    "uint8",
    "int16",
    "int32",
    "int64",
    "float16",
    "bfloat16",
    "float32",
    "float64",
    "complex64",
    "complex128",
}

_FLOAT_DTYPES = {"float16", "bfloat16", "float32", "float64"}
_INT_DTYPES = {"bool", "int8", "uint8", "int16", "int32", "int64"}


def convert_dtype(dtype) -> str:
    """Normalise any dtype spec (str, numpy dtype, jnp dtype) to canonical str.

    Parity: ``convert_dtype`` in the reference's data_feeder.
    """
    if dtype is None:
        return "float32"
    if isinstance(dtype, str):
        name = dtype
    elif isinstance(dtype, np.dtype):
        name = dtype.name
    elif isinstance(dtype, type) and issubclass(dtype, np.generic):
        name = np.dtype(dtype).name
    else:
        # jnp dtypes / python types
        name = np.dtype(dtype).name if not hasattr(dtype, "name") else dtype.name
    if name == "float":
        name = "float32"
    if name == "int":
        name = "int64"
    if name not in _ALL_DTYPES:
        raise TypeError(f"Unsupported dtype: {dtype!r} -> {name}")
    return name


def to_numpy_dtype(dtype) -> np.dtype:
    name = convert_dtype(dtype)
    if name == "bfloat16":
        if _HAS_JAX:
            return jnp.bfloat16
        raise TypeError("bfloat16 requires jax")
    return np.dtype(name)


def to_jax_dtype(dtype):
    name = convert_dtype(dtype)
    return jnp.dtype(name) if name != "bfloat16" else jnp.bfloat16


def is_floating(dtype) -> bool:
    return convert_dtype(dtype) in _FLOAT_DTYPES


def is_integer(dtype) -> bool:
    return convert_dtype(dtype) in _INT_DTYPES


_DEFAULT_DTYPE = "float32"


def set_default_dtype(d):
    """Parity: paddle.set_default_dtype (float types only, like the
    reference's framework.set_default_dtype)."""
    global _DEFAULT_DTYPE
    d = convert_dtype(d)
    if d not in ("float16", "bfloat16", "float32", "float64"):
        raise TypeError(
            f"default dtype must be a float type, got {d!r}")
    _DEFAULT_DTYPE = d


def get_default_dtype() -> str:
    return _DEFAULT_DTYPE
