"""``paddle.fluid.dygraph`` — v2.1-era imperative API.

Parity: ``/root/reference/python/paddle/fluid/dygraph/`` (guard,
to_variable, Layer, the ``dygraph.nn`` layer classes with their
``act=...`` constructor argument, no_grad, TracedLayer).
"""

from __future__ import annotations

import contextlib

import numpy as np

from ...dygraph.tensor import Tensor
from ...framework import program as fw
from ...nn import functional as _F
from ...nn.layer_base import Layer, Sequential  # noqa: F401
from ... import nn as _nn

__all__ = [
    "guard", "to_variable", "no_grad", "grad", "enabled", "Layer",
    "Sequential", "Linear", "Conv2D", "Conv2DTranspose", "Pool2D",
    "BatchNorm", "Embedding", "LayerNorm", "GroupNorm", "SpectralNorm",
    "Dropout", "LayerList", "ParameterList", "PRelu", "NCE", "BilinearTensorProduct",
    "TracedLayer", "ProgramTranslator", "declarative", "jit",
]


@contextlib.contextmanager
def guard(place=None):
    """v2.1 pattern: ``with fluid.dygraph.guard(): ...`` — dygraph mode."""
    was_static = not fw.in_dygraph_mode()
    fw.disable_static()
    try:
        yield
    finally:
        if was_static:
            fw.enable_static()


def enabled() -> bool:
    return fw.in_dygraph_mode()


def to_variable(value, name=None, zero_copy=None, dtype=None):
    arr = np.asarray(value)
    if dtype is not None:
        arr = arr.astype(dtype)
    return Tensor(arr, stop_gradient=True)


from ...dygraph import no_grad  # noqa: F401,E402
from ...autograd import grad  # noqa: F401,E402


def _act_wrap(out, act):
    return getattr(_F, act)(out) if act else out


class Linear(Layer):
    """fluid.dygraph.Linear(input_dim, output_dim, param_attr, bias_attr,
    act, dtype) — 2.x nn.Linear plus the fused ``act``."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__()
        self._linear = _nn.Linear(input_dim, output_dim,
                                  weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    @property
    def weight(self):
        return self._linear.weight

    @property
    def bias(self):
        return self._linear.bias

    def forward(self, x):
        return _act_wrap(self._linear(x), self._act)


class Conv2D(Layer):
    """fluid.dygraph.Conv2D(num_channels, num_filters, filter_size, ...)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__()
        self._conv = _nn.Conv2D(num_channels, num_filters, filter_size,
                                stride=stride, padding=padding,
                                dilation=dilation, groups=groups,
                                weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    @property
    def weight(self):
        return self._conv.weight

    def forward(self, x):
        return _act_wrap(self._conv(x), self._act)


class Conv2DTranspose(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32",
                 output_size=None):
        super().__init__()
        self._conv = _nn.Conv2DTranspose(
            num_channels, num_filters, filter_size, stride=stride,
            padding=padding, dilation=dilation, groups=groups,
            weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        return _act_wrap(self._conv(x), self._act)


class Pool2D(Layer):
    """fluid.dygraph.Pool2D(pool_size, pool_type, pool_stride, ...)."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format="NCHW"):
        super().__init__()
        self._kw = dict(pool_size=pool_size, pool_type=pool_type,
                        pool_stride=pool_stride, pool_padding=pool_padding,
                        global_pooling=global_pooling, ceil_mode=ceil_mode,
                        exclusive=exclusive, data_format=data_format)

    def forward(self, x):
        from ..layers import pool2d

        return pool2d(x, **self._kw)


class BatchNorm(Layer):
    """fluid.dygraph.BatchNorm(num_channels, act=..., ...)."""

    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", in_place=False,
                 moving_mean_name=None, moving_variance_name=None,
                 do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__()
        self._bn = _nn.BatchNorm2D(
            num_channels, momentum=momentum, epsilon=epsilon,
            weight_attr=param_attr, bias_attr=bias_attr,
            data_format=data_layout, use_global_stats=use_global_stats)
        self._act = act

    def forward(self, x):
        return _act_wrap(self._bn(x), self._act)


class Embedding(Layer):
    """fluid.dygraph.Embedding(size=[vocab, dim], ...)."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__()
        self._emb = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                                  sparse=is_sparse, weight_attr=param_attr)

    @property
    def weight(self):
        return self._emb.weight

    def forward(self, x):
        return self._emb(x)


class LayerNorm(Layer):
    def __init__(self, normalized_shape, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__()
        self._ln = _nn.LayerNorm(normalized_shape, epsilon=epsilon,
                                 weight_attr=param_attr if scale else False,
                                 bias_attr=bias_attr if shift else False)
        self._act = act

    def forward(self, x):
        return _act_wrap(self._ln(x), self._act)


class GroupNorm(Layer):
    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, data_layout="NCHW"):
        super().__init__()
        self._gn = _nn.GroupNorm(groups, channels, epsilon=epsilon,
                                 weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x):
        return _act_wrap(self._gn(x), self._act)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__()
        raise NotImplementedError(
            "fluid.dygraph.SpectralNorm: use paddle.nn.utils.spectral_norm "
            "on the owning layer instead")


class Dropout(Layer):
    def __init__(self, p=0.5, seed=None, dropout_implementation=
                 "downgrade_in_infer", is_test=False):
        super().__init__()
        self._p = p
        self._mode = dropout_implementation

    def forward(self, x):
        return _F.dropout(x, p=self._p, training=self.training,
                          mode=self._mode)


class PRelu(Layer):
    def __init__(self, mode="all", channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__()
        num = 1 if mode == "all" else (channel if mode == "channel" else
                                       int(np.prod(input_shape)))
        self._prelu = _nn.PReLU(num_parameters=num, weight_attr=param_attr)

    def forward(self, x):
        return self._prelu(x)


LayerList = _nn.LayerList
ParameterList = _nn.ParameterList


class NCE(Layer):
    def __init__(self, *a, **k):
        super().__init__()
        raise NotImplementedError(
            "fluid.dygraph.NCE is a PS-era sampled-softmax layer; compute "
            "sampled softmax with paddle ops or full softmax_with_cross_entropy")


class BilinearTensorProduct(Layer):
    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 act=None, param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__()
        self._b = _nn.Bilinear(input1_dim, input2_dim, output_dim,
                               weight_attr=param_attr, bias_attr=bias_attr)
        self._act = act

    def forward(self, x, y):
        return _act_wrap(self._b(x, y), self._act)


# -- jit bridge --------------------------------------------------------------
from ... import jit  # noqa: E402

declarative = jit.to_static


class ProgramTranslator:
    """Parity: dygraph_to_static ProgramTranslator singleton surface."""

    _instance = None
    _enabled = True

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, flag: bool):
        type(self)._enabled = bool(flag)

    def enable_to_static(self, flag: bool):
        self.enable(flag)


def _traced_layer_unavailable(*a, **k):
    raise NotImplementedError(
        "fluid.dygraph.TracedLayer: use paddle.jit.save / paddle.jit.load "
        "(the StaticFunction trace covers its role)")


TracedLayer = _traced_layer_unavailable
