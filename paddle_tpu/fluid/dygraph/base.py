"""``paddle.fluid.dygraph.base`` module alias (guard/to_variable/
enabled/no_grad live here in the reference).

Parity: ``/root/reference/python/paddle/fluid/dygraph/base.py``.
"""

from . import enabled, guard, no_grad, to_variable  # noqa: F401
