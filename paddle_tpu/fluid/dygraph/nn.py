"""``paddle.fluid.dygraph.nn`` module alias — v2.1 scripts import the
layer classes from here (``from paddle.fluid.dygraph.nn import Linear``).

Parity: ``/root/reference/python/paddle/fluid/dygraph/nn.py``.
"""

from . import (  # noqa: F401
    BatchNorm, BilinearTensorProduct, Conv2D, Conv2DTranspose, Dropout,
    Embedding, GroupNorm, LayerNorm, Linear, NCE, Pool2D, PRelu,
    SpectralNorm,
)
