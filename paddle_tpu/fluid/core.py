"""``paddle.fluid.core`` shim — the pybind module's commonly-touched names.

Parity role: ``/root/reference/python/paddle/fluid/core.py`` (loads the
C++ pybind .so).  User code mostly touches places, device counts, and a
few feature probes; those are mapped here.  Anything else raises with
guidance instead of AttributeError.
"""

from __future__ import annotations

from ..framework.place import (  # noqa: F401
    CPUPlace, CUDAPinnedPlace, CUDAPlace, NPUPlace, Place, TPUPlace,
    XPUPlace, is_compiled_with_cuda, is_compiled_with_npu,
    is_compiled_with_xpu,
)


def get_cuda_device_count() -> int:
    return 0


def get_tpu_device_count() -> int:
    import jax

    try:
        return len([d for d in jax.devices() if d.platform == "tpu"])
    except Exception:
        return 0


def is_compiled_with_mkldnn() -> bool:
    return False


def is_compiled_with_brpc() -> bool:
    return False


def is_compiled_with_dist() -> bool:
    return True  # jax.distributed-backed collectives


class VarDesc:
    class VarType:
        FP16 = "float16"
        BF16 = "bfloat16"
        FP32 = "float32"
        FP64 = "float64"
        INT8 = "int8"
        INT16 = "int16"
        INT32 = "int32"
        INT64 = "int64"
        BOOL = "bool"
        UINT8 = "uint8"
        LOD_TENSOR = "lod_tensor"
        SELECTED_ROWS = "selected_rows"
        LOD_TENSOR_ARRAY = "lod_tensor_array"


def __getattr__(name):  # noqa: N807
    raise NotImplementedError(
        f"fluid.core.{name}: the C++ pybind internals are replaced by the "
        "XLA runtime in the TPU-native build; use the public paddle API "
        "for this capability.")
