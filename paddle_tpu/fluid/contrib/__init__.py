"""``paddle.fluid.contrib`` — the slim/quant + mixed-precision entries
v2.1 user code touches.

Parity: ``/root/reference/python/paddle/fluid/contrib/`` (slim.quantization
and mixed_precision are the surviving users; the rest was PS-era).
"""

from . import mixed_precision  # noqa: F401
from . import slim  # noqa: F401
