"""fluid.contrib.slim — quantization entries (QAT/PTQ).

Parity: ``/root/reference/python/paddle/fluid/contrib/slim/quantization``;
maps to the 2.x incubate.quant implementations.
"""

from . import quantization  # noqa: F401
