"""fluid.contrib.slim.quantization — ImperativeQuantAware / PTQ.

Parity: ``imperative/qat.py`` + ``imperative/ptq.py`` under the reference's
``fluid/contrib/slim/quantization``.
"""

from .....incubate.quant import (  # noqa: F401
    ImperativePTQ, ImperativeQuantAware,
)
