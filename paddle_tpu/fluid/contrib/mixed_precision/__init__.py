"""fluid.contrib.mixed_precision — AMP decorate/Config for v2.1 scripts.

Parity: ``/root/reference/python/paddle/fluid/contrib/mixed_precision/``
(decorate + CustomOpLists); maps onto the 2.x static AMP rewrite.
"""

from ....amp import GradScaler, auto_cast, decorate  # noqa: F401


class CustomOpLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        self.white_list = set(custom_white_list or [])
        self.black_list = set(custom_black_list or [])


AutoMixedPrecisionLists = CustomOpLists
