"""``paddle.fluid.metrics`` — python-side metric accumulators.

Parity: ``/root/reference/python/paddle/fluid/metrics.py`` (Accuracy,
Precision, Recall, Auc — the numpy accumulators fed with fetched values).
"""

from __future__ import annotations

import numpy as np

from ..metric import Accuracy as _Acc2, Auc as _Auc2  # noqa: F401


class MetricBase:
    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *a, **k):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    """fluid accumulator form: update(value=batch_acc, weight=batch_size)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        self.value += float(np.asarray(value).sum()) * weight
        self.weight += weight

    def eval(self):
        return self.value / max(self.weight, 1e-12)


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").reshape(-1)
        labels = np.asarray(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def eval(self):
        return self.tp / max(self.tp + self.fp, 1e-12)


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype("int32").reshape(-1)
        labels = np.asarray(labels).astype("int32").reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def eval(self):
        return self.tp / max(self.tp + self.fn, 1e-12)


class Auc(MetricBase):
    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._auc = _Auc2(curve=curve, num_thresholds=num_thresholds)

    def reset(self):
        self._auc.reset()

    def update(self, preds, labels):
        self._auc.update(np.asarray(preds), np.asarray(labels))

    def eval(self):
        return self._auc.accumulate()
