"""``paddle.fluid.layers`` — the v2.1-era layer-builder surface.

Parity: ``/root/reference/python/paddle/fluid/layers/`` (nn.py 150 public
functions + control_flow/tensor/loss/sequence_lod/learning_rate_scheduler/
detection/metric_op/io/rnn/distributions — 308 unique names).  Pre-2.x user
code writes ``import paddle.fluid as fluid; fluid.layers.fc(...)``; this
package maps every name onto the 2.x TPU implementations (static.nn
builders, tensor_api, nn.functional, vision.ops) so that code runs
unmodified.  Genuinely parameter-server-era or long-deprecated names raise
an informative error naming the modern replacement.

Semantic note on LR schedules: the reference's ``learning_rate_scheduler``
functions emit LR *graph ops*; here they return the matching 2.x
``optimizer.lr`` scheduler object, which every optimizer accepts — the
training-visible behavior (LR value per step) is identical.
"""

from __future__ import annotations

import numpy as np

from ... import tensor_api as T
from ...framework import program as fw
from ...nn import functional as F
from ...ops.dispatch import dispatch, single
from ...static import nn as snn
from ...static.input import data as _static_data

# the full static.nn builder family (batch_norm, embedding, conv2d,
# sequence_*, cond/while_loop/case/switch_case, create_parameter, ...)
from ...static.nn import *  # noqa: F401,F403
from ...static.nn import __all__ as _snn_all


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """v2.1 keyword signature (input=/param_attr=/act=) over static.nn.fc
    (weight_attr=/activation= in 2.x)."""
    return snn.fc(input, size, num_flatten_dims=num_flatten_dims,
                  weight_attr=param_attr, bias_attr=bias_attr,
                  activation=act, name=name)

# tensor-array / control-flow extras
from ... import tensor_api as _T_arr
array_length = _T_arr.array_length
array_read = _T_arr.array_read
array_write = _T_arr.array_write
create_array = _T_arr.create_array


def _d(op, ins, attrs=None, slot="Out"):
    return single(dispatch(op, ins, attrs or {}), slot)


# ---------------------------------------------------------------------------
# io.py: fluid.layers.data (append_batch_size semantics)
# ---------------------------------------------------------------------------


def data(name, shape, append_batch_size=True, dtype="float32", lod_level=0,
         **kw):
    """v2.1 ``fluid.layers.data``: prepends the -1 batch dim — unless the
    caller already gave ANY variable (-1/None) dim, which the reference
    treats as "shape is complete" (fluid/layers/io.py:data)."""
    shape = [-1 if d is None else int(d) for d in shape]
    if append_batch_size and all(d >= 0 for d in shape):
        shape = [-1] + shape
    return _static_data(name, shape, dtype=dtype, lod_level=lod_level)


# ---------------------------------------------------------------------------
# tensor.py
# ---------------------------------------------------------------------------

def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    """fluid arg order is (shape, dtype, value) — 2.x full is (shape, value,
    dtype)."""
    r = T.full(shape, value, dtype=dtype)
    if out is not None:
        T.assign(r, out)
        return out
    return r


cast = T.cast
concat = T.concat
assign = T.assign
argmax = T.argmax
argmin = T.argmin
argsort = T.argsort
zeros = T.zeros
ones = T.ones
zeros_like = T.zeros_like
ones_like = T.ones_like
linspace = T.linspace
diag = T.diag
eye = T.eye
reverse = T.flip
isfinite = T.isfinite
has_inf = lambda x: T.any(T.isinf(x))  # noqa: E731
has_nan = lambda x: T.any(T.isnan(x))  # noqa: E731


def create_tensor(dtype, name=None, persistable=False):
    blk = fw.default_main_program().current_block()
    from ...framework import unique_name

    return blk.create_var(name=name or unique_name.generate("create_tensor"),
                          dtype=dtype, shape=(), persistable=persistable)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ...static import create_global_var as _cgv

    return _cgv(shape, value, dtype, persistable=persistable, name=name)


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    shape = list(shape)
    shape[output_dim_idx] = T.shape(input)[input_dim_idx]
    return T.full(shape, value, dtype=dtype)


def tensor_array_to_tensor(input, axis=1, use_stack=False):
    import builtins

    items = ([array_read(input, i) for i in builtins.range(len(input))]
             if isinstance(input, list) else list(input))
    out = (T.stack(items, axis=axis) if use_stack
           else T.concat(items, axis=axis))
    return out, T.shape(out)


def range(start, end, step, dtype):  # noqa: A001 — reference name
    return T.arange(start, end, step, dtype=dtype)


def sums(input, out=None):
    r = T.add_n(input)
    if out is not None:
        T.assign(r, out)
        return out
    return r


# ---------------------------------------------------------------------------
# nn.py: activations / elementwise / reductions / shape ops
# ---------------------------------------------------------------------------

relu = F.relu
relu6 = F.relu6
elu = F.elu
selu = F.selu
prelu = snn.prelu
leaky_relu = F.leaky_relu
softmax = F.softmax
log = T.log
pow = T.pow  # noqa: A001
sign = T.sign
sqrt = T.sqrt
abs = T.abs  # noqa: A001
square = T.square
exp = T.exp
floor = T.floor
ceil = T.ceil
round = T.round  # noqa: A001
sin = T.sin
cos = T.cos
tanh = T.tanh
sigmoid = F.sigmoid
swish = F.swish
mish = F.mish
hard_swish = F.hardswish
hard_sigmoid = F.hardsigmoid
maxout = F.maxout
stanh = T.stanh if hasattr(T, "stanh") else None
logsigmoid = F.log_sigmoid
softplus = F.softplus
softsign = F.softsign
softshrink = F.softshrink
hard_shrink = F.hardshrink
thresholded_relu = F.thresholded_relu
erf = T.erf


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return T.clip(x, t_min, t_max)


def soft_relu(x, threshold=40.0, name=None):
    return T.log(1 + T.exp(T.clip(x, -threshold, threshold)))


def _reduce(fn):
    def wrapper(input, dim=None, keep_dim=False, name=None):
        return fn(input, axis=dim, keepdim=keep_dim)

    return wrapper


reduce_sum = _reduce(T.sum)
reduce_mean = _reduce(T.mean)
reduce_max = _reduce(T.max)
reduce_min = _reduce(T.min)
reduce_prod = _reduce(T.prod)
reduce_all = _reduce(T.all)
reduce_any = _reduce(T.any)


def _elementwise(op):
    def wrapper(x, y, axis=-1, act=None, name=None):
        out = _d(op, {"X": [x], "Y": [y]}, {"axis": axis})
        if act:
            out = getattr(F, act)(out)
        return out

    return wrapper


elementwise_add = _elementwise("elementwise_add")
elementwise_sub = _elementwise("elementwise_sub")
elementwise_mul = _elementwise("elementwise_mul")
elementwise_div = _elementwise("elementwise_div")
elementwise_max = _elementwise("elementwise_max")
elementwise_min = _elementwise("elementwise_min")
elementwise_pow = _elementwise("elementwise_pow")
elementwise_mod = _elementwise("elementwise_mod")
elementwise_floordiv = _elementwise("elementwise_floordiv")


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    return _d("mul", {"X": [x], "Y": [y]},
              {"x_num_col_dims": x_num_col_dims,
               "y_num_col_dims": y_num_col_dims})


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    out = T.matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if alpha != 1.0:
        out = T.scale(out, scale=alpha)
    return out


mean = T.mean
scale = T.scale
clip = T.clip
def clip_by_norm(x, max_norm, name=None):
    norm = T.sqrt(T.sum(T.square(x)))
    factor = T.minimum(T.full_like(norm, 1.0),
                       T.full_like(norm, float(max_norm)) /
                       T.maximum(norm, T.full_like(norm, 1e-12)))
    return x * factor
sum = T.add_n  # noqa: A001 — fluid.layers.sum adds a LIST of tensors
slice = T.slice  # noqa: A001
strided_slice = T.strided_slice
shape = T.shape
rank = T.rank
size = lambda x: T.numel(x)  # noqa: E731
logical_and = T.logical_and
logical_or = T.logical_or
logical_xor = T.logical_xor
logical_not = T.logical_not
equal = T.equal
not_equal = T.not_equal
less_than = T.less_than
less_equal = T.less_equal
greater_than = T.greater_than
greater_equal = T.greater_equal
reshape = T.reshape
squeeze = T.squeeze
unsqueeze = T.unsqueeze
transpose = T.transpose
split = T.split
stack = T.stack
unstack = T.unstack
unbind = T.unbind
expand = lambda x, expand_times, name=None: T.tile(x, expand_times)  # noqa: E731
expand_as = T.expand_as
gather = T.gather
gather_nd = T.gather_nd
scatter = T.scatter
scatter_nd = T.scatter_nd
scatter_nd_add = T.scatter_nd_add
where = T.nonzero  # fluid.layers.where(cond) = indices of True (nonzero)
topk = T.topk
unique = T.unique
flatten = F.flatten
one_hot = F.one_hot
label_smooth = F.label_smooth
l2_normalize = lambda x, axis, epsilon=1e-12, name=None: F.normalize(  # noqa: E731
    x, p=2, axis=axis, epsilon=epsilon)
pad = F.pad
unfold = F.unfold
pixel_shuffle = F.pixel_shuffle if hasattr(F, "pixel_shuffle") else None
dropout_impl = F.dropout


def dropout(x, dropout_prob, is_test=False, seed=None,
            name=None, dropout_implementation="downgrade_in_infer"):
    return F.dropout(x, p=dropout_prob, training=not is_test,
                     mode=dropout_implementation)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCHW"):
    if global_pooling:
        return _d("pool2d", {"X": [input]},
                  {"pooling_type": pool_type, "ksize": [1, 1],
                   "global_pooling": True, "data_format": data_format})
    fn = F.max_pool2d if pool_type == "max" else F.avg_pool2d
    kw = dict(kernel_size=pool_size, stride=pool_stride,
              padding=pool_padding, ceil_mode=ceil_mode,
              data_format=data_format)
    if pool_type != "max":
        kw["exclusive"] = exclusive
    return fn(input, **kw)


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if require_index:
        if pool_type != "max":
            raise ValueError("require_index needs pool_type='max'")
        return F.adaptive_max_pool2d(input, pool_size, return_mask=True)
    fn = (F.adaptive_max_pool2d if pool_type == "max"
          else F.adaptive_avg_pool2d)
    return fn(input, pool_size)


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", **kw):
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode=resample.lower())


def resize_bilinear(input, out_shape=None, scale=None, name=None, **kw):
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode="bilinear")


def resize_nearest(input, out_shape=None, scale=None, name=None, **kw):
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode="nearest")


def pad2d(input, paddings=(0, 0, 0, 0), mode="constant", pad_value=0.0,
          data_format="NCHW", name=None):
    return F.pad(input, list(paddings), mode=mode.replace("edge", "replicate"),
                 value=pad_value, data_format=data_format)


def crop_tensor(x, shape=None, offsets=None, name=None):
    return T.crop(x, shape=shape, offsets=offsets)


crop = crop_tensor
lrn = F.local_response_norm if hasattr(F, "local_response_norm") else None


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,  # noqa: A002
                   name=None):
    return T.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    return T.standard_normal(shape, dtype=dtype) * std + mean


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):  # noqa: A002
    shape = list(shape)
    shape[output_dim_idx] = T.shape(input)[input_dim_idx]
    return T.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    shape = list(shape)
    shape[output_dim_idx] = T.shape(input)[input_dim_idx]
    return T.standard_normal(shape, dtype=dtype) * std + mean


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    from ...static import create_global_var as _cgv

    counter = _cgv([1], begin - step, "int64", persistable=True,
                   name=counter_name or "@STEP_COUNTER@")
    return increment(counter, value=step, in_place=True)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    """v2.1 smooth_l1_loss op semantics (smooth_l1_loss_op.cc): the diff is
    scaled by sigma^2 inside the huber branch point, weights multiply the
    diff (inside) / the loss (outside), and the loss is SUMMED over every
    non-batch dim — output shape [N, 1]."""
    sigma2 = float(sigma if sigma is not None else 1.0) ** 2
    diff = T.subtract(x, y)
    if inside_weight is not None:
        diff = T.multiply(diff, inside_weight)
    ad = T.abs(diff)
    inv = 1.0 / sigma2
    quad = T.scale(T.multiply(diff, diff), 0.5 * sigma2)
    lin = T.subtract(ad, T.full_like(ad, 0.5 * inv))
    loss = T.where(T.less_than(ad, T.full_like(ad, inv)), quad, lin)
    if outside_weight is not None:
        loss = T.multiply(loss, outside_weight)
    n = loss.shape[0]
    return T.sum(T.reshape(loss, [n, -1]), axis=1, keepdim=True)


# ---------------------------------------------------------------------------
# loss.py
# ---------------------------------------------------------------------------


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    """fluid semantics: ``input`` is post-softmax PROBABILITIES."""
    return _d("cross_entropy", {"X": [input], "Label": [label]},
              {"soft_label": soft_label, "ignore_index": ignore_index},
              slot="Y")


softmax_with_cross_entropy = F.softmax_with_cross_entropy
square_error_cost = F.square_error_cost


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, name=None,
                                      normalize=False):
    """v2.1 op semantics (sigmoid_cross_entropy_with_logits_op.cc):
    elementwise BCE-with-logits, positions where ``label == ignore_index``
    contribute 0, and ``normalize=True`` divides by the count of
    non-ignored elements (not the total)."""
    loss = F.binary_cross_entropy_with_logits(x, label, reduction="none")
    keep = T.cast(T.not_equal(label, T.full_like(label, ignore_index)),
                  loss.dtype)
    loss = T.multiply(loss, keep)
    if normalize:
        total = T.sum(keep)
        loss = T.divide(loss, T.maximum(total, T.full_like(total, 1.0)))
    return loss
log_loss = F.log_loss if hasattr(F, "log_loss") else None
mse_loss = F.mse_loss
kldiv_loss = F.kl_div
nce = snn.nce if hasattr(snn, "nce") else None
npair_loss = None
margin_rank_loss = (
    lambda label, left, right, margin=0.1, name=None:
    F.margin_ranking_loss(left, right, label, margin=margin,
                          reduction="none"))
huber_loss = (lambda input, label, delta:
              F.smooth_l1_loss(input, label, reduction="none", delta=delta))


def dice_loss(input, label, epsilon=1e-5):
    label = T.cast(label, input.dtype)
    label = T.squeeze(label, [-1]) if label.shape[-1] == 1 else label
    label = F.one_hot(T.cast(label, "int64"), input.shape[-1])
    reduce_dim = list(np.arange(1, len(input.shape)))
    inse = T.sum(input * label, axis=reduce_dim)
    dice_denominator = T.sum(input, axis=reduce_dim) + T.sum(
        label, axis=reduce_dim)
    dice_score = 1 - inse * 2 / (dice_denominator + epsilon)
    return T.mean(dice_score)


# ---------------------------------------------------------------------------
# metric_op.py
# ---------------------------------------------------------------------------

from ...static import accuracy, auc  # noqa: F401,E402


# ---------------------------------------------------------------------------
# control_flow.py extras (cond/while_loop/case/switch_case come from
# static.nn; the imperative builders live in static.control_flow)
# ---------------------------------------------------------------------------


def increment(x, value=1.0, in_place=True):
    out = T.add(x, T.full_like(x, value))
    if in_place:
        T.assign(out, x)
        return x
    return out


def is_empty(x, name=None):
    return T.equal(T.numel(x), T.full([], 0, "int64"))


class Print:  # noqa: N801 — reference exports Print here too
    def __new__(cls, input, **kw):
        from ...static import Print as _p

        return _p(input, **kw)


# ---------------------------------------------------------------------------
# learning_rate_scheduler.py — return 2.x scheduler objects
# ---------------------------------------------------------------------------


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    from ...optimizer import lr

    return lr.NoamDecay(d_model, warmup_steps, learning_rate=learning_rate)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    from ...optimizer import lr

    # per-step gamma so value(step) matches the reference's graph formula
    class _Exp(lr.LRScheduler):
        def get_lr(self):
            e = self.last_epoch / decay_steps
            if staircase:
                e = int(e)
            return self.base_lr * decay_rate ** e

    return _Exp(learning_rate=learning_rate)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    import math

    from ...optimizer import lr

    class _NatExp(lr.LRScheduler):
        def get_lr(self):
            e = self.last_epoch / decay_steps
            if staircase:
                e = int(e)
            return self.base_lr * math.exp(-decay_rate * e)

    return _NatExp(learning_rate=learning_rate)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    from ...optimizer import lr

    class _Inv(lr.LRScheduler):
        def get_lr(self):
            e = self.last_epoch / decay_steps
            if staircase:
                e = int(e)
            return self.base_lr / (1 + decay_rate * e)

    return _Inv(learning_rate=learning_rate)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    from ...optimizer import lr

    return lr.PolynomialDecay(learning_rate, decay_steps,
                              end_lr=end_learning_rate, power=power,
                              cycle=cycle)


def piecewise_decay(boundaries, values):
    from ...optimizer import lr

    return lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from ...optimizer import lr

    return lr.CosineAnnealingDecay(learning_rate, step_each_epoch * epochs)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from ...optimizer import lr

    if not isinstance(learning_rate, lr.LRScheduler):
        learning_rate = float(learning_rate)
    return lr.LinearWarmup(learning_rate, warmup_steps, start_lr, end_lr)


# ---------------------------------------------------------------------------
# detection.py — map to vision.ops
# ---------------------------------------------------------------------------

from ...vision.ops import (  # noqa: F401,E402
    box_coder, distribute_fpn_proposals, prior_box, roi_align, yolo_box,
)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, scale_x_y=1.0):
    from ...vision.ops import yolo_loss as _yl

    return _yl(x, gt_box, gt_label, anchors, anchor_mask, class_num,
               ignore_thresh, downsample_ratio, gt_score=gt_score,
               use_label_smooth=use_label_smooth, scale_x_y=scale_x_y)


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    from ...vision.ops import multiclass_nms as _nms

    return _nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                nms_threshold=nms_threshold, normalized=normalized,
                nms_eta=nms_eta, background_label=background_label)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    # true max-over-bins RoI pooling (roi_pool_op parity) — NOT roi_align's
    # bilinear average; vision.ops.roi_pool implements the integer-bin max
    from ...vision.ops import roi_pool as _rp

    return _rp(input, rois, boxes_num=rois_num,
               output_size=(pooled_height, pooled_width),
               spatial_scale=spatial_scale)


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    from ...vision.ops import generate_proposals as _gp

    return _gp(scores, bbox_deltas, im_info, anchors, variances,
               pre_nms_top_n=pre_nms_top_n, post_nms_top_n=post_nms_top_n,
               nms_thresh=nms_thresh, min_size=min_size, eta=eta)


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=None,
                    deformable_groups=None, im2col_step=None,
                    param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    return snn.deform_conv2d(
        input, offset, mask if modulated else None, num_filters, filter_size,
        stride=stride, padding=padding, dilation=dilation,
        groups=groups or 1, deformable_groups=deformable_groups or 1,
        param_attr=param_attr, bias_attr=bias_attr)


def box_clip(input, im_info, name=None):
    h = im_info[:, 0]
    w = im_info[:, 1]
    zero = T.zeros([], dtype=input.dtype)
    xmin = T.maximum(T.minimum(input[..., 0], w - 1), zero)
    ymin = T.maximum(T.minimum(input[..., 1], h - 1), zero)
    xmax = T.maximum(T.minimum(input[..., 2], w - 1), zero)
    ymax = T.maximum(T.minimum(input[..., 3], h - 1), zero)
    return T.stack([xmin, ymin, xmax, ymax], axis=-1)


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    from ...ops import detection_ops  # noqa: F401 — registers the op

    outs = dispatch("bipartite_match", {"DistMat": [dist_matrix]}, {})
    return (single(outs, "ColToRowMatchIndices"),
            single(outs, "ColToRowMatchDist"))


# ---------------------------------------------------------------------------
# rnn.py — the modern RNN API covers these; LoD-dynamic ones are PS-era
# ---------------------------------------------------------------------------


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, **kw):
    from ... import nn

    rnn = nn.LSTM(input.shape[-1], hidden_size, num_layers=num_layers,
                  direction="bidirect" if is_bidirec else "forward")
    out, (h, c) = rnn(input, (init_h, init_c))
    return out, h, c


# ---------------------------------------------------------------------------
# distributions (moved to paddle.distribution in 2.x)
# ---------------------------------------------------------------------------


def _unsupported(name, why, instead):
    def raiser(*a, **k):
        raise NotImplementedError(
            f"fluid.layers.{name} is {why} in the TPU-native build; "
            f"use {instead} instead.")

    raiser.__name__ = name
    return raiser


# ---------------------------------------------------------------------------
# v2.1 names wired to their existing 2.x implementations (arg order is the
# fluid one; the bodies are the 2.x ops)
# ---------------------------------------------------------------------------


def grid_sampler(x, grid, name=None):
    """fluid.layers.grid_sampler — bilinear + zeros padding + align_corners
    (the only mode the v2.1 op exposed)."""
    return F.grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                         align_corners=True)


temporal_shift = F.temporal_shift


def affine_grid(theta, out_shape, name=None):
    """fluid.layers.affine_grid — v2.1 had no align_corners knob (True)."""
    return F.affine_grid(theta, out_shape, align_corners=True)


gather_tree = F.gather_tree
multiplex = T.multiplex


def mean_iou(input, label, num_classes):
    """fluid.layers.mean_iou (mean_iou_op) — returns
    ``(mean_iou, out_wrong, out_correct)``: per-class wrong/correct counts
    (a mismatch increments BOTH classes' wrong counters) and the IoU mean
    over classes that appear at all."""
    from ...dygraph import tracer

    def fn(pred, lab):
        import jax.numpy as jnp

        pred = pred.reshape(-1).astype(jnp.int64)
        lab = lab.reshape(-1).astype(jnp.int64)
        hit = pred == lab
        correct = jnp.bincount(jnp.where(hit, pred, num_classes),
                               length=num_classes + 1)[:num_classes]
        wrong = (jnp.bincount(jnp.where(hit, num_classes, pred),
                              length=num_classes + 1)[:num_classes]
                 + jnp.bincount(jnp.where(hit, num_classes, lab),
                                length=num_classes + 1)[:num_classes])
        denom = correct + wrong
        valid = denom > 0
        iou = jnp.where(valid, correct / jnp.maximum(denom, 1), 0.0)
        miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1)
        return (miou.astype(jnp.float32), wrong.astype(jnp.int32),
                correct.astype(jnp.int32))

    return tracer.trace_fn(fn, [input, label], name="mean_iou")


def unique_with_counts(x, dtype="int32"):
    """fluid.layers.unique_with_counts — ``(out, index, count)`` in the
    v2.1 contract: ``out`` keeps FIRST-APPEARANCE order (not sorted; the
    docs' example [2,3,3,1,5,3] -> [2,3,1,5]), ``index`` maps each input
    element to its slot in ``out``, and index/count carry ``dtype``
    (int32 by default), unlike the 2.x sorted ``T.unique``."""
    from ...dygraph import tracer

    def fn(a):
        import jax.numpy as jnp

        flat = a.reshape(-1)
        u, first, inv, counts = jnp.unique(
            flat, return_index=True, return_inverse=True, return_counts=True)
        order = jnp.argsort(first)       # sorted-unique slot -> appearance
        rank = jnp.argsort(order)        # appearance rank of each slot
        return (u[order], rank[inv.reshape(-1)].astype(dtype),
                counts[order].astype(dtype))

    return tracer.trace_fn(fn, [x], name="unique_with_counts")


def space_to_depth(x, blocksize, name=None):
    """fluid.layers.space_to_depth (space_to_depth_op): NCHW blocks of
    ``blocksize`` move into channels with (offset_h, offset_w, c) channel
    ordering — out[:, (oh*bs + ow)*C + c, h, w]."""
    from ...dygraph import tracer

    bs = int(blocksize)

    def fn(a):
        n, c, h, w = a.shape
        assert h % bs == 0 and w % bs == 0, (
            f"space_to_depth: spatial dims {(h, w)} must divide "
            f"blocksize {bs}")
        a = a.reshape(n, c, h // bs, bs, w // bs, bs)
        a = a.transpose(0, 3, 5, 1, 2, 4)
        return a.reshape(n, c * bs * bs, h // bs, w // bs)

    return tracer.trace_fn(fn, [x], name="space_to_depth")


# PS-era / LoD-runtime / long-deprecated names: informative raise with the
# modern route (reference: fluid/layers/nn.py, sequence_lod.py, io.py)
_PS_ERA = {
    "linear_chain_crf": ("CRF training on the PS runtime",
                         "paddle.text CRF layers or an external CRF lib"),
    "chunk_eval": ("a PS-era metric op", "paddle.metric with seqeval-style "
                   "python evaluation"),
    "im2sequence": ("a LoD-producing op", "paddle.nn.functional.unfold"),
    "ctc_greedy_decoder": ("a LoD-consuming decode op",
                           "paddle.nn.functional.ctc_decode-style numpy "
                           "post-processing"),
    "dynamic_lstm": ("a LoD-dynamic recurrent op", "paddle.nn.LSTM"),
    "dynamic_lstmp": ("a LoD-dynamic recurrent op", "paddle.nn.LSTM"),
    "dynamic_gru": ("a LoD-dynamic recurrent op", "paddle.nn.GRU"),
    "gru_unit": ("a single-step PS-era cell op", "paddle.nn.GRUCell"),
    "lstm_unit": ("a single-step PS-era cell op", "paddle.nn.LSTMCell"),
    "beam_search": ("a low-level LoD beam op",
                    "paddle_tpu.models.generation beam search"),
    "beam_search_decode": ("a low-level LoD beam op",
                           "paddle_tpu.models.generation beam search"),
    "py_reader": ("the legacy queue-feed reader", "paddle.io.DataLoader"),
    "double_buffer": ("the legacy queue-feed pipeline",
                      "paddle.io.DataLoader(prefetch_factor=...)"),
    "read_file": ("the legacy file reader", "paddle.io.DataLoader"),
    "load": ("the legacy persistable loader", "paddle.static.load"),
    "random_crop": ("a stateful data-aug op",
                    "paddle.vision.transforms.RandomCrop"),
    "sampling_id": ("a sampler over softmax rows",
                    "paddle.multinomial"),
    "similarity_focus": ("a deprecated attention op", "explicit tensor ops"),
    "hash": ("a PS sparse-feature op", "python-side feature hashing"),
    "add_position_encoding": ("deprecated", "explicit position embeddings"),
    "merge_selected_rows": ("a SelectedRows runtime op",
                            "dense gradients (SelectedRows are dense here)"),
    "get_tensor_from_selected_rows": ("a SelectedRows runtime op",
                                      "the tensor itself"),
    "shuffle_channel": ("deprecated", "reshape+transpose"),
    "psroi_pool": ("a niche detection op", "roi_align"),
    "prroi_pool": ("a niche detection op", "roi_align"),
    "fsp_matrix": ("a distillation helper", "explicit matmul over features"),
    "continuous_value_model": ("a PS CTR op", "explicit feature slicing"),
    "filter_by_instag": ("a PS instance-tag op", "python-side filtering"),
    "shard_index": ("a PS sharding op",
                    "mesh sharding (paddle.distributed)"),
    "affine_channel": ("deprecated", "scale+bias tensor ops"),
    "inplace_abn": ("a fused-CUDA ABN", "paddle.static.nn.batch_norm"),
    "pad_constant_like": ("deprecated", "paddle.nn.functional.pad"),
    "lod_reset": ("a LoD mutation op", "the padded+mask sequence design"),
    "lod_append": ("a LoD mutation op", "the padded+mask sequence design"),
    "image_resize_short": ("deprecated", "paddle.vision.transforms.Resize"),
    "resize_linear": ("1-D resize", "paddle.nn.functional.interpolate"),
    "resize_trilinear": ("3-D resize", "paddle.nn.functional.interpolate"),
    "deformable_roi_pooling": ("a niche detection op", "roi_align"),
    "bilinear_tensor_product": ("available via static.nn",
                                "paddle.static.nn.bilinear_tensor_product"),
    "StaticRNN": ("the legacy symbolic RNN builder",
                  "paddle.nn.RNN / paddle.static.nn.while_loop"),
    "DynamicRNN": ("the LoD-dynamic RNN builder", "paddle.nn.RNN"),
    "IfElse": ("the legacy block builder", "paddle.static.nn.cond"),
    "Switch": ("the legacy block builder", "paddle.static.nn.case"),
    "While": ("the legacy block builder", "paddle.static.nn.while_loop"),
}

for _n, (_why, _instead) in _PS_ERA.items():
    if globals().get(_n) is None:
        globals()[_n] = _unsupported(_n, _why, _instead)

# drop placeholders that resolved to None (feature exists under another name)
for _n in [k for k, v in list(globals().items()) if v is None]:
    globals()[_n] = _unsupported(_n, "not bound", "the paddle.nn 2.x API")


# ---------------------------------------------------------------------------
# surface completion: the remaining reference __all__ names (rnn.py decoder
# classes, distributions, pool3d, losses, detection extras) — mapped to the
# 2.x implementations where they exist, informative raises for PS-era ones
# ---------------------------------------------------------------------------

from ... import nn as _nn2  # noqa: E402

RNNCell = _nn2.RNNCellBase
GRUCell = _nn2.GRUCell
LSTMCell = _nn2.LSTMCell
BeamSearchDecoder = _nn2.BeamSearchDecoder
dynamic_decode = _nn2.dynamic_decode


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Reference fluid.layers.rnn is a FUNCTION (cell, inputs, ...) ->
    (outputs, final_states); the 2.x nn.RNN Layer runs it."""
    runner = _nn2.RNN(cell, is_reverse=is_reverse, time_major=time_major)
    return runner(inputs, initial_states=initial_states,
                  sequence_length=sequence_length)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    runner = _nn2.BiRNN(cell_fw, cell_bw, time_major=time_major)
    return runner(inputs, initial_states=initial_states,
                  sequence_length=sequence_length)

from ...distribution import (  # noqa: E402,F401
    Categorical, Normal, Uniform,
)

sequence_mask = F.sequence_mask
triu = T.triu
sigmoid_focal_loss = F.sigmoid_focal_loss
kldiv_loss = F.kl_div


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """Reference warpctc → the 2.x CTC loss (per-sequence losses,
    reduction='none' — the op's output shape).  The LoD calling mode
    (lengths omitted) is not supported: this build's sequences are
    padded+mask, so the padded-mode lengths are required."""
    if input_length is None or label_length is None:
        raise ValueError(
            "fluid.layers.warpctc here requires input_length and "
            "label_length (padded-tensor mode); the LoD mode has no "
            "ragged runtime in the TPU-native build")
    if norm_by_times:
        raise NotImplementedError(
            "fluid.layers.warpctc norm_by_times=True is not wired; divide "
            "the returned per-sequence losses by input_length instead")
    return F.ctc_loss(input, label, input_length, label_length, blank=blank,
                      reduction="none")


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    if path_table is not None or path_code is not None or is_custom:
        raise NotImplementedError(
            "fluid.layers.hsigmoid custom-tree mode (path_table/path_code) "
            "is not wired; use the default complete-binary-tree mode or "
            "paddle.nn.HSigmoidLoss directly")
    layer = snn._reuse("hsigmoid", name, lambda: _nn2.HSigmoidLoss(
        int(input.shape[-1]), num_classes, weight_attr=param_attr,
        bias_attr=bias_attr))
    return layer(input, label)


def cos_sim(X, Y):
    # reference returns [N, 1]
    return T.unsqueeze(F.cosine_similarity(X, Y, axis=-1), [-1])


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, name=None, exclusive=True, data_format="NCDHW"):
    ndhwc = data_format == "NDHWC"
    if ndhwc:  # the 3-D kernels are NCDHW; transpose around them
        input = T.transpose(input, [0, 4, 1, 2, 3])
    if global_pooling:
        out = (T.max(input, axis=[2, 3, 4], keepdim=True)
               if pool_type == "max"
               else T.mean(input, axis=[2, 3, 4], keepdim=True))
    elif pool_type == "max":
        out = F.max_pool3d(input, kernel_size=pool_size, stride=pool_stride,
                           padding=pool_padding, ceil_mode=ceil_mode)
    else:
        out = F.avg_pool3d(input, kernel_size=pool_size, stride=pool_stride,
                           padding=pool_padding, ceil_mode=ceil_mode,
                           exclusive=exclusive)
    if ndhwc:
        out = T.transpose(out, [0, 2, 3, 4, 1])
    return out


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    if require_index:
        raise NotImplementedError(
            "adaptive_pool3d(require_index=True) (argmax indices) is not "
            "wired; use the values-only form")
    fn = (F.adaptive_max_pool3d if pool_type == "max"
          else F.adaptive_avg_pool3d)
    return fn(input, pool_size)


def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking (bpr_loss_op.h): for each row,
    sum over j != label of -log(sigmoid(score_label - score_j)),
    divided by (num_classes - 1)."""
    n, c = input.shape[0], input.shape[-1]
    idx = T.cast(T.reshape(label, [-1, 1]), "int64")
    pos = T.gather_nd(input, T.concat([
        T.unsqueeze(T.arange(0, n, 1, dtype="int64"), [-1]), idx], axis=-1))
    diff = T.unsqueeze(pos, [-1]) - input
    loss = -T.log(F.sigmoid(diff) + 1e-8)
    # mask out the j == label term (the reference kernel skips it)
    mask = T.cast(T.not_equal(
        T.unsqueeze(T.arange(0, c, 1, dtype="int64"), [0]),
        idx), loss.dtype)
    return T.sum(loss * mask, axis=-1, keepdim=True) / float(int(c) - 1)


def rank_loss(label, left, right, name=None):
    """rank_loss_op.cc: C(o) = -o~*o + log(1 + exp(o)), o = left - right."""
    o = left - right
    return -label * o + T.log(1.0 + T.exp(o))


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU between [N,4] and [M,4] boxes (iou_similarity_op)."""
    x1 = T.unsqueeze(x, [1])  # [N,1,4]
    y1 = T.unsqueeze(y, [0])  # [1,M,4]
    ixmin = T.maximum(x1[..., 0], y1[..., 0])
    iymin = T.maximum(x1[..., 1], y1[..., 1])
    ixmax = T.minimum(x1[..., 2], y1[..., 2])
    iymax = T.minimum(x1[..., 3], y1[..., 3])
    off = 0.0 if box_normalized else 1.0
    iw = T.clip(ixmax - ixmin + off, 0.0, 1e10)
    ih = T.clip(iymax - iymin + off, 0.0, 1e10)
    inter = iw * ih
    ax = ((x1[..., 2] - x1[..., 0] + off) * (x1[..., 3] - x1[..., 1] + off))
    ay = ((y1[..., 2] - y1[..., 0] + off) * (y1[..., 3] - y1[..., 1] + off))
    return inter / (ax + ay - inter + 1e-10)


class Assert:
    """fluid.layers.Assert(cond) — trace-time check on concrete values;
    a symbolic condition raises via the Variable truthiness guard with
    conversion guidance (assert inside jitted graphs is host-side)."""

    def __new__(cls, cond, data=None, summarize=20, name=None):
        import numpy as np

        arr = (np.asarray(cond._array) if hasattr(cond, "_array")
               else np.asarray(cond))
        # reference Assert requires ALL elements true (assert_op.cc)
        if not bool(np.all(arr)):
            raise AssertionError(
                f"fluid.layers.Assert failed (cond={arr.reshape(-1)[:summarize]})"
                + (f"; data={data}" if data is not None else ""))
        return cond


_PS_ERA_2 = {
    "MultivariateNormalDiag": ("moved in 2.x", "paddle.distribution"),
    "BasicDecoder": ("the legacy seq2seq decoder kit",
                     "paddle.nn.BeamSearchDecoder + dynamic_decode"),
    "Decoder": ("the legacy seq2seq decoder kit",
                "paddle.nn.BeamSearchDecoder + dynamic_decode"),
    "DecodeHelper": ("the legacy seq2seq helper kit",
                     "models.generation greedy/beam utilities"),
    "TrainingHelper": ("the legacy seq2seq helper kit",
                       "teacher forcing via plain layer calls"),
    "GreedyEmbeddingHelper": ("the legacy seq2seq helper kit",
                              "models.generation greedy decode"),
    "SampleEmbeddingHelper": ("the legacy seq2seq helper kit",
                              "models.generation sampling decode"),
    "anchor_generator": ("a detection-era op", "vision.ops.prior_box"),
    "density_prior_box": ("a detection-era op", "vision.ops.prior_box"),
    "detection_output": ("a detection-era op",
                         "vision.ops.multiclass_nms over decoded boxes"),
    "matrix_nms": ("pending", "vision.ops.multiclass_nms"),
    "locality_aware_nms": ("a niche OCR op", "vision.ops.multiclass_nms"),
    "collect_fpn_proposals": ("a detection-era op",
                              "vision.ops.distribute_fpn_proposals"),
    "box_decoder_and_assign": ("a detection-era op", "vision.ops.box_coder"),
    "polygon_box_transform": ("a niche OCR op", "explicit tensor ops"),
    "roi_perspective_transform": ("a niche OCR op", "vision.ops.roi_align"),
    "retinanet_detection_output": ("a detection-era op",
                                   "vision.ops.multiclass_nms"),
    "retinanet_target_assign": ("a detection-era op",
                                "python-side target assignment"),
    "rpn_target_assign": ("a detection-era op",
                          "python-side target assignment"),
    "generate_mask_labels": ("a detection-era op",
                             "python-side target assignment"),
    "generate_proposal_labels": ("a detection-era op",
                                 "python-side target assignment"),
    "ssd_loss": ("a detection-era composite", "explicit loss composition "
                 "over vision.ops.iou/box utilities"),
    "target_assign": ("a detection-era op",
                      "python-side target assignment"),
    "center_loss": ("a stateful-centers op",
                    "an explicit centers buffer + mse update"),
    "sampled_softmax_with_cross_entropy": (
        "a sampling-softmax op", "full softmax_with_cross_entropy (the "
        "50k-vocab chunked CE keeps it cheap on TPU)"),
    "teacher_student_sigmoid_loss": ("a PS CTR loss",
                                     "explicit sigmoid-loss composition"),
    "edit_distance": ("a host-side metric", "python/numpy edit distance "
                      "over decoded sequences"),
    "create_py_reader_by_data": ("the legacy queue-feed reader",
                                 "paddle.io.DataLoader"),
    "reorder_lod_tensor_by_rank": ("a LoD-runtime op",
                                   "the padded+mask sequence design"),
    "autodoc": ("an internal doc decorator", "nothing — decorate directly"),
    "templatedoc": ("an internal doc decorator",
                    "nothing — decorate directly"),
    "generate_activation_fn": ("an internal codegen helper",
                               "paddle.nn.functional activations"),
    "generate_inplace_fn": ("an internal codegen helper",
                            "paddle tensor in-place methods"),
    "generate_layer_fn": ("an internal codegen helper",
                          "the public layer builders"),
}

for _n, (_why, _instead) in _PS_ERA_2.items():
    if globals().get(_n) is None:
        globals()[_n] = _unsupported(_n, _why, _instead)
