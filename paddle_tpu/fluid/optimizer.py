"""``paddle.fluid.optimizer`` — v2.1 optimizer names.

Parity: ``/root/reference/python/paddle/fluid/optimizer.py`` (SGDOptimizer,
MomentumOptimizer, AdamOptimizer, ... — each with ``minimize(loss)`` for
static graphs).  All map onto the 2.x optimizers, which already implement
``minimize`` in both modes; ``regularization`` maps to ``weight_decay``.
"""

from __future__ import annotations

from .. import optimizer as _opt


def _fluidify(cls, **renames):
    class FluidOptimizer(cls):
        def __init__(self, *args, regularization=None, grad_clip=None,
                     parameter_list=None, **kw):
            if regularization is not None and "weight_decay" not in kw:
                kw["weight_decay"] = regularization
            if parameter_list is not None and "parameters" not in kw:
                kw["parameters"] = parameter_list
            if grad_clip is not None:
                kw["grad_clip"] = grad_clip
            for old, new in renames.items():
                if old in kw:
                    kw[new] = kw.pop(old)
            super().__init__(*args, **kw)

    FluidOptimizer.__name__ = cls.__name__ + "Optimizer"
    FluidOptimizer.__qualname__ = FluidOptimizer.__name__
    return FluidOptimizer


SGDOptimizer = _fluidify(_opt.SGD)
MomentumOptimizer = _fluidify(_opt.Momentum)
AdamOptimizer = _fluidify(_opt.Adam)
AdamaxOptimizer = _fluidify(_opt.Adamax)
AdagradOptimizer = _fluidify(_opt.Adagrad)
AdadeltaOptimizer = _fluidify(_opt.Adadelta)
RMSPropOptimizer = _fluidify(_opt.RMSProp)
LambOptimizer = _fluidify(_opt.Lamb)
LarsMomentumOptimizer = _fluidify(_opt.LarsMomentum)

# fluid also exposes the short names
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Adagrad = AdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Lamb = LambOptimizer

from ..incubate import (  # noqa: E402,F401
    ExponentialMovingAverage, LookAhead, ModelAverage,
)

LookaheadOptimizer = LookAhead


def _unsupported(name, instead):
    class _Raiser:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"fluid.optimizer.{name} is parameter-server-era; "
                f"use {instead} instead.")

    _Raiser.__name__ = name
    return _Raiser


DGCMomentumOptimizer = _unsupported(
    "DGCMomentumOptimizer",
    "fleet.DistributedStrategy dgc=True (fleet/meta_optimizers)")
PipelineOptimizer = _unsupported(
    "PipelineOptimizer", "fleet hybrid pp (meta_parallel.PipelineParallel)")
RecomputeOptimizer = _unsupported(
    "RecomputeOptimizer",
    "paddle.distributed.fleet recompute / incubate.checkpoint")
GradientMergeOptimizer = _unsupported(
    "GradientMergeOptimizer", "fleet.DistributedStrategy gradient_merge")
