"""``paddle.fluid.executor`` module alias.

Parity: ``/root/reference/python/paddle/fluid/executor.py``.
"""

from ..framework.scope import Scope, global_scope, scope_guard  # noqa: F401
from ..static.executor import Executor  # noqa: F401
