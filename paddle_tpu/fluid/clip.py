"""``paddle.fluid.clip`` (GradientClipBy* → 2.x nn clip classes).

Parity: ``/root/reference/python/paddle/fluid/clip.py``.
"""

from ..nn import (  # noqa: F401
    ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue,
)

GradientClipByGlobalNorm = ClipGradByGlobalNorm
GradientClipByNorm = ClipGradByNorm
GradientClipByValue = ClipGradByValue


def set_gradient_clip(clip, param_list=None, program=None):
    raise NotImplementedError(
        "fluid.clip.set_gradient_clip was deprecated in the reference too; "
        "pass grad_clip=... to the optimizer instead.")
