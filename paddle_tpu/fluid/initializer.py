"""``paddle.fluid.initializer`` — v2.1 initializer names.

Parity: ``/root/reference/python/paddle/fluid/initializer.py`` (Constant,
Uniform, Normal, TruncatedNormal, Xavier, Bilinear, MSRA + the *Initializer
aliases and set_global_initializer).
"""

from ..nn import initializer as _init

Constant = ConstantInitializer = _init.Constant
Uniform = UniformInitializer = _init.Uniform
Normal = NormalInitializer = _init.Normal
TruncatedNormal = TruncatedNormalInitializer = _init.TruncatedNormal


def Xavier(uniform=True, fan_in=None, fan_out=None, seed=0):  # noqa: N802
    """Reference XavierInitializer: ``uniform=True`` by DEFAULT (the 2.x
    split classes are XavierUniform/XavierNormal)."""
    cls = _init.XavierUniform if uniform else _init.XavierNormal
    return cls(fan_in=fan_in, fan_out=fan_out)


def MSRA(uniform=True, fan_in=None, seed=0, negative_slope=0.0,  # noqa: N802
         nonlinearity="relu"):
    """Reference MSRAInitializer: ``uniform=True`` by default."""
    cls = _init.KaimingUniform if uniform else _init.KaimingNormal
    return cls(fan_in=fan_in, negative_slope=negative_slope,
               nonlinearity=nonlinearity)


XavierInitializer = Xavier
MSRAInitializer = MSRA
Bilinear = BilinearInitializer = getattr(_init, "Bilinear", None)
NumpyArrayInitializer = _init.Assign

set_global_initializer = _init.set_global_initializer

if Bilinear is None:
    def _bilinear_unavailable(*a, **k):
        raise NotImplementedError(
            "Bilinear initializer: initialize conv-transpose weights with "
            "an explicit numpy kernel + initializer.Assign")

    Bilinear = BilinearInitializer = _bilinear_unavailable
