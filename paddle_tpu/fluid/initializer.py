"""``paddle.fluid.initializer`` — v2.1 initializer names.

Parity: ``/root/reference/python/paddle/fluid/initializer.py`` (Constant,
Uniform, Normal, TruncatedNormal, Xavier, Bilinear, MSRA + the *Initializer
aliases and set_global_initializer).
"""

from ..nn import initializer as _init

Constant = ConstantInitializer = _init.Constant
Uniform = UniformInitializer = _init.Uniform
Normal = NormalInitializer = _init.Normal
TruncatedNormal = TruncatedNormalInitializer = _init.TruncatedNormal
Xavier = XavierInitializer = _init.XavierNormal
MSRA = MSRAInitializer = _init.KaimingNormal
Bilinear = BilinearInitializer = getattr(_init, "Bilinear", None)
NumpyArrayInitializer = _init.Assign

set_global_initializer = _init.set_global_initializer

if Bilinear is None:
    def _bilinear_unavailable(*a, **k):
        raise NotImplementedError(
            "Bilinear initializer: initialize conv-transpose weights with "
            "an explicit numpy kernel + initializer.Assign")

    Bilinear = BilinearInitializer = _bilinear_unavailable
