"""``paddle.fluid.initializer`` — v2.1 initializer names.

Parity: ``/root/reference/python/paddle/fluid/initializer.py`` (Constant,
Uniform, Normal, TruncatedNormal, Xavier, Bilinear, MSRA + the *Initializer
aliases and set_global_initializer).
"""

from ..nn import initializer as _init

Constant = ConstantInitializer = _init.Constant
Uniform = UniformInitializer = _init.Uniform
Normal = NormalInitializer = _init.Normal
TruncatedNormal = TruncatedNormalInitializer = _init.TruncatedNormal


class Xavier(_init.Initializer):
    """Reference XavierInitializer: ``uniform=True`` by DEFAULT (the 2.x
    split classes are XavierUniform/XavierNormal).  A class (not a
    factory) so isinstance/subclass checks on the compat name keep
    working; __new__ returns the matching 2.x variant."""

    def __new__(cls, uniform=True, fan_in=None, fan_out=None, seed=0):
        if cls is not Xavier:
            return super().__new__(cls)
        impl = _init.XavierUniform if uniform else _init.XavierNormal
        return impl(fan_in=fan_in, fan_out=fan_out)


class MSRA(_init.Initializer):
    """Reference MSRAInitializer: ``uniform=True`` by default."""

    def __new__(cls, uniform=True, fan_in=None, seed=0, negative_slope=0.0,
                nonlinearity="relu"):
        if cls is not MSRA:
            return super().__new__(cls)
        impl = _init.KaimingUniform if uniform else _init.KaimingNormal
        return impl(fan_in=fan_in, negative_slope=negative_slope,
                    nonlinearity=nonlinearity)


XavierInitializer = Xavier
MSRAInitializer = MSRA
Bilinear = BilinearInitializer = getattr(_init, "Bilinear", None)
NumpyArrayInitializer = _init.Assign

set_global_initializer = _init.set_global_initializer

if Bilinear is None:
    def _bilinear_unavailable(*a, **k):
        raise NotImplementedError(
            "Bilinear initializer: initialize conv-transpose weights with "
            "an explicit numpy kernel + initializer.Assign")

    Bilinear = BilinearInitializer = _bilinear_unavailable
