"""``paddle.fluid.initializer`` — v2.1 initializer names.

Parity: ``/root/reference/python/paddle/fluid/initializer.py`` (Constant,
Uniform, Normal, TruncatedNormal, Xavier, Bilinear, MSRA + the *Initializer
aliases and set_global_initializer).
"""

import abc

from ..nn import initializer as _init

Constant = ConstantInitializer = _init.Constant
Uniform = UniformInitializer = _init.Uniform
Normal = NormalInitializer = _init.Normal
TruncatedNormal = TruncatedNormalInitializer = _init.TruncatedNormal


class _CompatInitMeta(abc.ABCMeta):
    """Metaclass for the v2.1 compat initializer names: ``__call__`` builds
    the matching 2.x variant, while ABCMeta's ``register`` makes that
    variant a VIRTUAL subclass — so ``isinstance(Xavier(), Xavier)`` and
    ``isinstance(XavierUniform(), Xavier)`` both hold even though the
    constructed object is a 2.x instance."""

    def __call__(cls, *args, **kwargs):
        if "_build" in vars(cls):  # the compat class itself, not a subclass
            return cls._build(*args, **kwargs)
        return super().__call__(*args, **kwargs)


class Xavier(_init.Initializer, metaclass=_CompatInitMeta):
    """Reference XavierInitializer: ``uniform=True`` by DEFAULT (the 2.x
    split classes are XavierUniform/XavierNormal)."""

    @staticmethod
    def _build(uniform=True, fan_in=None, fan_out=None, seed=0):
        impl = _init.XavierUniform if uniform else _init.XavierNormal
        return impl(fan_in=fan_in, fan_out=fan_out)


Xavier.register(_init.XavierUniform)
Xavier.register(_init.XavierNormal)


class MSRA(_init.Initializer, metaclass=_CompatInitMeta):
    """Reference MSRAInitializer: ``uniform=True`` by default."""

    @staticmethod
    def _build(uniform=True, fan_in=None, seed=0, negative_slope=0.0,
               nonlinearity="relu"):
        impl = _init.KaimingUniform if uniform else _init.KaimingNormal
        return impl(fan_in=fan_in, negative_slope=negative_slope,
                    nonlinearity=nonlinearity)


MSRA.register(_init.KaimingUniform)
MSRA.register(_init.KaimingNormal)


XavierInitializer = Xavier
MSRAInitializer = MSRA
Bilinear = BilinearInitializer = getattr(_init, "Bilinear", None)
NumpyArrayInitializer = _init.Assign

set_global_initializer = _init.set_global_initializer

if Bilinear is None:
    def _bilinear_unavailable(*a, **k):
        raise NotImplementedError(
            "Bilinear initializer: initialize conv-transpose weights with "
            "an explicit numpy kernel + initializer.Assign")

    Bilinear = BilinearInitializer = _bilinear_unavailable
