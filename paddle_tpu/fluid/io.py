"""``paddle.fluid.io`` — save/load + DataLoader.

Parity: ``/root/reference/python/paddle/fluid/io.py`` (save_inference_model
with the directory-style signature, save/load_params, save/load_persistables,
batch/shuffle readers re-exported from paddle.reader) and
``fluid/reader.py`` (DataLoader).
"""

from __future__ import annotations

import os

from ..io import DataLoader, Dataset  # noqa: F401
from ..io_api import batch  # noqa: F401
from ..reader import shuffle  # noqa: F401
from ..static import io as _sio
from ..static import (  # noqa: F401
    load_program_state, set_program_state,
)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, export_for_deployment=True,
                         program_only=False):
    """v2.1 signature: dirname + feed NAMES (2.x static.save_inference_model
    takes a path prefix + feed VARS)."""
    from ..framework import program as fw

    program = main_program or fw.default_main_program()
    block = program.global_block()
    feed_vars = [block.var(n) for n in feeded_var_names]
    prefix = os.path.join(dirname, model_filename or "__model__")
    _sio.save_inference_model(prefix, feed_vars, list(target_vars), executor,
                              program=program)
    return [v.name for v in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    prefix = os.path.join(dirname, model_filename or "__model__")
    return _sio.load_inference_model(prefix, executor)


def save_params(executor, dirname, main_program=None, filename=None):
    _save_vars(executor, dirname, main_program, filename, params_only=True)


def save_persistables(executor, dirname, main_program=None, filename=None):
    _save_vars(executor, dirname, main_program, filename, params_only=False)


def _save_vars(executor, dirname, main_program, filename, params_only):
    import numpy as np

    from ..framework import program as fw
    from ..framework.scope import global_scope

    program = main_program or fw.default_main_program()
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    state = {}
    for var in program.global_block().vars.values():
        if not getattr(var, "persistable", False):
            continue
        if params_only and not isinstance(var, fw.Parameter):
            continue
        val = scope.find_var(var.name)
        if val is not None:
            state[var.name] = np.asarray(val)
    np.savez(os.path.join(dirname, filename or "__params__.npz"), **state)


def load_params(executor, dirname, main_program=None, filename=None):
    _load_vars(executor, dirname, main_program, filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    _load_vars(executor, dirname, main_program, filename)


def _load_vars(executor, dirname, main_program, filename):
    import numpy as np

    from ..framework.scope import global_scope

    scope = global_scope()
    path = os.path.join(dirname, filename or "__params__.npz")
    data = np.load(path)
    for name in data.files:
        scope.set(name, data[name])
