"""``paddle.fluid`` — the pre-2.x compatibility namespace.

Parity: ``/root/reference/python/paddle/fluid/__init__.py`` (the reference's
public surface re-exports fluid, and v2.1-era model code — the
PaddleClas/PaddleNLP generations the BASELINE configs name — writes
``import paddle.fluid as fluid``).  Every name maps onto the 2.x TPU
implementations; nothing here is a second implementation.
"""

from __future__ import annotations

# -- framework ---------------------------------------------------------------
from ..framework.program import (  # noqa: F401
    Program, Variable, default_main_program, default_startup_program,
    program_guard, in_dygraph_mode, name_scope,
)
from ..framework import unique_name  # noqa: F401
from ..framework.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace,
)
from ..static import cpu_places, cuda_places  # noqa: F401
from ..nn.layer_base import ParamAttr  # noqa: F401
from ..static import WeightNormParamAttr  # noqa: F401

# -- executor ----------------------------------------------------------------
from ..static.executor import Executor  # noqa: F401
from ..framework.scope import Scope, global_scope, scope_guard  # noqa: F401

# -- static graph pieces -----------------------------------------------------
from ..static import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy, ParallelExecutor,
    append_backward, gradients,
)
from ..static.input import data  # noqa: F401

# -- submodules --------------------------------------------------------------
from . import layers  # noqa: F401
from . import dygraph  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import io  # noqa: F401
from . import nets  # noqa: F401
from . import metrics  # noqa: F401
from . import core  # noqa: F401
from . import framework  # noqa: F401
from . import executor  # noqa: F401
from . import backward  # noqa: F401
from . import param_attr  # noqa: F401
from . import contrib  # noqa: F401

from .layers import embedding, one_hot  # noqa: F401  (fluid.embedding alias)


def enable_dygraph(place=None):
    from ..framework import program as fw

    fw.disable_static()


def disable_dygraph():
    from ..framework import program as fw

    fw.enable_static()


def enable_imperative(place=None):
    enable_dygraph(place)


def disable_imperative():
    disable_dygraph()


def is_compiled_with_cuda() -> bool:
    return False


def get_flags(flags):
    from ..framework import flags as _f

    if isinstance(flags, str):
        flags = [flags]
    return {name: _f.flag(name) for name in flags}


def set_flags(flags_dict):
    from ..framework import flags as _f

    for name, value in flags_dict.items():
        _f.set_flag(name, value)


def memory_optimize(*a, **k):
    """No-op: XLA owns buffer liveness (reference transpiler-era pass)."""


def release_memory(*a, **k):
    """No-op: XLA owns buffer liveness."""


def require_version(min_version, max_version=None):
    return None


def load_op_library(*a, **k):
    raise NotImplementedError(
        "fluid.load_op_library loads CUDA .so custom ops; use "
        "paddle_tpu.utils.cpp_extension (C++ + pure_callback) instead.")


# -- remaining reference fluid.__all__ names --------------------------------

from ..framework.place import NPUPlace, XPUPlace  # noqa: E402,F401
from .. import profiler  # noqa: E402,F401
from ..dygraph.tensor import Tensor  # noqa: E402,F401


class LoDTensor:
    """Compat alias: LoD tensors are padded+mask in this build (see
    ops/sequence_ops.py design note); a plain Tensor carries the data."""

    def __new__(cls, *a, **k):
        import numpy as np

        return Tensor(np.zeros([0], "float32")) if not a else Tensor(a[0])


LoDTensorArray = list  # dygraph semantics: a python list of Tensors


class DataFeeder:
    """Parity: fluid/data_feeder.py — converts per-sample rows into the
    feed dict the Executor takes."""

    def __init__(self, feed_list, place=None, program=None):
        self._names = [getattr(v, "name", str(v)) for v in feed_list]

    def feed(self, iterable):
        import numpy as np

        cols = list(zip(*iterable))
        if len(cols) != len(self._names):
            raise ValueError(
                f"DataFeeder got {len(cols)} columns for "
                f"{len(self._names)} feed vars")
        return {n: np.stack([np.asarray(v) for v in c])
                for n, c in zip(self._names, cols)}


def save(program, model_path, protocol=4, **configs):
    """Parity: fluid.save — persistables of a Program to one file."""
    import numpy as np

    from ..framework import program as fw
    from ..framework.scope import global_scope

    state = {}
    for var in program.global_block().vars.values():
        if getattr(var, "persistable", False):
            val = global_scope().find_var(var.name)
            if val is not None:
                state[var.name] = np.asarray(val)
    np.savez(model_path + ".pdparams.npz", **state)


def load(program, model_path, executor=None, var_list=None):
    """Parity: fluid.load — restore persistables saved by fluid.save."""
    import numpy as np

    from ..framework.scope import global_scope

    data = np.load(model_path + ".pdparams.npz")
    names = set(var_list) if var_list else None
    for name in data.files:
        if names is None or name in names:
            global_scope().set(name, data[name])


def install_check():
    """Parity: fluid.install_check.run_check."""
    from ..utils import run_check

    return run_check()


def _cuda_synchronize(place=None):
    """No-op: XLA execution is synchronized at fetch (block_until_ready)."""


class _TranspilerUnavailable:
    def __getattr__(self, name):
        raise NotImplementedError(
            "fluid.transpiler is the parameter-server-era program rewriter; "
            "the collective path (paddle.distributed.fleet) replaces it in "
            "the TPU-native build.")


transpiler = _TranspilerUnavailable()
