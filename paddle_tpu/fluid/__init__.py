"""``paddle.fluid`` — the pre-2.x compatibility namespace.

Parity: ``/root/reference/python/paddle/fluid/__init__.py`` (the reference's
public surface re-exports fluid, and v2.1-era model code — the
PaddleClas/PaddleNLP generations the BASELINE configs name — writes
``import paddle.fluid as fluid``).  Every name maps onto the 2.x TPU
implementations; nothing here is a second implementation.
"""

from __future__ import annotations

# -- framework ---------------------------------------------------------------
from ..framework.program import (  # noqa: F401
    Program, Variable, default_main_program, default_startup_program,
    program_guard, in_dygraph_mode, name_scope,
)
from ..framework import unique_name  # noqa: F401
from ..framework.place import (  # noqa: F401
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace,
)
from ..static import cpu_places, cuda_places  # noqa: F401
from ..nn.layer_base import ParamAttr  # noqa: F401
from ..static import WeightNormParamAttr  # noqa: F401

# -- executor ----------------------------------------------------------------
from ..static.executor import Executor  # noqa: F401
from ..framework.scope import Scope, global_scope, scope_guard  # noqa: F401

# -- static graph pieces -----------------------------------------------------
from ..static import (  # noqa: F401
    BuildStrategy, CompiledProgram, ExecutionStrategy, ParallelExecutor,
    append_backward, gradients,
)
from ..static.input import data  # noqa: F401

# -- submodules --------------------------------------------------------------
from . import layers  # noqa: F401
from . import dygraph  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import io  # noqa: F401
from . import nets  # noqa: F401
from . import metrics  # noqa: F401
from . import core  # noqa: F401
from . import framework  # noqa: F401
from . import executor  # noqa: F401
from . import backward  # noqa: F401
from . import param_attr  # noqa: F401
from . import contrib  # noqa: F401

from .layers import embedding, one_hot  # noqa: F401  (fluid.embedding alias)


def enable_dygraph(place=None):
    from ..framework import program as fw

    fw.disable_static()


def disable_dygraph():
    from ..framework import program as fw

    fw.enable_static()


def enable_imperative(place=None):
    enable_dygraph(place)


def disable_imperative():
    disable_dygraph()


def is_compiled_with_cuda() -> bool:
    return False


def get_flags(flags):
    from ..framework import flags as _f

    if isinstance(flags, str):
        flags = [flags]
    return {name: _f.flag(name) for name in flags}


def set_flags(flags_dict):
    from ..framework import flags as _f

    for name, value in flags_dict.items():
        _f.set_flag(name, value)


def memory_optimize(*a, **k):
    """No-op: XLA owns buffer liveness (reference transpiler-era pass)."""


def release_memory(*a, **k):
    """No-op: XLA owns buffer liveness."""


def require_version(min_version, max_version=None):
    return None


def load_op_library(*a, **k):
    raise NotImplementedError(
        "fluid.load_op_library loads CUDA .so custom ops; use "
        "paddle_tpu.utils.cpp_extension (C++ + pure_callback) instead.")
