"""``paddle.fluid.backward`` module alias.

Parity: ``/root/reference/python/paddle/fluid/backward.py``.
"""

from ..static.backward import append_backward, gradients  # noqa: F401
