"""``paddle.fluid.param_attr`` module alias.

Parity: ``/root/reference/python/paddle/fluid/param_attr.py``.
"""

from ..nn.layer_base import ParamAttr  # noqa: F401
from ..static import WeightNormParamAttr  # noqa: F401
