"""``paddle.fluid.nets`` — composite helpers the v2.1 tutorials use.

Parity: ``/root/reference/python/paddle/fluid/nets.py``
(simple_img_conv_pool, img_conv_group, sequence_conv_pool, glu,
scaled_dot_product_attention).
"""

from __future__ import annotations

from . import layers
from .. import tensor_api as T
from ..nn import functional as F
from ..static import nn as snn


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1, conv_padding=0,
                         conv_dilation=1, conv_groups=1, param_attr=None,
                         bias_attr=None, act=None, use_cudnn=True):
    conv_out = snn.conv2d(
        input, num_filters, filter_size, stride=conv_stride,
        padding=conv_padding, dilation=conv_dilation, groups=conv_groups,
        param_attr=param_attr, bias_attr=bias_attr, act=act)
    return layers.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride, pool_padding=pool_padding,
                         global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    tmp = input
    n = len(conv_num_filter)

    def _expand(v):
        return v if isinstance(v, (list, tuple)) else [v] * n

    padding, fsize = _expand(conv_padding), _expand(conv_filter_size)
    attrs, with_bn = _expand(param_attr), _expand(conv_with_batchnorm)
    drop = _expand(conv_batchnorm_drop_rate)
    for i in range(n):
        act = conv_act if not with_bn[i] else None
        tmp = snn.conv2d(tmp, conv_num_filter[i], fsize[i],
                         padding=padding[i], param_attr=attrs[i], act=act)
        if with_bn[i]:
            tmp = snn.batch_norm(tmp, act=conv_act)
            if drop[i]:
                tmp = layers.dropout(tmp, dropout_prob=drop[i])
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, param_attr=None,
                       act="sigmoid", pool_type="max", bias_attr=None):
    conv_out = snn.sequence_conv(input, num_filters, filter_size,
                                 param_attr=param_attr, bias_attr=bias_attr,
                                 act=act)
    return snn.sequence_pool(conv_out, pool_type=pool_type)


def glu(input, dim=-1):
    a, b = T.split(input, 2, axis=dim)
    return T.multiply(a, F.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Reference nets.py helper — here it rides the fused SDPA kernel."""
    b = T.shape(queries)[0]

    def _split(x):
        s = x.shape
        return T.transpose(
            T.reshape(x, [0, 0, num_heads, s[-1] // num_heads]),
            [0, 2, 1, 3])

    q, k, v = _split(queries), _split(keys), _split(values)
    out = F.scaled_dot_product_attention(q, k, v, dropout_p=dropout_rate)
    out = T.transpose(out, [0, 2, 1, 3])
    s = out.shape
    return T.reshape(out, [0, 0, s[-2] * s[-1]])
