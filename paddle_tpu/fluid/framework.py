"""``paddle.fluid.framework`` module alias.

Parity: ``/root/reference/python/paddle/fluid/framework.py`` — Program /
Variable / Parameter / default programs / guards / mode probes.
"""

from ..framework.program import (  # noqa: F401
    Block, Operator, Parameter, Program, Variable, default_main_program,
    default_startup_program, program_guard, in_dygraph_mode, name_scope,
)
from ..framework import unique_name  # noqa: F401
from ..framework.place import (  # noqa: F401
    CPUPlace, CUDAPlace, is_compiled_with_cuda,
)
from ..nn.layer_base import ParamAttr  # noqa: F401
from . import core  # noqa: F401


def _non_static_mode():
    return in_dygraph_mode()


_in_legacy_dygraph = _non_static_mode
