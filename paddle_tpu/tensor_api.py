"""The ``paddle.*`` tensor-function surface.

Parity: ``/root/reference/python/paddle/tensor/`` (math.py, creation.py,
manipulation.py, search.py, logic.py, linalg.py, random.py — ~10k LoC) and
the operator monkey-patches ``fluid/dygraph/math_op_patch.py`` /
``fluid/layers/math_op_patch.py``.

Every function funnels through :func:`paddle_tpu.ops.dispatch.dispatch`,
which appends an op in static mode or runs the jit-cached kernel eagerly in
dygraph mode — one implementation for both, unlike the reference's dual
``core.ops.*`` / ``LayerHelper.append_op`` branches.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Union

import numpy as np

from .dygraph.tensor import Tensor, to_tensor
from .framework import program as fw
from .framework.dtype import convert_dtype
from .ops.dispatch import dispatch, single

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "empty", "empty_like", "arange", "linspace", "eye", "rand",
    "randn", "randint", "randperm", "uniform", "normal", "bernoulli",
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "matmul", "mm", "bmm", "dot", "t", "transpose",
    "sum", "mean", "max", "min", "prod", "abs", "sqrt", "rsqrt", "square",
    "exp", "log", "log2", "log10", "log1p", "sin", "cos", "tan", "asin",
    "acos", "atan", "sinh", "cosh", "tanh", "floor", "ceil", "round",
    "sign", "reciprocal", "clip", "cumsum", "maximum", "minimum", "add_n",
    "scale", "isnan", "isinf", "isfinite", "numel",
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "equal_all", "allclose", "logical_and", "logical_or",
    "logical_xor", "logical_not",
    "reshape", "flatten", "squeeze", "unsqueeze", "concat", "split", "chunk",
    "stack", "unstack", "expand", "expand_as", "tile", "gather", "gather_nd",
    "scatter", "scatter_nd_add", "index_select", "masked_select", "where",
    "nonzero", "roll", "flip", "tril", "triu", "unique", "topk", "argmax",
    "argmin", "argsort", "sort", "cast", "slice", "strided_slice",
    "take_along_axis", "broadcast_to", "meshgrid", "norm", "dist", "kron",
    "flops", "increment", "is_tensor", "shape", "real", "create_parameter",
    "create_array", "array_write", "array_read", "array_length",
    "multiplex", "histogram", "bincount", "cross", "diag", "mv",
    "cholesky", "inverse", "erf", "expm1", "lgamma", "digamma", "trunc",
    "conj", "real", "imag", "atan2", "bitwise_and", "bitwise_or",
    "bitwise_xor", "bitwise_not", "stanh", "logsumexp", "trace",
    "diagonal", "diagflat", "std", "var", "median", "reverse",
    "multinomial", "index_sample", "scatter_nd",
    "shard_index", "crop", "crop_tensor", "neg", "all", "any",
    "floor_mod", "is_empty", "rank", "broadcast_shape",
    "broadcast_tensors", "standard_normal", "unbind", "tolist",
    "assign", "addmm", "reshape_", "squeeze_", "unsqueeze_", "tanh_",
    "scatter_",
]


def _attrs_axis(axis):
    if axis is None:
        return {"reduce_all": True, "dim": []}
    if isinstance(axis, int):
        axis = [axis]
    return {"reduce_all": False, "dim": list(axis)}


def _d(op_type, ins, attrs=None, slot="Out"):
    return single(dispatch(op_type, ins, attrs or {}), slot)


def is_tensor(x) -> bool:
    return isinstance(x, (Tensor, fw.Variable))


def _is_scalar(v) -> bool:
    return isinstance(v, (int, float, bool, np.number))


def _wrap(v, like=None):
    """Lift python scalars / numpy arrays to Tensor (dygraph) for binary ops."""
    if is_tensor(v):
        return v
    if fw.in_dygraph_mode():
        dtype = None
        if like is not None and _is_scalar(v) and not isinstance(v, bool):
            dtype = like.dtype
        return Tensor(np.asarray(v), dtype=dtype)
    # static mode: create a fill_constant var
    arr = np.asarray(v)
    dtype = str(arr.dtype) if arr.dtype != np.float64 else "float32"
    if like is not None and _is_scalar(v) and not isinstance(v, bool):
        dtype = like.dtype if isinstance(like.dtype, str) else str(like.dtype)
    return _d(
        "fill_constant",
        {},
        {"shape": list(arr.shape), "value": float(arr) if arr.ndim == 0 else arr.tolist(), "dtype": dtype},
    )


# ---------------------------------------------------------------------------
# creation
# ---------------------------------------------------------------------------


def zeros(shape, dtype=None, name=None):
    return full(shape, 0.0, dtype)


def ones(shape, dtype=None, name=None):
    return full(shape, 1.0, dtype)


def full(shape, fill_value, dtype=None, name=None):
    if dtype is None:
        from .framework.dtype import get_default_dtype

        dtype = get_default_dtype()
    if is_tensor(shape):
        shape = [int(s) for s in np.asarray(shape.numpy())]
    shape = [int(s) for s in (shape if isinstance(shape, (list, tuple)) else [shape])]
    if is_tensor(fill_value):
        fill_value = float(fill_value.numpy())
    return _d(
        "fill_constant",
        {},
        {"shape": shape, "value": fill_value, "dtype": convert_dtype(dtype)},
    )


def zeros_like(x, dtype=None, name=None):
    return full_like(x, 0.0, dtype)


def ones_like(x, dtype=None, name=None):
    return full_like(x, 1.0, dtype)


def full_like(x, fill_value, dtype=None, name=None):
    return _d(
        "fill_any_like",
        {"X": [x]},
        {"value": float(fill_value), "dtype": convert_dtype(dtype) if dtype else -1},
    )


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    if end is None:
        start, end = 0, start
    if dtype is None:
        # NB: builtins.all — paddle.all shadows the builtin in this module
        import builtins

        dtype = (
            "int64"
            if builtins.all(isinstance(v, (int, np.integer))
                            for v in (start, end, step))
            else "float32"
        )
    return _d(
        "range", {}, {"start": start, "end": end, "step": step, "dtype": convert_dtype(dtype)}
    )


def linspace(start, stop, num, dtype="float32", name=None):
    return _d(
        "linspace", {}, {"start": start, "stop": stop, "num": num, "dtype": convert_dtype(dtype)}
    )


def eye(num_rows, num_columns=None, dtype="float32", name=None):
    return _d(
        "eye",
        {},
        {
            "num_rows": num_rows,
            "num_columns": num_columns or num_rows,
            "dtype": convert_dtype(dtype),
        },
    )


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, min=0.0, max=1.0)


def randn(shape, dtype=None, name=None):
    if dtype is None:
        from .framework.dtype import get_default_dtype

        dtype = get_default_dtype()
    return _d(
        "gaussian_random",
        {},
        {"shape": list(shape), "mean": 0.0, "std": 1.0, "dtype": convert_dtype(dtype)},
    )


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    return _d(
        "randint",
        {},
        {"low": low, "high": high, "shape": list(shape), "dtype": convert_dtype(dtype)},
    )


def randperm(n, dtype="int64", name=None):
    return _d("randperm", {}, {"n": n, "dtype": convert_dtype(dtype)})


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    if dtype is None:
        from .framework.dtype import get_default_dtype

        dtype = get_default_dtype()
    return _d(
        "uniform_random",
        {},
        {"shape": list(shape), "min": min, "max": max, "seed": seed, "dtype": convert_dtype(dtype)},
    )


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if shape is None:
        shape = [1]
    return _d(
        "gaussian_random",
        {},
        {"shape": list(shape), "mean": mean, "std": std, "dtype": "float32"},
    )


def bernoulli(x, name=None):
    return _d("bernoulli", {"X": [x]}, {})


# ---------------------------------------------------------------------------
# binary math
# ---------------------------------------------------------------------------


def _binop(op_type):
    def f(x, y, name=None):
        x2 = _wrap(x, like=y if is_tensor(y) else None)
        y2 = _wrap(y, like=x if is_tensor(x) else None)
        return _d(op_type, {"X": [x2], "Y": [y2]}, {})

    return f


add = _binop("elementwise_add")
subtract = _binop("elementwise_sub")
multiply = _binop("elementwise_mul")
divide = _binop("elementwise_div")
floor_divide = _binop("elementwise_floordiv")
mod = _binop("elementwise_mod")
remainder = mod
maximum = _binop("elementwise_max")
minimum = _binop("elementwise_min")
equal = _binop("equal")
not_equal = _binop("not_equal")
less_than = _binop("less_than")
less_equal = _binop("less_equal")
greater_than = _binop("greater_than")
greater_equal = _binop("greater_equal")
logical_and = _binop("logical_and")
logical_or = _binop("logical_or")
logical_xor = _binop("logical_xor")


def logical_not(x, name=None):
    return _d("logical_not", {"X": [x]}, {})


def pow(x, y, name=None):
    if _is_scalar(y):
        return _d("pow", {"X": [x]}, {"factor": float(y)})
    return _d("elementwise_pow", {"X": [x], "Y": [_wrap(y, like=x)]}, {})


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _d(
        "matmul_v2", {"X": [x], "Y": [y]}, {"trans_x": transpose_x, "trans_y": transpose_y}
    )


mm = matmul


def bmm(x, y, name=None):
    return matmul(x, y)


def dot(x, y, name=None):
    return _d("dot", {"X": [x], "Y": [y]}, {})


def mv(x, vec, name=None):
    return _d("matmul_v2", {"X": [x], "Y": [vec]}, {})


def cholesky(x, upper=False, name=None):
    """Parity: tensor/linalg.py cholesky:735."""
    return _d("cholesky", {"X": [x]}, {"upper": bool(upper)})


def inverse(x, name=None):
    """Parity: tensor/math.py inverse (inverse_op.cc)."""
    return _d("inverse", {"Input": [x]}, {}, slot="Output")


def equal_all(x, y, name=None):
    eq = equal(x, y)
    return _d("reduce_all", {"X": [eq]}, {"reduce_all": True})


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    diff = abs(subtract(x, y))
    tol = add(full_like(diff, atol), scale(abs(y), rtol))
    return _d("reduce_all", {"X": [less_equal(diff, tol)]}, {"reduce_all": True})


# ---------------------------------------------------------------------------
# unary math
# ---------------------------------------------------------------------------


def _unop(op_type):
    def f(x, name=None):
        return _d(op_type, {"X": [x]}, {})

    return f


abs = _unop("abs")
sqrt = _unop("sqrt")
rsqrt = _unop("rsqrt")
square = _unop("square")
exp = _unop("exp")
log = _unop("log")
log2 = _unop("log2")
log10 = _unop("log10")
log1p = _unop("log1p")
sin = _unop("sin")
cos = _unop("cos")
tan = _unop("tan")
asin = _unop("asin")
acos = _unop("acos")
atan = _unop("atan")
sinh = _unop("sinh")
cosh = _unop("cosh")
tanh = _unop("tanh")
floor = _unop("floor")
ceil = _unop("ceil")
round = _unop("round")
sign = _unop("sign")
reciprocal = _unop("reciprocal")
isnan = _unop("isnan_v2")
isinf = _unop("isinf_v2")
isfinite = _unop("isfinite_v2")


def clip(x, min=None, max=None, name=None):
    attrs = {}
    if min is not None:
        attrs["min"] = float(min)
    if max is not None:
        attrs["max"] = float(max)
    return _d("clip", {"X": [x]}, attrs)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    out = _d(
        "scale",
        {"X": [x]},
        {"scale": float(scale), "bias": float(bias), "bias_after_scale": bias_after_scale},
    )
    if act:
        out = _d(act, {"X": [out]}, {})
    return out


def increment(x, value=1.0, name=None):
    return scale(x, 1.0, value)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """``paddle.create_parameter`` (tensor/creation.py role): a trainable
    parameter usable in both modes (static: main-program Parameter + startup
    init op, the LayerHelper.create_parameter path)."""
    from .nn.layer_base import Layer
    from .nn import ParamAttr

    helper = Layer()
    if name is not None:
        attr = attr or ParamAttr(name=name)
    return helper.create_parameter(
        shape, attr=attr, dtype=dtype, is_bias=is_bias,
        default_initializer=default_initializer)


def cumsum(x, axis=None, dtype=None, name=None):
    flatten = axis is None
    out = _d("cumsum", {"X": [x]}, {"axis": axis if axis is not None else -1, "flatten": flatten})
    if dtype is not None:
        out = cast(out, dtype)
    return out


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    attrs = _attrs_axis(axis)
    attrs["keep_dim"] = keepdim
    out = _d("reduce_sum", {"X": [x]}, attrs)
    if dtype is not None:
        out = cast(out, dtype)
    return out


def mean(x, axis=None, keepdim=False, name=None):
    attrs = _attrs_axis(axis)
    attrs["keep_dim"] = keepdim
    return _d("reduce_mean", {"X": [x]}, attrs)


def max(x, axis=None, keepdim=False, name=None):
    attrs = _attrs_axis(axis)
    attrs["keep_dim"] = keepdim
    return _d("reduce_max", {"X": [x]}, attrs)


def min(x, axis=None, keepdim=False, name=None):
    attrs = _attrs_axis(axis)
    attrs["keep_dim"] = keepdim
    return _d("reduce_min", {"X": [x]}, attrs)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    attrs = _attrs_axis(axis)
    attrs["keep_dim"] = keepdim
    out = _d("reduce_prod", {"X": [x]}, attrs)
    if dtype is not None:
        out = cast(out, dtype)
    return out


def add_n(inputs, name=None):
    if is_tensor(inputs):
        inputs = [inputs]
    return _d("sum", {"X": list(inputs)}, {})


def numel(x, name=None):
    n = 1
    for s in x.shape:
        n *= s
    return to_tensor(np.asarray(n, dtype="int64")) if fw.in_dygraph_mode() else full([1], n, "int64")


def norm(x, p="fro", axis=None, keepdim=False, name=None):
    if p == "fro":
        return sqrt(sum(square(x), axis=axis, keepdim=keepdim))
    return _d(
        "p_norm",
        {"X": [x]},
        {
            "porder": float(p),
            "axis": axis if axis is None or isinstance(axis, int) else list(axis),
            "keepdim": keepdim,
        },
    )


def dist(x, y, p=2.0, name=None):
    return norm(subtract(x, y), p=p)


# ---------------------------------------------------------------------------
# manipulation
# ---------------------------------------------------------------------------


def cast(x, dtype):
    return _d("cast", {"X": [x]}, {"out_dtype": convert_dtype(dtype)})


def reshape(x, shape, name=None):
    if is_tensor(shape):
        shape = [int(s) for s in np.asarray(shape.numpy())]
    shape = [int(s) if not is_tensor(s) else int(s.numpy()) for s in shape]
    return _d("reshape2", {"X": [x]}, {"shape": shape})


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _d(
        "flatten_contiguous_range",
        {"X": [x]},
        {"start_axis": start_axis, "stop_axis": stop_axis},
    )


def squeeze(x, axis=None, name=None):
    if axis is None:
        axis = []
    elif isinstance(axis, int):
        axis = [axis]
    return _d("squeeze2", {"X": [x]}, {"axes": list(axis)})


def unsqueeze(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return _d("unsqueeze2", {"X": [x]}, {"axes": list(axis)})


def transpose(x, perm, name=None):
    return _d("transpose2", {"X": [x]}, {"axis": list(perm)})


def t(x, name=None):
    if len(x.shape) <= 1:
        return x
    return transpose(x, [1, 0])


def concat(x, axis=0, name=None):
    if is_tensor(axis):
        axis = int(axis.numpy())
    return _d("concat", {"X": list(x)}, {"axis": axis})


def split(x, num_or_sections, axis=0, name=None):
    if is_tensor(axis):
        axis = int(axis.numpy())
    if isinstance(num_or_sections, int):
        attrs = {"num": num_or_sections, "sections": [], "axis": axis}
        n = num_or_sections
    else:
        secs = list(num_or_sections)
        dim = x.shape[axis]
        known = [s for s in secs if s not in (-1, None)]
        secs = [s if s not in (-1, None) else dim - int(np.sum(known)) for s in secs]
        attrs = {"num": 0, "sections": secs, "axis": axis}
        n = len(secs)
    out = dispatch("split", {"X": [x]}, attrs)
    return list(out["Out"])


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def stack(x, axis=0, name=None):
    return single(dispatch("stack", {"X": list(x)}, {"axis": axis}), "Y")


def unstack(x, axis=0, num=None, name=None):
    return list(dispatch("unstack", {"X": [x]}, {"axis": axis})["Y"])


def expand(x, shape, name=None):
    shape = [int(s) if not is_tensor(s) else int(s.numpy()) for s in shape]
    return _d("expand_v2", {"X": [x]}, {"shape": shape})


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return _d("broadcast_to", {"X": [x]}, {"shape": list(shape)})


def tile(x, repeat_times, name=None):
    return _d("tile", {"X": [x]}, {"repeat_times": list(repeat_times)})


def gather(x, index, axis=0, name=None):
    if is_tensor(axis):
        axis = int(axis.numpy())
    return _d("gather", {"X": [x], "Index": [index]}, {"axis": axis})


def gather_nd(x, index, name=None):
    return _d("gather_nd", {"X": [x], "Index": [index]}, {})


def scatter(x, index, updates, overwrite=True, name=None):
    return _d(
        "scatter", {"X": [x], "Ids": [index], "Updates": [updates]}, {"overwrite": overwrite}
    )


def scatter_nd_add(x, index, updates, name=None):
    return _d("scatter_nd_add", {"X": [x], "Index": [index], "Updates": [updates]}, {})


def index_select(x, index, axis=0, name=None):
    return _d("index_select", {"X": [x], "Index": [index]}, {"dim": axis})


def masked_select(x, mask, name=None):
    return single(dispatch("masked_select", {"X": [x], "Mask": [mask]}, {}), "Y")


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return _d("where", {"Condition": [condition], "X": [x], "Y": [y]}, {})


def nonzero(x, as_tuple=False, name=None):
    out = _d("where_index", {"Condition": [x]}, {})
    if as_tuple:
        n = out.shape[-1]
        return tuple(single(dispatch("slice", {"Input": [out]}, {
            "axes": [1], "starts": [i], "ends": [i + 1], "decrease_axis": [1]
        })) for i in range(n))
    return out


def roll(x, shifts, axis=None, name=None):
    return _d(
        "roll",
        {"X": [x]},
        {"shifts": shifts if isinstance(shifts, (list, tuple)) else [shifts],
         "axis": list(axis) if isinstance(axis, (list, tuple)) else ([axis] if axis is not None else None)},
    )


def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return _d("flip", {"X": [x]}, {"axis": list(axis)})


def tril(x, diagonal=0, name=None):
    return _d("tril_triu", {"X": [x]}, {"diagonal": diagonal, "lower": True})


def triu(x, diagonal=0, name=None):
    return _d("tril_triu", {"X": [x]}, {"diagonal": diagonal, "lower": False})


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    outs = dispatch("unique", {"X": [x]}, {})
    result = [outs["Out"][0]]
    if return_index:
        result.append(outs["Index"][0])
    if return_inverse:
        result.append(outs["Indices"][0])
    if return_counts:
        result.append(outs["Counts"][0])
    return result[0] if len(result) == 1 else tuple(result)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    outs = dispatch("top_k_v2", {"X": [x]}, {"k": int(k), "axis": axis, "largest": largest})
    return outs["Out"][0], outs["Indices"][0]


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    flatten_ = axis is None
    return _d(
        "arg_max",
        {"X": [x]},
        {"axis": axis if axis is not None else -1, "flatten": flatten_,
         "keepdims": keepdim, "dtype": convert_dtype(dtype)},
    )


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _d(
        "arg_min",
        {"X": [x]},
        {"axis": axis if axis is not None else -1, "flatten": axis is None,
         "keepdims": keepdim, "dtype": convert_dtype(dtype)},
    )


def argsort(x, axis=-1, descending=False, name=None):
    return dispatch("argsort", {"X": [x]}, {"axis": axis, "descending": descending})["Indices"][0]


def sort(x, axis=-1, descending=False, name=None):
    return dispatch("argsort", {"X": [x]}, {"axis": axis, "descending": descending})["Out"][0]


def slice(x, axes, starts, ends, name=None):
    return _d(
        "slice",
        {"Input": [x]},
        {"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )


def strided_slice(x, axes, starts, ends, strides, name=None):
    return _d(
        "strided_slice",
        {"Input": [x]},
        {"axes": list(axes), "starts": list(starts), "ends": list(ends), "strides": list(strides)},
    )


def take_along_axis(arr, indices, axis, name=None):
    return single(
        dispatch("take_along_axis", {"Input": [arr], "Index": [indices]}, {"Axis": axis}),
        "Result",
    )


def meshgrid(*args, name=None):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    return list(dispatch("meshgrid", {"X": list(args)}, {})["Out"])


def diag(x, offset=0, padding_value=0, name=None):
    return _d("diag_v2", {"X": [_wrap(x)]}, {"offset": offset, "padding_value": padding_value})


def kron(x, y, name=None):
    return _d("kron", {"X": [_wrap(x)], "Y": [_wrap(y)]}, {})


def cross(x, y, axis=None, name=None):
    return _d("cross", {"X": [x], "Y": [y]}, {"dim": axis if axis is not None else -1})


def histogram(input, bins=100, min=0, max=0, name=None):
    return _d("histogram", {"X": [input]}, {"bins": bins, "min": min, "max": max})


def bincount(x, weights=None, minlength=0, name=None):
    ins = {"X": [x]}
    if weights is not None:
        ins["Weights"] = [weights]
    return _d("bincount", ins, {"minlength": minlength})


def multiplex(inputs, index, name=None):
    return _d("multiplex", {"X": list(inputs), "Ids": [index]}, {})


def shape(x):
    return single(dispatch("shape", {"Input": [x]}, {}))


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Parity: paddle.flops — implemented in hapi.dynamic_flops."""
    from .hapi.dynamic_flops import flops as _flops

    return _flops(net, input_size, custom_ops=custom_ops,
                  print_detail=print_detail)


# -- LoDTensorArray surface (dygraph semantics: a python list — the same
# -- thing the reference's dygraph mode uses; fluid/layers/tensor.py
# -- create_array:1480, array_write, array_read, array_length) --------------


def create_array(dtype="float32", initialized_list=None):
    arr = list(initialized_list or [])
    for v in arr:
        if not hasattr(v, "_array"):
            raise TypeError(
                f"create_array initialized_list expects Tensors, got {type(v)}")
    return arr


def array_write(x, i, array=None):
    idx = int(np.asarray(i._array if hasattr(i, "_array") else i))
    if array is None:
        array = []
    if idx > len(array):
        # reference dygraph path asserts i <= len(array): a gap would make
        # a later array_read return nothing, crashing far from the bad write
        raise IndexError(
            f"array_write index {idx} out of range for array of length "
            f"{len(array)} (must be <= length)")
    if idx == len(array):
        array.append(x)
    else:
        array[idx] = x
    return array


def array_read(array, i):
    idx = int(np.asarray(i._array if hasattr(i, "_array") else i))
    return array[idx]


def array_length(array):
    from .dygraph.tensor import Tensor

    return Tensor(np.asarray(len(array), dtype="int64"), stop_gradient=True)


# ---------------------------------------------------------------------------
# method / operator patching (math_op_patch parity)
# ---------------------------------------------------------------------------

_METHODS = {
    "add": add, "subtract": subtract, "multiply": multiply, "divide": divide,
    "matmul": matmul, "mm": mm, "bmm": bmm, "dot": dot, "pow": pow,
    "mod": mod, "floor_divide": floor_divide, "maximum": maximum,
    "minimum": minimum, "abs": abs, "sqrt": sqrt, "rsqrt": rsqrt,
    "square": square, "exp": exp, "log": log, "sin": sin, "cos": cos,
    "tanh": tanh, "floor": floor, "ceil": ceil, "round": round,
    "sign": sign, "reciprocal": reciprocal, "clip": clip, "scale": scale,
    "sum": sum, "mean": mean, "max": max, "min": min, "prod": prod,
    "norm": norm, "cumsum": cumsum, "isnan": isnan, "isinf": isinf,
    "isfinite": isfinite, "equal": equal, "not_equal": not_equal,
    "less_than": less_than, "less_equal": less_equal,
    "greater_than": greater_than, "greater_equal": greater_equal,
    "equal_all": equal_all, "allclose": allclose,
    "logical_and": logical_and, "logical_or": logical_or,
    "logical_not": logical_not, "logical_xor": logical_xor,
    "reshape": reshape, "flatten": flatten, "squeeze": squeeze,
    "unsqueeze": unsqueeze, "transpose": transpose, "concat": concat,
    "split": split, "chunk": chunk, "expand": expand, "expand_as": expand_as,
    "tile": tile, "gather": gather, "gather_nd": gather_nd,
    "scatter": scatter, "index_select": index_select,
    "masked_select": masked_select, "where": where, "nonzero": nonzero,
    "roll": roll, "flip": flip, "tril": tril, "triu": triu, "unique": unique,
    "topk": topk, "argmax": argmax, "argmin": argmin, "argsort": argsort,
    "sort": sort, "slice": slice, "strided_slice": strided_slice,
    "broadcast_to": broadcast_to, "unstack": unstack, "stack": None,
    "take_along_axis": take_along_axis, "dist": dist,
}


def _patch(cls):
    for name, fn in _METHODS.items():
        if fn is None or hasattr(cls, name):
            continue
        setattr(cls, name, fn)

    cls.__add__ = lambda s, o: add(s, o)
    cls.__radd__ = lambda s, o: add(o, s)
    cls.__sub__ = lambda s, o: subtract(s, o)
    cls.__rsub__ = lambda s, o: subtract(o, s)
    cls.__mul__ = lambda s, o: multiply(s, o)
    cls.__rmul__ = lambda s, o: multiply(o, s)
    cls.__truediv__ = lambda s, o: divide(s, o)
    cls.__rtruediv__ = lambda s, o: divide(o, s)
    cls.__floordiv__ = lambda s, o: floor_divide(s, o)
    cls.__mod__ = lambda s, o: mod(s, o)
    cls.__pow__ = lambda s, o: pow(s, o)
    cls.__rpow__ = lambda s, o: pow(_wrap(o, like=s), s)
    cls.__matmul__ = lambda s, o: matmul(s, o)
    cls.__neg__ = lambda s: scale(s, -1.0)
    cls.__abs__ = lambda s: globals()["abs"](s)
    cls.__eq__ = lambda s, o: equal(s, o)
    cls.__ne__ = lambda s, o: not_equal(s, o)
    cls.__lt__ = lambda s, o: less_than(s, o)
    cls.__le__ = lambda s, o: less_equal(s, o)
    cls.__gt__ = lambda s, o: greater_than(s, o)
    cls.__ge__ = lambda s, o: greater_equal(s, o)


_patch(Tensor)
Tensor.__hash__ = lambda self: id(self)
_patch(fw.Variable)
fw.Variable.__hash__ = lambda self: id(self)
fw.Variable.cast = lambda self, dtype: cast(self, dtype)
Tensor.numpy = Tensor.numpy  # keep explicit


# ---------------------------------------------------------------------------
# surface-completeness batch (reference python/paddle/__init__.py parity)
# ---------------------------------------------------------------------------


def erf(x, name=None):
    return _d("erf", {"X": [x]})


def expm1(x, name=None):
    return _d("expm1", {"X": [x]})


def lgamma(x, name=None):
    return _d("lgamma", {"X": [x]})


def digamma(x, name=None):
    return _d("digamma", {"X": [x]})


def trunc(x, name=None):
    return _d("trunc", {"X": [x]})


def conj(x, name=None):
    return _d("conj", {"X": [x]})


def real(x, name=None):
    return _d("real", {"X": [x]})


def imag(x, name=None):
    return _d("imag", {"X": [x]})


def atan2(x, y, name=None):
    return _d("atan2", {"X": [x], "Y": [y]})


def bitwise_and(x, y, out=None, name=None):
    return _d("bitwise_and", {"X": [x], "Y": [y]})


def bitwise_or(x, y, out=None, name=None):
    return _d("bitwise_or", {"X": [x], "Y": [y]})


def bitwise_xor(x, y, out=None, name=None):
    return _d("bitwise_xor", {"X": [x], "Y": [y]})


def bitwise_not(x, out=None, name=None):
    return _d("bitwise_not", {"X": [x]})


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _d("stanh", {"X": [x]}, {"scale_a": scale_a, "scale_b": scale_b})


def logsumexp(x, axis=None, keepdim=False, name=None):
    attrs = {"keepdim": keepdim}
    if axis is None:
        attrs["reduce_all"] = True
    else:
        attrs["axis"] = [axis] if isinstance(axis, int) else list(axis)
    return _d("logsumexp", {"X": [x]}, attrs)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return _d("trace", {"Input": [x]},
              {"offset": offset, "axis1": axis1, "axis2": axis2})


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _d("diagonal", {"Input": [x]},
              {"offset": offset, "axis1": axis1, "axis2": axis2})


def diagflat(x, offset=0, name=None):
    return _d("diagflat", {"X": [x]}, {"offset": offset})


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    attrs = {"unbiased": bool(unbiased), "keep_dim": keepdim}
    if axis is None:
        attrs["reduce_all"] = True
    else:
        attrs["dim"] = [axis] if isinstance(axis, int) else list(axis)
    return _d("reduce_std", {"X": [x]}, attrs)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    attrs = {"unbiased": bool(unbiased), "keep_dim": keepdim}
    if axis is None:
        attrs["reduce_all"] = True
    else:
        attrs["dim"] = [axis] if isinstance(axis, int) else list(axis)
    return _d("reduce_var", {"X": [x]}, attrs)


def median(x, axis=None, keepdim=False, name=None):
    return _d("median", {"X": [x]}, {"axis": axis, "keepdim": keepdim})


def reverse(x, axis, name=None):
    return _d("reverse", {"X": [x]},
              {"axis": [axis] if isinstance(axis, int) else list(axis)})


def multinomial(x, num_samples=1, replacement=False, name=None):
    return _d("multinomial", {"X": [x]},
              {"num_samples": num_samples, "replacement": bool(replacement)})


def index_sample(x, index):
    return _d("index_sample", {"X": [x], "Index": [index]})


def scatter_nd(index, updates, shape, name=None):
    """Parity: paddle.scatter_nd — scatter into zeros of ``shape``."""
    z = zeros(shape, dtype=updates.dtype)
    return scatter_nd_add(z, index, updates)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    if not 0 <= shard_id < nshards:
        raise ValueError(
            f"shard_id({shard_id}) must be in [0, nshards({nshards}))")
    return _d("shard_index", {"X": [input]},
              {"index_num": index_num, "nshards": nshards,
               "shard_id": shard_id, "ignore_value": ignore_value})


def crop(x, shape=None, offsets=None, name=None):
    xs = list(x.shape)
    offsets = [int(o) for o in offsets] if offsets is not None else [0] * len(xs)
    shape = list(shape) if shape is not None else xs
    # paddle semantics: -1/None means "to the end" = input dim minus offset
    shape = [xs[i] - offsets[i] if (s is None or int(s) < 0) else int(s)
             for i, s in enumerate(shape)]
    return _d("crop_tensor", {"X": [x]},
              {"offsets": offsets, "shape": shape})


crop_tensor = crop


def neg(x, name=None):
    return scale(x, scale=-1.0)


def all(x, axis=None, keepdim=False, name=None):
    attrs = {"keep_dim": keepdim}
    if axis is None:
        attrs["reduce_all"] = True
    else:
        attrs["dim"] = [axis] if isinstance(axis, int) else list(axis)
    return _d("reduce_all", {"X": [x]}, attrs)


def any(x, axis=None, keepdim=False, name=None):
    attrs = {"keep_dim": keepdim}
    if axis is None:
        attrs["reduce_all"] = True
    else:
        attrs["dim"] = [axis] if isinstance(axis, int) else list(axis)
    return _d("reduce_any", {"X": [x]}, attrs)


def floor_mod(x, y, name=None):
    return mod(x, y)


def is_empty(x, name=None):
    import numpy as _np

    n = int(_np.prod(x.shape)) if 0 not in x.shape else 0
    return full([1], n == 0, dtype="bool")


def rank(input):
    return to_tensor(np.asarray(len(input.shape), "int32"))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def broadcast_tensors(input, name=None):
    tgt = list(np.broadcast_shapes(*[tuple(t.shape) for t in input]))
    return [broadcast_to(t, tgt) for t in input]


def standard_normal(shape, dtype=None, name=None):
    return randn(shape, dtype=dtype)


def unbind(input, axis=0):
    """Parity: paddle.unbind — split + squeeze along ``axis``."""
    n = input.shape[axis]
    parts = split(input, n, axis=axis)
    return [squeeze(p, [axis]) for p in parts]


def tolist(x):
    return np.asarray(x.numpy()).tolist()


def assign(x, output=None):
    """Parity: paddle.assign (assign_op.cc) — copy into ``output`` or a new
    tensor."""
    if not is_tensor(x):
        x = _wrap(x)
    out = _d("assign", {"X": [x]})
    if output is not None:
        output.set_value(out.numpy() if hasattr(out, "numpy") else out)
        return output
    return out


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """Parity: paddle.addmm (addmm_op.cc): beta*input + alpha*(x @ y)."""
    return _d("addmm", {"Input": [input], "X": [x], "Y": [y]},
              {"Beta": float(beta), "Alpha": float(alpha)})


# -- in-place surface variants (reference *_ API) ---------------------------
# Tape-safe: when the receiver has gradient history, the mutation goes
# through Tensor._taped_inplace (version-bump clone + consumer re-pointing,
# so the record's outputs re-home onto the receiver and backward stays
# correct); otherwise the array is rebound directly (same split scale_ /
# fill_ use, dygraph/tensor.py).


def _inplace_apply(x, fn, tensor_inputs, name):
    from .dygraph import tracer as _tr

    if _tr.has_grad() and x.grad_node is not None:
        return x._taped_inplace(fn, list(tensor_inputs), name=name)
    import jax.numpy as _jnp  # noqa: F401  (fn may close over jnp)

    x._array = fn(x._array, *[t._array for t in tensor_inputs])
    return x


def _resolve_reshape(shape, cur_shape):
    """paddle reshape semantics: 0 copies the input dim, one -1 is
    inferred."""
    out = [cur_shape[i] if int(s) == 0 else int(s)
           for i, s in enumerate(shape)]
    if out.count(-1) > 1:
        raise ValueError(f"only one -1 allowed in shape, got {shape}")
    if -1 in out:
        import numpy as _np

        known = int(_np.prod([s for s in out if s != -1])) or 1
        total = int(_np.prod(cur_shape)) if cur_shape else 1
        out[out.index(-1)] = total // known
    return out


def reshape_(x, shape, name=None):
    import jax.numpy as jnp

    tgt = _resolve_reshape(list(shape), list(x.shape))
    return _inplace_apply(x, lambda a: jnp.reshape(a, tgt), (), "reshape_")


def squeeze_(x, axis=None, name=None):
    import jax.numpy as jnp

    ax = (tuple(axis) if isinstance(axis, (list, tuple))
          else (axis,) if axis is not None else None)
    return _inplace_apply(x, lambda a: jnp.squeeze(a, axis=ax), (),
                          "squeeze_")


def unsqueeze_(x, axis, name=None):
    import jax.numpy as jnp

    axes = sorted(axis if isinstance(axis, (list, tuple)) else [axis])

    def fn(a):
        for ax in axes:
            a = jnp.expand_dims(a, ax)
        return a

    return _inplace_apply(x, fn, (), "unsqueeze_")


def tanh_(x, name=None):
    import jax.numpy as jnp

    return _inplace_apply(x, jnp.tanh, (), "tanh_")


def scatter_(x, index, updates, overwrite=True, name=None):
    def fn(a, idx, upd):
        return a.at[idx].set(upd) if overwrite else a.at[idx].add(upd)

    return _inplace_apply(x, fn, (index, updates), "scatter_")
