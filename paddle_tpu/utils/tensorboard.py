"""Hand-encoded TensorBoard event files — no tensorboard/protobuf deps.

Role parity: the reference's VisualDL training visualization
(``/root/reference/python/paddle/hapi/callbacks.py`` VisualDL callback).
VisualDL itself is not in this build; TensorBoard's event format is the
open equivalent every viewer reads, and its wire format is small enough
to emit directly (the same trick as ``onnx/proto.py``):

  * TFRecord framing: [len u64le][masked-crc32c(len)][payload]
    [masked-crc32c(payload)], crc32c = Castagnoli polynomial;
  * ``Event`` proto: wall_time (1, double), step (2, int64),
    file_version (3, string) | summary (5, message);
  * ``Summary.Value``: tag (1, string), simple_value (2, float).

``SummaryWriter`` mirrors the tensorboardX/VisualDL ``add_scalar``
surface, so ``tensorboard --logdir <dir>`` opens the output directly.
"""

from __future__ import annotations

import os
import socket
import struct
import time

from ..onnx.proto import f_bytes, f_string, f_varint

__all__ = ["SummaryWriter", "read_events", "read_scalars"]

# -- crc32c (Castagnoli, table-driven) --------------------------------------

_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ (poly if c & 1 else 0)
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    table = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = table[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _record(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (header + struct.pack("<I", _masked_crc(header))
            + payload + struct.pack("<I", _masked_crc(payload)))


# -- Event / Summary protos --------------------------------------------------


def _f_double(field: int, value: float) -> bytes:
    from ..onnx.proto import _tag

    return _tag(field, 1) + struct.pack("<d", float(value))


def _f_float32(field: int, value: float) -> bytes:
    from ..onnx.proto import _tag

    return _tag(field, 5) + struct.pack("<f", float(value))


def _event(wall_time: float, step: int = 0, file_version: str = None,
           summary: bytes = None) -> bytes:
    msg = _f_double(1, wall_time)
    if step:
        msg += f_varint(2, int(step))
    if file_version is not None:
        msg += f_string(3, file_version)
    if summary is not None:
        msg += f_bytes(5, summary)
    return msg


def _scalar_summary(tag: str, value: float) -> bytes:
    val = f_string(1, tag) + _f_float32(2, value)
    return f_bytes(1, val)


# -- reader ------------------------------------------------------------------
#
# The writer above framed records for years with nothing checking its own
# output beyond "tensorboard opens it".  This reader closes the loop: it
# deframes TFRecords VERIFYING both masked CRCs (a corrupt byte fails
# loudly instead of skewing a chart) and parses the Event/Summary protos
# with the repo's own proto reader — write scalars, read back
# (tag, step, value), asserted in tests/test_tensorboard_hdfs.py.


def read_events(path: str) -> list:
    """Deframe one event file into raw Event dicts
    ``{wall_time, step, file_version, summary}`` (``summary`` is the
    still-encoded Summary message or None).  Raises ``ValueError`` on a
    truncated record or a CRC mismatch."""
    from ..onnx.proto import parse_message

    with open(path, "rb") as f:
        raw = f.read()
    out, pos = [], 0
    while pos < len(raw):
        if pos + 12 > len(raw):
            raise ValueError(f"truncated record header at byte {pos}")
        (ln,) = struct.unpack_from("<Q", raw, pos)
        (lcrc,) = struct.unpack_from("<I", raw, pos + 8)
        if lcrc != _masked_crc(raw[pos:pos + 8]):
            raise ValueError(f"length CRC mismatch at byte {pos}")
        if pos + 12 + ln + 4 > len(raw):
            raise ValueError(f"truncated record payload at byte {pos}")
        payload = raw[pos + 12:pos + 12 + ln]
        (pcrc,) = struct.unpack_from("<I", raw, pos + 12 + ln)
        if pcrc != _masked_crc(payload):
            raise ValueError(f"payload CRC mismatch at byte {pos}")
        pos += 12 + ln + 4
        msg = parse_message(payload)
        out.append({
            "wall_time": msg.get(1, [0.0])[0],
            "step": int(msg.get(2, [0])[0]),
            "file_version": (msg[3][0].decode()
                             if 3 in msg else None),
            "summary": msg.get(5, [None])[0],
        })
    return out


def read_scalars(path_or_dir: str) -> dict:
    """Read every scalar out of an event file — or out of every
    ``events.out.tfevents.*`` under a log dir — as
    ``{tag: [(step, value), ...]}`` in write order."""
    from ..onnx.proto import parse_message

    if os.path.isdir(path_or_dir):
        paths = sorted(
            os.path.join(path_or_dir, f) for f in os.listdir(path_or_dir)
            if f.startswith("events.out.tfevents."))
    else:
        paths = [path_or_dir]
    out: dict = {}
    for path in paths:
        for ev in read_events(path):
            if ev["summary"] is None:
                continue
            summ = parse_message(ev["summary"])
            for val_msg in summ.get(1, []):
                val = parse_message(val_msg)
                if 1 not in val or 2 not in val:
                    continue      # not a simple_value summary
                tag = val[1][0].decode()
                out.setdefault(tag, []).append(
                    (ev["step"], float(val[2][0])))
    return out


class SummaryWriter:
    """Minimal ``add_scalar`` writer producing real TB event files."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        os.makedirs(log_dir, exist_ok=True)
        host = socket.gethostname() or "host"
        self._path = os.path.join(
            log_dir, f"events.out.tfevents.{int(time.time())}.{host}")
        self._f = open(self._path, "ab")
        self._f.write(_record(_event(time.time(),
                                     file_version="brain.Event:2")))
        self._f.flush()

    def add_scalar(self, tag: str, value, step: int = 0,
                   walltime: float = None):
        import numpy as np

        v = float(np.asarray(
            value.numpy() if hasattr(value, "numpy") else value))
        self._f.write(_record(_event(
            walltime if walltime is not None else time.time(),
            step=step, summary=_scalar_summary(tag, v))))

    def flush(self):
        self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()
