"""Weights-cache resolution — ``paddle.utils.download``.

Role parity: ``/root/reference/python/paddle/utils/download.py``
(``get_weights_path_from_url``:386-file module — URL fetch + md5-checked
cache under ``~/.cache/paddle``).  This build runs in a zero-egress
environment: the same cache layout is honored (a pre-seeded file is
found, md5-verified, and reused), and a missing file raises with the
exact path to place it at instead of attempting a network fetch.
"""

from __future__ import annotations

import hashlib
import os
import os.path as osp

__all__ = ["get_weights_path_from_url"]

WEIGHTS_HOME = osp.expanduser("~/.cache/paddle/hapi/weights")


def _md5check(fullname: str, md5sum: str | None) -> bool:
    if md5sum is None:
        return True
    md5 = hashlib.md5()
    with open(fullname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            md5.update(chunk)
    return md5.hexdigest() == md5sum


def get_path_from_url(url: str, root_dir: str, md5sum: str | None = None,
                      check_exist: bool = True) -> str:
    fname = osp.split(url)[-1]
    fullname = osp.join(root_dir, fname)
    if osp.exists(fullname) and (not check_exist
                                 or _md5check(fullname, md5sum)):
        return fullname
    raise RuntimeError(
        f"weights file {fname!r} not found in the local cache and this "
        f"environment has no network egress.  Place the file (from {url}) "
        f"at: {fullname}")


def get_weights_path_from_url(url: str, md5sum: str | None = None) -> str:
    """Resolve a weights URL to a local cached path (zero-egress: cache
    lookup only; reference downloads on miss)."""
    os.makedirs(WEIGHTS_HOME, exist_ok=True)
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
