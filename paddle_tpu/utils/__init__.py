"""``paddle.utils`` — extension loading and misc utilities.

Parity: ``/root/reference/python/paddle/utils/`` (cpp_extension, op
library loading)."""

from . import cpp_extension  # noqa: F401
from .cpp_extension import load_op_library  # noqa: F401
