"""``paddle.utils`` — extension loading and misc utilities.

Parity: ``/root/reference/python/paddle/utils/__init__.py`` —
``deprecated`` (deprecated.py:119), ``try_import`` (lazy_import.py),
``run_check`` (install_check.py), ``require_version``
(fluid/framework.py), ``unique_name``, ``download``, ``cpp_extension``,
and the profiler re-exports.
"""

from . import cpp_extension  # noqa: F401
from . import download  # noqa: F401
from .cpp_extension import load_op_library  # noqa: F401
from ..framework import unique_name  # noqa: F401
from ..profiler import Profiler, ProfilerOptions, get_profiler  # noqa: F401

__all__ = ["deprecated", "run_check", "require_version", "try_import"]


def deprecated(update_to="", since="", reason=""):
    """Mark an API deprecated: amend the docstring and warn once per call
    site (reference utils/deprecated.py)."""
    import functools
    import warnings

    def decorator(func):
        msg = f"API \"{func.__module__}.{func.__name__}\" is deprecated"
        if update_to:
            msg += f", please use \"{update_to}\" instead"
        if since:
            msg += f" since {since}"
        if reason:
            msg += f", reason: {reason}"
        func.__doc__ = (f"\n    Warning:\n        {msg}\n\n"
                        + (func.__doc__ or ""))

        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)

        return wrapper

    return decorator


def try_import(module_name, err_msg=None):
    """Import a soft dependency with an actionable error
    (reference utils/lazy_import.py)."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg is None:
            err_msg = (f"Failed importing {module_name}. This likely means "
                       f"that some paddle modules require additional "
                       f"dependencies that have to be manually installed "
                       f"(usually with `pip install {module_name}`).")
        raise ImportError(err_msg)


def require_version(min_version, max_version=None):
    """Check the installed framework version against a range
    (reference fluid/framework.py require_version)."""
    import paddle_tpu

    def parse(v):
        return tuple(int(p) for p in str(v).split(".")[:3] if p.isdigit())

    cur = parse(paddle_tpu.__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"VersionError: paddle_tpu version {paddle_tpu.__version__} is "
            f"below the required minimum {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"VersionError: paddle_tpu version {paddle_tpu.__version__} is "
            f"above the allowed maximum {max_version}")


def run_check():
    """Sanity-check the install: run a small matmul + grad on the live
    backend and report (reference utils/install_check.py run_check)."""
    import numpy as np

    import paddle_tpu as paddle

    dev = paddle.get_device()
    x = paddle.to_tensor(np.ones((4, 4), "float32"), stop_gradient=False)
    w = paddle.to_tensor(np.full((4, 4), 0.5, "float32"),
                         stop_gradient=False)
    y = paddle.matmul(x, w).sum()
    y.backward()
    got = float(np.asarray(y.numpy()))
    assert abs(got - 32.0) < 1e-4, f"matmul check failed: {got}"
    g = np.asarray(x.grad.numpy())
    assert np.allclose(g, 2.0), "backward check failed"
    print(f"PaddlePaddle (paddle_tpu) is installed successfully! "
          f"Device: {dev}.")
