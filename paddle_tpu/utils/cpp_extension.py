"""Custom C++ op loading — the ``paddle.utils.cpp_extension`` surface.

Parity: ``/root/reference/python/paddle/utils/cpp_extension/`` (``load``:
runtime g++ compile of user sources; ``paddle.utils.load_op_library`` ≙
``load_op_library`` here) over the C ABI in
``paddle_tpu/extension/paddle_tpu_ext.h`` (the reference's
``custom_operator.cc`` + PD_BUILD_OP role).

TPU-first: the loaded kernels execute as XLA host callbacks
(``jax.pure_callback``) — they compose with jit/vmap-free graphs and the
static Executor, run on the host CPU, and (when a ``pt_<name>_backward``
symbol exists) participate in autograd through a registered grad op.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
import tempfile
from types import SimpleNamespace
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["load", "load_op_library", "get_include"]

_MAX_DIMS = 8

_DTYPES = ["float32", "float64", "int32", "int64", "uint8", "bool",
           "bfloat16"]


def get_include() -> str:
    """Directory containing ``paddle_tpu_ext.h``."""
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "extension")


class _PTTensor(ctypes.Structure):
    _fields_ = [
        ("data", ctypes.c_void_p),
        ("dims", ctypes.c_int64 * _MAX_DIMS),
        ("ndim", ctypes.c_int32),
        ("dtype", ctypes.c_int32),
    ]


def _np_to_pt(arr: np.ndarray) -> _PTTensor:
    t = _PTTensor()
    t.data = arr.ctypes.data_as(ctypes.c_void_p)
    for i, d in enumerate(arr.shape):
        t.dims[i] = d
    t.ndim = arr.ndim
    t.dtype = _DTYPES.index(str(arr.dtype))
    return t


def _dtype_code(dt) -> int:
    return _DTYPES.index(str(np.dtype(dt)))


class _CustomOp:
    """One op's C entry points + the registered framework kernel."""

    def __init__(self, lib, name: str):
        self.name = name
        self._n_out = int(getattr(lib, f"pt_{name}_num_outputs")())
        self._infer = getattr(lib, f"pt_{name}_infer_shape")
        self._fwd = getattr(lib, f"pt_{name}_forward")
        self._bwd = getattr(lib, f"pt_{name}_backward", None)

    def infer(self, shapes: Sequence[tuple], dtypes: Sequence[str]):
        n_in = len(shapes)
        in_dims = (ctypes.c_int64 * (_MAX_DIMS * n_in))()
        in_ndims = (ctypes.c_int32 * n_in)()
        in_dtypes = (ctypes.c_int32 * n_in)()
        for i, (sh, dt) in enumerate(zip(shapes, dtypes)):
            in_ndims[i] = len(sh)
            in_dtypes[i] = _dtype_code(dt)
            for j, d in enumerate(sh):
                in_dims[i * _MAX_DIMS + j] = d
        out_dims = (ctypes.c_int64 * (_MAX_DIMS * self._n_out))()
        out_ndims = (ctypes.c_int32 * self._n_out)()
        out_dtypes = (ctypes.c_int32 * self._n_out)()
        rc = self._infer(in_dims, in_ndims, in_dtypes, n_in,
                         out_dims, out_ndims, out_dtypes)
        if rc != 0:
            raise RuntimeError(f"custom op {self.name}: infer_shape rc={rc}")
        outs = []
        for k in range(self._n_out):
            shape = tuple(out_dims[k * _MAX_DIMS + j]
                          for j in range(out_ndims[k]))
            outs.append((shape, _DTYPES[out_dtypes[k]]))
        return outs

    def _call_c(self, fn, arrays: List[np.ndarray], out_specs):
        ins = (_PTTensor * len(arrays))(*[_np_to_pt(a) for a in arrays])
        out_arrays = [np.empty(sh, dtype=dt) for sh, dt in out_specs]
        outs = (_PTTensor * len(out_arrays))(
            *[_np_to_pt(a) for a in out_arrays])
        rc = fn(ins, len(arrays), outs, len(out_arrays))
        if rc != 0:
            raise RuntimeError(f"custom op {self.name}: kernel rc={rc}")
        return out_arrays

    def forward_host(self, *arrays):
        arrays = [np.ascontiguousarray(np.asarray(a)) for a in arrays]
        specs = self.infer([a.shape for a in arrays],
                           [a.dtype for a in arrays])
        return tuple(self._call_c(self._fwd, arrays, specs))

    def backward_host(self, n_grad_in, *arrays):
        arrays = [np.ascontiguousarray(np.asarray(a)) for a in arrays]
        # grad inputs match the ORIGINAL inputs' shapes/dtypes
        specs = [(a.shape, a.dtype) for a in arrays[:n_grad_in]]
        return tuple(self._call_c(self._bwd, arrays, specs))


def _mark_custom(op_type: str) -> None:
    """Tag extension ops so framework-wide sweeps (tests/test_op_sweep.py
    coverage gate) can tell them apart from built-ins."""
    from ..ops import registry

    registry.get_op_def(op_type).is_custom = True


def _register(op: _CustomOp):
    """Register the op (and its grad when available) with the framework."""
    import jax

    from ..ops.registry import GRAD_SUFFIX, register_op

    def fwd_kernel(ins, attrs):
        xs = ins["X"]
        specs = op.infer([tuple(x.shape) for x in xs],
                         [str(x.dtype) for x in xs])
        result_shapes = [jax.ShapeDtypeStruct(sh, np.dtype(dt))
                         for sh, dt in specs]

        def cb(*arrays):
            return op.forward_host(*arrays)

        outs = jax.pure_callback(cb, tuple(result_shapes), *xs)
        return {"Out": list(outs)}

    if op._bwd is not None:
        grad_type = op.name + "_grad"

        def grad_kernel(ins, attrs):
            xs = ins["X"]
            gouts = ins["Out" + GRAD_SUFFIX]
            n = len(xs)
            result_shapes = [jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
                             for x in xs]

            def cb(*arrays):
                return op.backward_host(n, *arrays)

            grads = jax.pure_callback(cb, tuple(result_shapes),
                                      *(list(xs) + list(gouts)))
            return {"X" + GRAD_SUFFIX: list(grads)}

        register_op(grad_type, list_slots=("X", "Out" + GRAD_SUFFIX,
                                           "X" + GRAD_SUFFIX),
                    no_grad=True)(grad_kernel)
        _mark_custom(grad_type)

        def grad_maker(fwd_op, no_grad_set):
            return [{
                "type": grad_type,
                "inputs": {
                    "X": list(fwd_op.input("X")),
                    "Out" + GRAD_SUFFIX: [n + GRAD_SUFFIX
                                          for n in fwd_op.output("Out")],
                },
                "outputs": {
                    # "" placeholders keep positional alignment with the
                    # kernel's returned grad list (registry default-maker
                    # convention) when some inputs are in no_grad_set
                    "X" + GRAD_SUFFIX: [(n + GRAD_SUFFIX)
                                        if n not in no_grad_set else ""
                                        for n in fwd_op.input("X")],
                },
                "attrs": dict(fwd_op.attrs),
            }]

        register_op(op.name, list_slots=("X", "Out"),
                    grad_maker=grad_maker)(fwd_kernel)
    else:
        register_op(op.name, list_slots=("X", "Out"),
                    no_grad=True)(fwd_kernel)
    _mark_custom(op.name)

    def surface(*tensors):
        from ..ops.dispatch import dispatch

        outs = dispatch(op.name, {"X": list(tensors)}, {})["Out"]
        return outs[0] if len(outs) == 1 else outs

    surface.__name__ = op.name
    return surface


def load_op_library(path: str):
    """Parity: ``paddle.utils.load_op_library`` — load a compiled .so and
    register every op it exports; returns a namespace of callables.
    Colliding with a BUILT-IN op raises (reference duplicate-registration
    semantics); re-loading a custom op of the same name replaces it."""
    from ..ops import registry

    lib = ctypes.CDLL(os.path.abspath(path))
    lib.pt_op_list.restype = ctypes.c_char_p
    names = lib.pt_op_list().decode().split(",")
    ns = SimpleNamespace()
    for raw in names:
        name = raw.strip()
        if not name:
            continue
        if registry.is_registered(name) and not getattr(
                registry.get_op_def(name), "is_custom", False):
            raise ValueError(
                f"custom op {name!r} collides with a built-in framework op")
        setattr(ns, name, _register(_CustomOp(lib, name)))
    ns._library_path = os.path.abspath(path)
    return ns


def compile_cached(name: str, sources: Sequence[str],
                   extra_cflags: Optional[list] = None,
                   extra_include_paths: Optional[list] = None,
                   extra_ldflags: Optional[list] = None,
                   hash_extra_files: Optional[list] = None,
                   build_directory: Optional[str] = None,
                   verbose: bool = False) -> str:
    """Compile C++ sources to a shared library with a content-hash build
    cache; returns the .so path.  Shared by :func:`load` (custom ops) and
    the DataLoader shm-ring transport (``io/shm_ring.py``).

    Raises RuntimeError on compile failure and OSError/FileNotFoundError
    when no compiler exists — callers that have a fallback catch those."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_dir, exist_ok=True)
    h = hashlib.sha1()
    for src in list(sources) + list(hash_extra_files or []):
        with open(src, "rb") as f:
            h.update(f.read())
    h.update(repr((sorted(extra_cflags or []),
                   sorted(extra_include_paths or []),
                   sorted(extra_ldflags or []))).encode())
    so_path = os.path.join(build_dir, f"lib{name}_{h.hexdigest()[:12]}.so")
    if not os.path.exists(so_path):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++14"]
        for inc in extra_include_paths or []:
            cmd.append(f"-I{inc}")
        cmd += list(extra_cflags or [])
        cmd += [os.path.abspath(s) for s in sources]
        # compile to a temp name + atomic rename: an interrupted/concurrent
        # g++ must never leave a half-written .so that later loads treat as
        # a valid cache hit
        tmp_path = f"{so_path}.tmp.{os.getpid()}"
        cmd += ["-o", tmp_path]
        cmd += list(extra_ldflags or [])
        if verbose:
            print("cpp_extension:", " ".join(cmd), file=sys.stderr)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{proc.stderr[-4000:]}")
        os.replace(tmp_path, so_path)
    return so_path


def load(name: str, sources: Sequence[str], extra_cflags: Optional[list]
         = None, extra_include_paths: Optional[list] = None,
         build_directory: Optional[str] = None, verbose: bool = False):
    """Parity: ``paddle.utils.cpp_extension.load`` — compile user C++
    sources into a shared library with g++ and register the exported ops.
    Recompiles only when sources change (content-hash build cache)."""
    header = os.path.join(get_include(), "paddle_tpu_ext.h")
    so_path = compile_cached(
        name, sources, extra_cflags=extra_cflags,
        extra_include_paths=[get_include()] + list(extra_include_paths or []),
        hash_extra_files=[header], build_directory=build_directory,
        verbose=verbose)
    return load_op_library(so_path)
