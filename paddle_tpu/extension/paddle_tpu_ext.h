/* paddle_tpu custom-op C ABI.
 *
 * Parity role: the reference's custom-operator extension ABI
 * (paddle/fluid/framework/custom_operator.cc + extension/include/ext_*.h:
 * PD_BUILD_OP macro family).  TPU-first twist: the framework's compute
 * graph is XLA, so a custom C++ kernel executes as an XLA HOST CALLBACK
 * (jax.pure_callback) — correct everywhere, host-speed; device-resident
 * custom kernels should be written as Pallas instead (kernels/ guide).
 *
 * Contract per op <name> exported from the shared library:
 *   int pt_<name>_num_outputs(void);
 *   int pt_<name>_infer_shape(const int64_t* in_dims, const int32_t* in_ndims,
 *                             const int32_t* in_dtypes, int n_in,
 *                             int64_t* out_dims, int32_t* out_ndims,
 *                             int32_t* out_dtypes);   // dims arrays are
 *                                                     // PT_MAX_DIMS-strided
 *   int pt_<name>_forward(const PT_Tensor* ins, int n_in,
 *                         PT_Tensor* outs, int n_out);
 *   // optional — enables autograd through the op:
 *   int pt_<name>_backward(const PT_Tensor* ins_and_gradouts, int n_in,
 *                          PT_Tensor* grad_ins, int n_out);
 * plus one library-level symbol listing the ops:
 *   const char* pt_op_list(void);   // "relu2,my_gelu"
 * All functions return 0 on success.  Output buffers are allocated by the
 * framework from infer_shape results before forward/backward run.
 */
#ifndef PADDLE_TPU_EXT_H_
#define PADDLE_TPU_EXT_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define PT_MAX_DIMS 8

/* dtype codes (keep in sync with utils/cpp_extension.py _DTYPES) */
enum PT_DType {
  PT_FLOAT32 = 0,
  PT_FLOAT64 = 1,
  PT_INT32 = 2,
  PT_INT64 = 3,
  PT_UINT8 = 4,
  PT_BOOL = 5,
  PT_BFLOAT16 = 6,
};

typedef struct {
  void* data;
  int64_t dims[PT_MAX_DIMS];
  int32_t ndim;
  int32_t dtype;
} PT_Tensor;

static inline int64_t pt_numel(const PT_Tensor* t) {
  int64_t n = 1;
  for (int32_t i = 0; i < t->ndim; ++i) n *= t->dims[i];
  return n;
}

/* single-translation-unit convenience: PT_EXPORT_OPS("relu2,my_op") */
#define PT_EXPORT_OPS(names) \
  const char* pt_op_list(void) { return names; }

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_EXT_H_ */
