"""``paddle.optimizer`` — Optimizer base + SGD/Momentum/Adam/AdamW/Lamb/
RMSProp/Adagrad + lr schedulers.

Parity: ``/root/reference/python/paddle/optimizer/optimizer.py`` (base:
accumulators, regularization, grad clip, minimize/step/clear_grad) and the
per-optimizer modules (adam.py, adamw.py, momentum.py, lamb.py, sgd.py,
rmsprop.py, adagrad.py); schedulers in lr.py.

Both modes share ONE update-kernel path: in static mode the update op is
appended with outputs bound to the SAME persistable vars (executor donates →
in-place in HBM); in dygraph the kernel runs eagerly and the param/state
arrays are rebound.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework import program as fw
from ..framework import unique_name
from ..framework.scope import global_scope
from ..dygraph.tensor import Tensor
from ..dygraph import tracer
from . import lr as lr_sched_mod
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "LarsMomentum", "Adam", "AdamW", "Lamb",
    "RMSProp", "Adagrad", "Adadelta", "Adamax", "lr",
]

lr = lr_sched_mod


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        from ..regularizer import L2Decay

        if isinstance(weight_decay, (int, float)) and not isinstance(weight_decay, bool):
            self.regularization = L2Decay(float(weight_decay))
        else:
            self.regularization = weight_decay
        if isinstance(learning_rate, LRScheduler):
            import weakref

            bound = getattr(learning_rate, "_bound_optimizers", None)
            if bound is None:
                bound = learning_rate._bound_optimizers = weakref.WeakSet()
            bound.add(self)
        self._grad_clip = grad_clip
        # accumulators: acc_name -> param_name -> Tensor (dygraph) / Variable (static)
        self._accumulators: Dict[str, Dict[str, object]] = {}
        # state loaded before the owning accumulator exists (lazy creation);
        # keyed by the serialized name ``{param}_{acc}_0`` and consumed by
        # _add_accumulator (reference Optimizer._accumulators_holder)
        self._accumulators_holder: Dict[str, object] = {}
        self._lr_var = None  # static-mode persistable lr var
        # fp16/bf16 params keep an fp32 master copy (reference multi_precision
        # adam: MasterParam in/out) — enabled by the optimizer arg or by
        # amp.decorate(level='O2')
        self._multi_precision = False

    # -- lr ---------------------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value: float):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)
        self._sync_static_lr()

    def _sync_static_lr(self):
        if self._lr_var is not None:
            import jax.numpy as jnp

            global_scope().set(
                self._lr_var.name, jnp.asarray([self.get_lr()], jnp.float32)
            )

    def _lr_input(self):
        """LearningRate input for update kernels in the current mode."""
        if fw.in_dygraph_mode():
            return Tensor(np.asarray([self.get_lr()], "float32"))
        if self._lr_var is None:
            block = fw.default_main_program().global_block()
            self._lr_var = block.create_var(
                name=unique_name.generate("learning_rate"),
                shape=(1,), dtype="float32", persistable=True, stop_gradient=True,
            )
            sb = fw.default_startup_program().global_block()
            sb.create_var(name=self._lr_var.name, shape=(1,), dtype="float32", persistable=True)
            sb.append_op(
                type="fill_constant", inputs={}, outputs={"Out": [self._lr_var.name]},
                attrs={"shape": [1], "value": self.get_lr(), "dtype": "float32"},
            )
        return self._lr_var

    # -- accumulators -----------------------------------------------------
    def _add_accumulator(self, name: str, param, fill_value: float = 0.0,
                         shape=None, dtype=None):
        store = self._accumulators.setdefault(name, {})
        pname = param.name
        if pname in store:
            return store[pname]
        shape = list(shape if shape is not None else param.shape)
        dtype = dtype or "float32"
        if fw.in_dygraph_mode():
            import jax.numpy as jnp

            from ..framework.dtype import to_jax_dtype

            # Lazily apply state loaded before this accumulator existed
            # (reference: Optimizer._add_accumulator reads
            # _accumulators_holder) — set_state_dict on a fresh optimizer
            # stashes snapshots here under the serialized key name.
            held = self._pop_held(pname, name, to_jax_dtype(dtype), shape)
            if held is not None:
                acc = Tensor(held, stop_gradient=True)
            else:
                acc = Tensor(jnp.full(shape, fill_value, to_jax_dtype(dtype)), stop_gradient=True)
        else:
            block = fw.default_main_program().global_block()
            acc = block.create_var(
                name=unique_name.generate(f"{pname}_{name}"),
                shape=shape, dtype=dtype, persistable=True, stop_gradient=True,
            )
            sb = fw.default_startup_program().global_block()
            sb.create_var(name=acc.name, shape=shape, dtype=dtype, persistable=True)
            sb.append_op(
                type="fill_constant", inputs={}, outputs={"Out": [acc.name]},
                attrs={"shape": shape, "value": fill_value, "dtype": dtype},
            )
        store[pname] = acc
        return acc

    def _pop_held(self, pname, acc_name, jax_dtype, shape=None):
        """Consume a value stashed by set_state_dict for a not-yet-created
        accumulator; returns a jnp array (cast/reshaped) or None."""
        held = self._accumulators_holder.pop(f"{pname}_{acc_name}_0", None)
        if held is None:
            return None
        import jax.numpy as jnp

        arr = jnp.asarray(held, jax_dtype)
        return arr.reshape(shape) if shape is not None else arr

    # -- fp32 master weights (multi_precision parity) ----------------------
    def _master_weight(self, p):
        """fp32 master copy for a low-precision param (created from the
        current value on first touch; amp.decorate pre-seeds it from the
        pristine fp32 weights before casting)."""
        import jax.numpy as jnp

        store = self._accumulators.setdefault("master_weight", {})
        mw = store.get(p.name)
        if mw is None:
            # a checkpointed fp32 master loaded before this param's first
            # step must win over an upcast of the (lossy) low-precision param
            held = self._pop_held(p.name, "master_weight", jnp.float32)
            if held is not None:
                mw = Tensor(held, stop_gradient=True)
            else:
                mw = Tensor(p._array.astype(jnp.float32), stop_gradient=True)
            mw.name = p.name  # alias so per-param accumulators keep their keys
            store[p.name] = mw
        return mw

    def _update_target(self, p):
        """Returns (target, finalize): the tensor the update kernel should
        write (master when multi_precision applies) and a callback that
        mirrors the new master value into the low-precision param."""
        import jax.numpy as jnp

        if (self._multi_precision and fw.in_dygraph_mode()
                and p._array.dtype in (jnp.float16, jnp.bfloat16)):
            mw = self._master_weight(p)

            def finalize():
                p._array = mw._array.astype(p._array.dtype)

            return mw, finalize
        return p, None

    # -- the shared update executor ---------------------------------------
    def _run_update(self, op_type: str, ins: Dict[str, list], bind: Dict[str, object],
                    attrs: Dict[str, object]):
        """Run/append an update op.  ``bind`` maps output slot -> the var or
        Tensor that must receive the new value (in-place semantics)."""
        if fw.in_dygraph_mode():
            arrays = {s: [t._array if isinstance(t, Tensor) else t for t in vs]
                      for s, vs in ins.items()}
            outs = tracer.run_eager_kernel(op_type, arrays, attrs)
            for slot, target in bind.items():
                if slot in outs and target is not None:
                    target._array = outs[slot][0]
            return
        from ..ops.dispatch import dispatch_static

        dispatch_static(
            op_type, ins, attrs,
            outputs={slot: [v] for slot, v in bind.items() if v is not None},
        )

    # -- main entries ------------------------------------------------------
    def _params_grads_dygraph(self) -> List[Tuple]:
        assert self._parameter_list is not None, (
            "pass `parameters=` to the optimizer for dygraph mode"
        )
        out = []
        for p in self._parameter_list:
            if getattr(p, "trainable", True) and p.grad is not None:
                out.append((p, p.grad))
        return out

    def _apply_regularization(self, params_grads):
        if self.regularization is None:
            return params_grads
        out = []
        for p, g in params_grads:
            reg = getattr(p, "regularizer", None) or self.regularization
            if reg is not None:
                g = reg(p, g)
            out.append((p, g))
        return out

    def _apply_clip(self, params_grads):
        if self._grad_clip is not None:
            return self._grad_clip(params_grads)
        return params_grads

    @property
    def _param_groups(self):
        return self._parameter_list

    def step(self):
        """Dygraph update (parity: Optimizer.step / minimize dygraph branch)."""
        params_grads = self._params_grads_dygraph()
        params_grads = self._apply_regularization(params_grads)
        params_grads = self._apply_clip(params_grads)
        for p, g in params_grads:
            self._apply_optimize_op(p, g)
        if self._accumulators_holder:
            # Surface held state that can no longer be consumed, instead of
            # silently training from zeroed accumulators: keys for unknown
            # params, and keys for params that just stepped (their
            # accumulators were created above, so an unconsumed key means
            # this optimizer class never creates that accumulator — e.g. an
            # Adam checkpoint loaded into Momentum).  Keys for owned params
            # that had no grad this step stay held.
            owned = {p.name for p in (self._parameter_list or [])}
            stepped = {p.name for p, _ in params_grads}
            orphans = []
            for k in list(self._accumulators_holder):
                # longest-prefix match: with params 'emb' and 'emb_2', key
                # 'emb_2_moment1_0' must attribute to 'emb_2', not 'emb'
                owner = max((n for n in owned if k.startswith(n + "_")),
                            key=len, default=None)
                if owner is None or owner in stepped:
                    orphans.append(k)
                    self._accumulators_holder.pop(k)
            if orphans:
                import warnings

                warnings.warn(
                    f"optimizer.set_state_dict: {len(orphans)} loaded key(s) "
                    f"could not be applied to this optimizer and were "
                    f"ignored: {sorted(orphans)[:8]}"
                    + ("..." if len(orphans) > 8 else ""))

    def clear_grad(self):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        if fw.in_dygraph_mode():
            self.step()
            return None, self._params_grads_dygraph()
        from ..static.backward import append_backward

        params_grads = append_backward(loss, parameters, no_grad_set)
        params_grads = self._apply_regularization(params_grads)
        params_grads = self._apply_clip(params_grads)
        for p, g in params_grads:
            self._apply_optimize_op(p, g)
        return None, params_grads

    def apply_gradients(self, params_grads):
        params_grads = self._apply_regularization(params_grads)
        params_grads = self._apply_clip(params_grads)
        for p, g in params_grads:
            self._apply_optimize_op(p, g)

    def _apply_optimize_op(self, p, g):
        target, finalize = self._update_target(p)
        self._append_optimize_op(target, g)
        if finalize is not None:
            finalize()

    def _append_optimize_op(self, param, grad):
        raise NotImplementedError

    # -- state dict --------------------------------------------------------
    def state_dict(self):
        """Keys follow the reference's accumulator-variable naming
        ``{param}_{acc}_0`` (e.g. ``linear_0.w_0_moment1_0``) so .pdopt files
        interchange with reference-produced checkpoints."""
        d = {}
        # state loaded but not yet consumed (no step since set_state_dict)
        # must survive a save — otherwise checkpoint-after-load drops it
        d.update(self._accumulators_holder)
        for acc_name, store in self._accumulators.items():
            for pname, acc in store.items():
                d[f"{pname}_{acc_name}_0"] = acc
        if isinstance(self._learning_rate, LRScheduler):
            d["LR_Scheduler"] = self._learning_rate.state_dict()
        return d

    def _find_accumulator(self, key):
        """Resolve a state key in either the reference format
        ``{param}_{acc}_0`` or the legacy round-1 format ``{param}/{acc}``."""
        if "/" in key:
            pname, acc_name = key.rsplit("/", 1)
            return self._accumulators.get(acc_name, {}).get(pname)
        for acc_name, store in self._accumulators.items():
            suffix = f"_{acc_name}_0"
            if key.endswith(suffix):
                tgt = store.get(key[: -len(suffix)])
                if tgt is not None:
                    return tgt
        return None

    def set_state_dict(self, state):
        unmatched = []
        for key, val in state.items():
            if key == "LR_Scheduler":
                if isinstance(self._learning_rate, LRScheduler):
                    self._learning_rate.set_state_dict(val)
                continue
            tgt = self._find_accumulator(key)
            if tgt is not None and isinstance(tgt, Tensor):
                tgt.set_value(val.numpy() if hasattr(val, "numpy") else val)
            elif fw.in_dygraph_mode() and tgt is None:
                # Accumulators are created lazily on the first step(); stash
                # the value so _add_accumulator initializes from it later
                # (reference Optimizer._accumulators_holder behavior).
                # Normalize the legacy round-1 ``{param}/{acc}`` form to the
                # serialized ``{param}_{acc}_0`` key _add_accumulator pops.
                if "/" in key:
                    pname, acc_name = key.rsplit("/", 1)
                    key = f"{pname}_{acc_name}_0"
                # snapshot now — ``val`` may be a live Tensor whose buffer
                # the source optimizer keeps rebinding on step()
                import numpy as np

                self._accumulators_holder[key] = np.array(
                    val.numpy() if hasattr(val, "numpy") else val)
            else:
                # static mode (accumulators are scope Variables, restored via
                # load_program_state) or an existing non-Tensor target
                unmatched.append(key)
        if unmatched:
            import warnings

            warnings.warn(
                f"optimizer.set_state_dict: {len(unmatched)} key(s) could "
                f"not be applied and were ignored: {unmatched[:8]}"
                + ("..." if len(unmatched) > 8 else ""))

    set_dict = set_state_dict


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def _append_optimize_op(self, p, g):
        self._run_update(
            "sgd",
            {"Param": [p], "Grad": [g], "LearningRate": [self._lr_input()]},
            {"ParamOut": p},
            {},
        )


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _append_optimize_op(self, p, g):
        vel = self._add_accumulator("velocity", p)
        self._run_update(
            "momentum",
            {"Param": [p], "Grad": [g], "Velocity": [vel],
             "LearningRate": [self._lr_input()]},
            {"ParamOut": p, "VelocityOut": vel},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentum(Optimizer):
    """Layer-wise Adaptive Rate Scaling (parity:
    ``fluid/optimizer.py`` LarsMomentumOptimizer / lars_momentum_op) —
    large-batch vision training."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, epsilon=0.0,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay
        self._epsilon = epsilon

    def _append_optimize_op(self, p, g):
        vel = self._add_accumulator("velocity", p)
        self._run_update(
            "lars_momentum",
            {"Param": [p], "Grad": [g], "Velocity": [vel],
             "LearningRate": [self._lr_input()]},
            {"ParamOut": p, "VelocityOut": vel},
            {"mu": self._momentum, "lars_coeff": self._lars_coeff,
             "lars_weight_decay": self._lars_weight_decay,
             "epsilon": self._epsilon},
        )


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._multi_precision = bool(multi_precision)

    _op = "adam"

    def _extra_attrs(self):
        return {}

    def _append_optimize_op(self, p, g):
        m1 = self._add_accumulator("moment1", p)
        m2 = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
        b2p = self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])
        self._run_update(
            self._op,
            {"Param": [p], "Grad": [g], "LearningRate": [self._lr_input()],
             "Moment1": [m1], "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p]},
            {"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
             "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon,
             **self._extra_attrs()},
        )


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        if isinstance(weight_decay, (int, float)) and not isinstance(weight_decay, bool):
            self._coeff = float(weight_decay)
        else:
            self._coeff = 0.01
        self._apply_decay_param_fun = apply_decay_param_fun

    _op = "adamw"

    def _extra_attrs(self):
        return {"coeff": self._coeff, "with_decay": True}

    def _append_optimize_op(self, p, g):
        if self._apply_decay_param_fun is not None and not self._apply_decay_param_fun(p.name):
            # fall back to plain adam for excluded params
            saved, self._op = self._op, "adam"
            try:
                Adam._append_optimize_op(self, p, g)
            finally:
                self._op = saved
            return
        Adam._append_optimize_op(self, p, g)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, p, g):
        m1 = self._add_accumulator("moment1", p)
        m2 = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
        b2p = self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        self._run_update(
            "lamb",
            {"Param": [p], "Grad": [g], "LearningRate": [self._lr_input()],
             "Moment1": [m1], "Moment2": [m2], "Beta1Pow": [b1p], "Beta2Pow": [b2p]},
            {"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
             "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon,
             "weight_decay": wd},
        )


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _append_optimize_op(self, p, g):
        ms = self._add_accumulator("mean_square", p)
        mom = self._add_accumulator("momentum", p)
        ins = {"Param": [p], "Grad": [g], "LearningRate": [self._lr_input()],
               "MeanSquare": [ms], "Moment": [mom]}
        bind = {"ParamOut": p, "MeanSquareOut": ms, "MomentOut": mom}
        if self._centered:
            mg = self._add_accumulator("mean_grad", p)
            ins["MeanGrad"] = [mg]
            bind["MeanGradOut"] = mg
        self._run_update(
            "rmsprop", ins, bind,
            {"decay": self._rho, "epsilon": self._epsilon,
             "momentum": self._momentum, "centered": self._centered},
        )


class Adadelta(Optimizer):
    """Parity: paddle.optimizer.Adadelta (adadelta_op.cc)."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon, self._rho = epsilon, rho

    def _append_optimize_op(self, p, g):
        g2 = self._add_accumulator("_avg_squared_grad", p)
        u2 = self._add_accumulator("_avg_squared_update", p)
        self._run_update(
            "adadelta",
            {"Param": [p], "Grad": [g], "LearningRate": [self._lr_input()],
             "AvgSquaredGrad": [g2], "AvgSquaredUpdate": [u2]},
            {"ParamOut": p, "AvgSquaredGradOut": g2,
             "AvgSquaredUpdateOut": u2},
            {"rho": self._rho, "epsilon": self._epsilon},
        )


class Adamax(Optimizer):
    """Parity: paddle.optimizer.Adamax (adamax_op.cc, infinity norm)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, p, g):
        m = self._add_accumulator("moment", p)
        inf = self._add_accumulator("inf_norm", p)
        b1p = self._add_accumulator("beta1_pow_acc", p,
                                    fill_value=self._beta1, shape=[1])
        self._run_update(
            "adamax",
            {"Param": [p], "Grad": [g], "LearningRate": [self._lr_input()],
             "Moment": [m], "InfNorm": [inf], "Beta1Pow": [b1p]},
            {"ParamOut": p, "MomentOut": m, "InfNormOut": inf,
             "Beta1PowOut": b1p},
            {"beta1": self._beta1, "beta2": self._beta2,
             "epsilon": self._epsilon},
        )


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _append_optimize_op(self, p, g):
        mom = self._add_accumulator("moment", p, fill_value=self._init_acc)
        self._run_update(
            "adagrad",
            {"Param": [p], "Grad": [g], "LearningRate": [self._lr_input()],
             "Moment": [mom]},
            {"ParamOut": p, "MomentOut": mom},
            {"epsilon": self._epsilon},
        )
