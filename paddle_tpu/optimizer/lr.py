"""Learning-rate schedulers.

Parity: ``/root/reference/python/paddle/optimizer/lr.py`` (LRScheduler base +
NoamDecay, PiecewiseDecay, NaturalExpDecay, InverseTimeDecay,
PolynomialDecay, LinearWarmup, ExponentialDecay, MultiStepDecay, StepDecay,
LambdaDecay, ReduceOnPlateau, CosineAnnealingDecay).
"""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence

__all__ = [
    "LRScheduler", "NoamDecay", "PiecewiseDecay", "NaturalExpDecay",
    "InverseTimeDecay", "PolynomialDecay", "LinearWarmup", "ExponentialDecay",
    "MultiStepDecay", "StepDecay", "LambdaDecay", "ReduceOnPlateau",
    "CosineAnnealingDecay",
]


class LRScheduler:
    def __init__(self, learning_rate: float = 0.1, last_epoch: int = -1, verbose: bool = False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.last_lr = self.base_lr
        self.step()

    def __call__(self) -> float:
        return self.last_lr

    def step(self, epoch: Optional[int] = None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        # static-mode optimizers bind themselves here so scheduler steps
        # propagate into the scope's lr variable
        for o in getattr(self, "_bound_optimizers", []):
            o._sync_static_lr()
        if self.verbose:
            print(f"Epoch {self.last_epoch}: lr set to {self.last_lr}")

    def get_lr(self) -> float:
        raise NotImplementedError

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state.get("last_epoch", self.last_epoch)
        self.last_lr = state.get("last_lr", self.last_lr)

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * min(a, b)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries: Sequence[int], values: Sequence[float],
                 last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for i, b in enumerate(self.boundaries):
            if self.last_epoch < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        decay_steps = self.decay_steps
        if self.cycle:
            div = math.ceil(step / decay_steps) if step > 0 else 1
            decay_steps = decay_steps * div
        else:
            step = min(step, decay_steps)
        return (self.base_lr - self.end_lr) * (
            (1 - step / decay_steps) ** self.power
        ) + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr_sched = learning_rate if isinstance(learning_rate, LRScheduler) else None
        self.final_lr = learning_rate if not isinstance(learning_rate, LRScheduler) else None
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * self.last_epoch / self.warmup_steps + self.start_lr
        if self.lr_sched is not None:
            # pin the wrapped scheduler to this scheduler's epoch (reference
            # behavior) — extra get_lr() calls or step(epoch=...) resumes
            # stay in sync instead of free-running
            self.lr_sched.step(self.last_epoch - self.warmup_steps)
            return self.lr_sched()
        return self.final_lr


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (self.gamma ** self.last_epoch)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * (self.gamma ** n)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * (self.gamma ** (self.last_epoch // self.step_size))


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda: Callable[[int], float],
                 last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return (
            self.eta_min
            + (self.base_lr - self.eta_min)
            * (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2
        )


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.cooldown_counter = 0
        self.num_bad_epochs = 0
        self.base_lr = float(learning_rate)
        self.last_lr = self.base_lr
        self.last_epoch = 0
        self.verbose = verbose

    def get_lr(self):
        return self.last_lr

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return
        current = float(metrics.numpy()) if hasattr(metrics, "numpy") else float(metrics)
        self.last_epoch += 1
        if self.best is None or self._is_better(current, self.best):
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            new_lr = max(self.last_lr * self.factor, self.min_lr)
            if self.last_lr - new_lr > 1e-8:
                self.last_lr = new_lr
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0

    def _is_better(self, a, best):
        if self.mode == "min":
            if self.threshold_mode == "rel":
                return a < best * (1 - self.threshold)
            return a < best - self.threshold
        if self.threshold_mode == "rel":
            return a > best * (1 + self.threshold)
        return a > best + self.threshold
