"""Probability distributions — ``paddle.distribution``.

Role parity: ``/root/reference/python/paddle/distribution.py`` —
``Distribution``:42, ``Uniform``:169, ``Normal``:391, ``Categorical``:641,
imported at the reference top level (``python/paddle/__init__.py:47``).

TPU-first: sampling dispatches the registered explicit-PRNG ops
(``uniform_random`` / ``gaussian_random`` / ``multinomial`` in
``ops/math_ops.py``), so draws fold the global generator state, work in
both dygraph and static modes, and re-draw per executed step under jit;
the densities/divergences are plain traceable tensor math, so e.g. a
policy-gradient ``log_prob`` is differentiable end-to-end.

Reference quirks preserved on purpose:
  * ``Categorical`` takes UNNORMALIZED non-negative weights;
    ``probs``/``log_prob`` normalize by the plain sum (reference:
    ``distribution.py`` Categorical.probs ``prob = logits / dist_sum``)
    while ``entropy``/``kl_divergence`` use the softmax form — the two
    families agree only when the weights are already exponentials.
  * ``Uniform.log_prob`` returns ``-inf`` outside the open interval via
    ``log(0)``.
"""

from __future__ import annotations

import math

import numpy as np

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _is_tensor(v):
    from .dygraph.tensor import Tensor
    from .framework.program import Variable

    return isinstance(v, (Tensor, Variable))


def _to_tensor_pair(*args):
    """Mirror of reference ``Distribution._to_tensor``: numbers/lists/
    ndarrays become float tensors (``assign`` works in both dygraph and
    static modes — in static it appends a constant-producing op)."""
    from . import tensor_api as T

    arrays = []
    for a in args:
        if _is_tensor(a):
            arrays.append(a)
        else:
            host = np.asarray(a, dtype="float32")
            if host.ndim == 0:
                host = host.reshape(1)
            arrays.append(T.assign(host))
    return arrays


class Distribution:
    """Abstract base (reference ``distribution.py:42``)."""

    def __init__(self):
        super().__init__()

    def sample(self):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError

    def _validate_args(self, *args):
        """True iff ALL args are tensors; mixing tensors and host values is
        an error (reference ``distribution.py:71``)."""
        is_variable = False
        is_number = False
        for arg in args:
            if _is_tensor(arg):
                is_variable = True
            else:
                is_number = True
        if is_variable and is_number:
            raise ValueError(
                "if one argument is Tensor, all arguments should be Tensor")
        return is_variable

    def _check_values_dtype_in_probs(self, param, value):
        """Cast ``value`` to the parameter dtype (reference
        ``distribution.py:137``)."""
        from . import tensor_api as T

        if not _is_tensor(value):
            value = T.assign(np.asarray(value))
        pd = str(getattr(param, "dtype", "float32"))
        vd = str(value.dtype)
        if pd != vd:
            return T.cast(value, pd)
        return value


class Uniform(Distribution):
    """U(low, high) with broadcastable batch parameters
    (reference ``distribution.py:169``)."""

    def __init__(self, low, high, name=None):
        super().__init__()
        self.name = name if name is not None else "Uniform"
        self.all_arg_is_float = False
        if isinstance(low, int):
            low = float(low)
        if isinstance(high, int):
            high = float(high)
        if not self._validate_args(low, high):
            if isinstance(low, float) and isinstance(high, float):
                self.all_arg_is_float = True
            low, high = _to_tensor_pair(low, high)
        self.low, self.high = low, high
        self.dtype = str(self.low.dtype)

    def sample(self, shape, seed=0):
        from . import tensor_api as T

        batch_shape = list((self.low + self.high).shape)
        output_shape = list(shape) + batch_shape
        u = T.uniform(output_shape, dtype=self.dtype, min=0.0, max=1.0,
                      seed=seed)
        out = self.low + u * (self.high - self.low)
        if self.all_arg_is_float:
            return T.reshape(out, list(shape))
        return out

    def log_prob(self, value):
        from . import tensor_api as T

        value = self._check_values_dtype_in_probs(self.low, value)
        lb = T.cast(self.low < value, str(value.dtype))
        ub = T.cast(value < self.high, str(value.dtype))
        return T.log(lb * ub) - T.log(self.high - self.low)

    def probs(self, value):
        from . import tensor_api as T

        value = self._check_values_dtype_in_probs(self.low, value)
        lb = T.cast(self.low < value, str(value.dtype))
        ub = T.cast(value < self.high, str(value.dtype))
        return (lb * ub) / (self.high - self.low)

    def entropy(self):
        from . import tensor_api as T

        return T.log(self.high - self.low)


class Normal(Distribution):
    """N(loc, scale^2) (reference ``distribution.py:391``)."""

    def __init__(self, loc, scale, name=None):
        super().__init__()
        self.name = name if name is not None else "Normal"
        self.all_arg_is_float = False
        if isinstance(loc, int):
            loc = float(loc)
        if isinstance(scale, int):
            scale = float(scale)
        if not self._validate_args(loc, scale):
            if isinstance(loc, float) and isinstance(scale, float):
                self.all_arg_is_float = True
            loc, scale = _to_tensor_pair(loc, scale)
        self.loc, self.scale = loc, scale
        self.dtype = str(self.loc.dtype)

    def sample(self, shape, seed=0):
        from . import tensor_api as T

        batch_shape = list((self.loc + self.scale).shape)
        output_shape = list(shape) + batch_shape
        eps = T.randn(output_shape, dtype=self.dtype)
        out = self.loc + eps * self.scale
        if self.all_arg_is_float:
            return T.reshape(out, list(shape))
        return out

    def entropy(self):
        from . import tensor_api as T

        # 0.5 + 0.5 log(2 pi) + log(scale), broadcast to the batch shape
        zero = (self.loc + self.scale) * 0.0
        return 0.5 + zero + (0.5 * math.log(2.0 * math.pi)
                             + T.log(self.scale + zero * 0.0))

    def log_prob(self, value):
        from . import tensor_api as T

        value = self._check_values_dtype_in_probs(self.loc, value)
        var = self.scale * self.scale
        log_scale = T.log(self.scale)
        return (-1.0 * ((value - self.loc) * (value - self.loc)) / (2.0 * var)
                - log_scale - math.log(math.sqrt(2.0 * math.pi)))

    def probs(self, value):
        from . import tensor_api as T

        value = self._check_values_dtype_in_probs(self.loc, value)
        var = self.scale * self.scale
        return (T.exp(-1.0 * ((value - self.loc) * (value - self.loc))
                      / (2.0 * var))
                / (math.sqrt(2.0 * math.pi) * self.scale))

    def kl_divergence(self, other):
        from . import tensor_api as T

        if not isinstance(other, Normal):
            raise TypeError(
                f"kl_divergence expects Normal, got {type(other).__name__}")
        var_ratio = self.scale / other.scale
        var_ratio = var_ratio * var_ratio
        t1 = (self.loc - other.loc) / other.scale
        t1 = t1 * t1
        return 0.5 * var_ratio + 0.5 * (t1 - 1.0 - T.log(var_ratio))


class Categorical(Distribution):
    """Categorical over unnormalized non-negative weights
    (reference ``distribution.py:641``)."""

    def __init__(self, logits, name=None):
        super().__init__()
        self.name = name if name is not None else "Categorical"
        if not self._validate_args(logits):
            (logits,) = _to_tensor_pair(logits)
        self.logits = logits
        self.dtype = str(self.logits.dtype)

    def sample(self, shape):
        """Index draws with replacement; prepends ``shape`` and keeps the
        leading distribution dims of a >=2-D ``logits``."""
        from . import tensor_api as T

        num_samples = int(np.prod(shape)) if len(shape) else 1
        logits_shape = list(self.logits.shape)
        if len(logits_shape) > 1:
            sample_shape = list(shape) + logits_shape[:-1]
            logits = T.reshape(
                self.logits,
                [int(np.prod(logits_shape[:-1])), logits_shape[-1]])
        else:
            sample_shape = list(shape)
            logits = self.logits
        idx = T.multinomial(logits, num_samples, replacement=True)
        if len(logits_shape) > 1:
            # (num_dist, n) -> shape + dist_dims: samples vary fastest
            idx = T.transpose(idx, [1, 0])
        return T.reshape(idx, sample_shape)

    def _softmax_stats(self, logits):
        from . import tensor_api as T

        shifted = logits - T.max(logits, axis=-1, keepdim=True)
        e = T.exp(shifted)
        z = T.sum(e, axis=-1, keepdim=True)
        return shifted, e, z

    def kl_divergence(self, other):
        from . import tensor_api as T

        if not isinstance(other, Categorical):
            raise TypeError(
                f"kl_divergence expects Categorical, got "
                f"{type(other).__name__}")
        logits, e, z = self._softmax_stats(self.logits)
        o_logits, o_e, o_z = other._softmax_stats(other.logits)
        prob = e / z
        return T.sum(prob * (logits - T.log(z) - o_logits + T.log(o_z)),
                     axis=-1, keepdim=True)

    def entropy(self):
        from . import tensor_api as T

        logits, e, z = self._softmax_stats(self.logits)
        prob = e / z
        ent = -1.0 * T.sum(prob * (logits - T.log(z)), axis=-1, keepdim=True)
        return ent

    def probs(self, value):
        """Probability of category index ``value`` under weights/sum
        normalization (the reference's non-softmax convention)."""
        from . import tensor_api as T

        dist_sum = T.sum(self.logits, axis=-1, keepdim=True)
        prob = self.logits / dist_sum
        shape = list(self.logits.shape)
        value_shape = list(value.shape)
        if len(shape) == 1:
            num_value_in_one_dist = int(np.prod(value_shape))
            index_value = T.reshape(value, [num_value_in_one_dist, 1])
            index = index_value
        else:
            num_dist = int(np.prod(shape[:-1]))
            num_value_in_one_dist = value_shape[-1]
            prob = T.reshape(prob, [num_dist, shape[-1]])
            if len(value_shape) == 1:
                value = T.broadcast_to(
                    T.reshape(value, [1, -1]), [num_dist, value_shape[-1]])
                value_shape = [num_dist, value_shape[-1]]
            elif value_shape[:-1] != shape[:-1]:
                raise ValueError(
                    f"shape of value {value_shape[:-1]} must match shape "
                    f"of logits {shape[:-1]}")
            index_value = T.reshape(value, [num_dist, -1, 1])
            prefix = T.reshape(
                T.arange(0, num_dist, dtype=str(value.dtype)),
                [num_dist, 1, 1])
            prefix = T.broadcast_to(prefix,
                                    [num_dist, num_value_in_one_dist, 1])
            index = T.concat([prefix, index_value], axis=-1)
        out = T.gather_nd(prob, T.cast(index, "int64"))
        return T.reshape(out, value_shape)

    def log_prob(self, value):
        from . import tensor_api as T

        return T.log(self.probs(value))
