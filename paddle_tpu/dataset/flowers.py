"""Legacy ``paddle.dataset.flowers`` readers (reference
dataset/flowers.py): yields (image array as the backend produces it —
HWC for the default PIL backend — scaled to [0, 1], 0-based int label).

Split note (reference parity): the legacy API deliberately EXCHANGES the
official Flowers-102 splits — ``train()`` reads the official *test* ids
(~6149 images, ``tstid``) and ``test()`` the official *train* ids
(~1020, ``trnid``) — because the official train split is too small to
train on (dataset/flowers.py TRAIN_FLAG/TEST_FLAG comment).  The class
API (``paddle_tpu.vision.datasets.Flowers``) keeps the official mapping;
this shim applies the legacy exchange.
"""

import numpy as np

_LEGACY_MODE = {"train": "test", "test": "train", "valid": "valid"}


def _reader(mode, **kw):
    def reader():
        from ..vision.datasets import Flowers

        for img, label in Flowers(mode=_LEGACY_MODE[mode], **kw):
            img = np.asarray(img, "float32")
            if img.max() > 1.5:  # PIL-backed HWC uint8 path
                img = img / 255.0
            # imagelabels.mat labels are 1-based; legacy reader yields
            # int(label) - 1
            yield img, int(np.asarray(label).reshape(-1)[0]) - 1

    return reader


def train(**kw):
    return _reader("train", **kw)


def test(**kw):
    return _reader("test", **kw)


def valid(**kw):
    return _reader("valid", **kw)
