"""Legacy ``paddle.dataset.conll05`` readers (reference
dataset/conll05.py): SRL tuples from the CoNLL-2005 test split."""


def _reader(**kw):
    def reader():
        from ..text.datasets import Conll05st

        for sample in Conll05st(**kw):
            yield tuple(sample)

    return reader


def test(**kw):
    return _reader(**kw)
