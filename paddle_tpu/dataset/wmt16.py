"""Legacy ``paddle.dataset.wmt16`` readers (reference dataset/wmt16.py)."""


def _reader(mode, src_dict_size, trg_dict_size, lang, **kw):
    def reader():
        from ..text.datasets import WMT16

        for sample in WMT16(mode=mode, src_dict_size=src_dict_size,
                            trg_dict_size=trg_dict_size, lang=lang, **kw):
            yield tuple(sample)

    return reader


def train(src_dict_size=-1, trg_dict_size=-1, src_lang="en", **kw):
    return _reader("train", src_dict_size, trg_dict_size, src_lang, **kw)


def test(src_dict_size=-1, trg_dict_size=-1, src_lang="en", **kw):
    return _reader("test", src_dict_size, trg_dict_size, src_lang, **kw)


def validation(src_dict_size=-1, trg_dict_size=-1, src_lang="en", **kw):
    return _reader("val", src_dict_size, trg_dict_size, src_lang, **kw)
