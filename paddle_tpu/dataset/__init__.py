"""Legacy ``paddle.dataset`` reader-creator surface.

Parity: ``/root/reference/python/paddle/dataset/`` (mnist.py, cifar.py,
uci_housing.py, imdb.py, imikolov.py, movielens.py, flowers.py, voc2012.py,
wmt14.py, wmt16.py, conll05.py) — the pre-2.x API where each dataset module
exposes ``train()``/``test()`` functions returning a *reader creator* (a
zero-arg callable yielding sample tuples), consumed by
``paddle.batch``-style loops.

Thin compatibility layer: every reader delegates to the class-based
datasets in ``paddle_tpu.vision.datasets`` / ``paddle_tpu.text.datasets``
(which document the no-network-egress data placement convention); dataset
construction happens lazily inside the reader so importing this package
never requires the data files.
"""

from . import (  # noqa: F401
    cifar, conll05, flowers, imdb, imikolov, mnist, movielens, uci_housing,
    voc2012, wmt14, wmt16,
)

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "imikolov", "movielens",
           "flowers", "voc2012", "wmt14", "wmt16", "conll05"]
