"""Legacy ``paddle.dataset.movielens`` readers (reference
dataset/movielens.py): (user feats..., movie feats..., rating) tuples."""


def _reader(mode, **kw):
    def reader():
        from ..text.datasets import Movielens

        for sample in Movielens(mode=mode, **kw):
            yield tuple(sample)

    return reader


def train(**kw):
    return _reader("train", **kw)


def test(**kw):
    return _reader("test", **kw)
