"""Legacy ``paddle.dataset.wmt14`` readers (reference dataset/wmt14.py):
(src ids, trg ids, trg-next ids) tuples."""


def _reader(mode, dict_size, **kw):
    def reader():
        from ..text.datasets import WMT14

        for sample in WMT14(mode=mode, dict_size=dict_size, **kw):
            yield tuple(sample)

    return reader


def train(dict_size=-1, **kw):
    return _reader("train", dict_size, **kw)


def test(dict_size=-1, **kw):
    return _reader("test", dict_size, **kw)
