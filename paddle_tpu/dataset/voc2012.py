"""Legacy ``paddle.dataset.voc2012`` readers (reference
dataset/voc2012.py): yields (image array, segmentation label array)."""

import numpy as np


def _reader(mode, **kw):
    def reader():
        from ..vision.datasets import VOC2012

        for img, label in VOC2012(mode=mode, **kw):
            yield np.asarray(img), np.asarray(label)

    return reader


def train(**kw):
    return _reader("train", **kw)


def test(**kw):
    return _reader("test", **kw)


def val(**kw):
    return _reader("valid", **kw)
