"""Legacy ``paddle.dataset.imikolov`` readers (reference
dataset/imikolov.py): n-gram tuples from PTB text."""


def build_dict(min_word_freq=50):
    from ..text.datasets import Imikolov

    return Imikolov(mode="train", min_word_freq=min_word_freq).word_idx


def _reader(mode, n, word_idx, **kw):
    def reader():
        from ..text.datasets import Imikolov

        ds = Imikolov(mode=mode, data_type="NGRAM", window_size=n,
                      word_idx=word_idx, **kw)
        for sample in ds:
            yield tuple(int(v) for v in sample)

    return reader


def train(word_idx=None, n=5, **kw):
    return _reader("train", n, word_idx, **kw)


def test(word_idx=None, n=5, **kw):
    return _reader("test", n, word_idx, **kw)
