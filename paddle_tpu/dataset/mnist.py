"""Legacy ``paddle.dataset.mnist`` readers (reference dataset/mnist.py):
yields (flattened float32 image scaled to [-1, 1], int label)."""

import numpy as np


def _reader(mode, **kw):
    def reader():
        from ..vision.datasets import MNIST

        ds = MNIST(mode=mode, **kw)
        for img, label in ds:
            # MNIST.__getitem__ yields CHW float32 in [0, 1]; the legacy
            # reader contract is flat float32 in [-1, 1] (raw/127.5 - 1)
            flat = np.asarray(img, "float32").reshape(-1) * 2.0 - 1.0
            yield flat, int(label)

    return reader


def train(**kw):
    return _reader("train", **kw)


def test(**kw):
    return _reader("test", **kw)
